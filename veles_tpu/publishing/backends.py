"""Publishing backends: Markdown and HTML report writers
(reference backend.py / confluence_backend.py / jinja templates)."""

import json
import os

__all__ = ["MarkdownBackend", "HTMLBackend", "PDFBackend",
           "ConfluenceBackend"]


class BackendBase(object):
    def __init__(self, output_dir):
        self.output_dir = output_dir

    def render(self, info):
        raise NotImplementedError


class MarkdownBackend(BackendBase):
    def render(self, info):
        os.makedirs(self.output_dir, exist_ok=True)
        lines = [
            "# Training report: %s" % info["name"],
            "",
            "- date: %s" % info["date"],
            "- checksum: `%s`" % info["checksum"],
            "- epochs: %s" % info["epochs"],
            "",
            "## Metrics",
            "",
            "| split | value |",
            "|---|---|",
        ]
        for split in ("test", "validation", "train", "best"):
            lines.append("| %s | %s |" % (split,
                                          info["metrics"].get(split)))
        lines += [
            "",
            "## Dataset",
            "",
            "| split | samples |",
            "|---|---|",
        ]
        for split in ("test", "validation", "train"):
            lines.append("| %s | %s |" % (split,
                                          info["dataset"].get(split)))
        lines += ["", "## Unit run times", "",
                  "| unit | runs | seconds |", "|---|---|---|"]
        for u in info["units"]:
            lines.append("| %s | %d | %.4f |" % (u["name"], u["runs"],
                                                 u["time"]))
        if info.get("results"):
            lines += ["", "## Results", "", "```json",
                      json.dumps(info["results"], indent=1,
                                 default=repr),
                      "```"]
        path = os.path.join(self.output_dir, "report.md")
        with open(path, "w") as fout:
            fout.write("\n".join(lines) + "\n")
        return path


class HTMLBackend(BackendBase):
    def render(self, info):
        os.makedirs(self.output_dir, exist_ok=True)
        rows = "".join(
            "<tr><td>%s</td><td>%s</td></tr>" % (k, info["metrics"][k])
            for k in ("test", "validation", "train", "best"))
        units = "".join(
            "<tr><td>%s</td><td>%d</td><td>%.4f</td></tr>" %
            (u["name"], u["runs"], u["time"]) for u in info["units"])
        html = (
            "<html><head><title>%s</title></head><body>"
            "<h1>%s</h1><p>%s — epochs: %s</p>"
            "<h2>Metrics</h2><table border=1>%s</table>"
            "<h2>Units</h2><table border=1>"
            "<tr><th>unit</th><th>runs</th><th>s</th></tr>%s</table>"
            "</body></html>" % (
                info["name"], info["name"], info["date"],
                info["epochs"], rows, units))
        path = os.path.join(self.output_dir, "report.html")
        with open(path, "w") as fout:
            fout.write(html)
        return path


class PDFBackend(BackendBase):
    """PDF report via matplotlib's PdfPages (the reference rendered
    PDF through its jinja/confluence stack; matplotlib is already this
    framework's plotting engine)."""

    def render(self, info):
        import matplotlib
        matplotlib.use("Agg", force=False)
        from matplotlib.backends.backend_pdf import PdfPages
        import matplotlib.pyplot as plt

        os.makedirs(self.output_dir, exist_ok=True)
        path = os.path.join(self.output_dir, "report.pdf")
        with PdfPages(path) as pdf:
            fig = plt.figure(figsize=(8.27, 11.69))  # A4
            fig.text(0.5, 0.95, "Training report: %s" % info["name"],
                     ha="center", size=16, weight="bold")
            fig.text(0.1, 0.90, "date: %s" % info["date"], size=10)
            fig.text(0.1, 0.88, "checksum: %s" % info["checksum"],
                     size=8, family="monospace")
            fig.text(0.1, 0.86, "epochs: %s" % info["epochs"], size=10)

            ax = fig.add_axes([0.1, 0.62, 0.8, 0.20])
            ax.axis("off")
            rows = [[split, str(info["metrics"].get(split))]
                    for split in ("test", "validation", "train", "best")]
            table = ax.table(cellText=rows,
                             colLabels=["split", "metric"],
                             loc="center")
            table.scale(1, 1.4)
            ax.set_title("Metrics")

            ax2 = fig.add_axes([0.1, 0.40, 0.8, 0.16])
            ax2.axis("off")
            rows2 = [[split, str(info["dataset"].get(split))]
                     for split in ("test", "validation", "train")]
            ax2.table(cellText=rows2,
                      colLabels=["split", "samples"], loc="center")
            ax2.set_title("Dataset")

            units = info["units"][:20]
            if units:
                ax3 = fig.add_axes([0.1, 0.05, 0.8, 0.30])
                ax3.axis("off")
                rows3 = [[u["name"], str(u["runs"]),
                          "%.4f" % u["time"]] for u in units]
                ax3.table(cellText=rows3,
                          colLabels=["unit", "runs", "seconds"],
                          loc="center")
                ax3.set_title("Unit run times")
            pdf.savefig(fig)
            plt.close(fig)

            plots_dir = info.get("plots_dir")
            if plots_dir and os.path.isdir(plots_dir):
                for fname in sorted(os.listdir(plots_dir)):
                    if not fname.endswith(".png"):
                        continue
                    img = plt.imread(os.path.join(plots_dir, fname))
                    fig = plt.figure(figsize=(8.27, 11.69))
                    ax = fig.add_axes([0.05, 0.05, 0.9, 0.9])
                    ax.imshow(img)
                    ax.axis("off")
                    ax.set_title(fname)
                    pdf.savefig(fig)
                    plt.close(fig)
        return path


class ConfluenceBackend(BackendBase):
    """Publishes the report to Atlassian Confluence over the REST API
    (reference confluence_backend.py:42 rendered jinja XML and pushed
    through an XML-RPC client; the modern surface is REST + storage
    format, same roles: create-or-version the page, de-duplicate the
    title, attach plots and the workflow graph).

    ``server`` is the base URL (e.g. http://confluence:8090); auth is a
    bearer ``token`` or ``username``/``password`` basic pair.  Network
    egress is absent from CI images, so tests run against a local mock
    server speaking the same three endpoints.
    """

    def __init__(self, server, space, page=None, parent_id=None,
                 token=None, username=None, password=None,
                 output_dir=None):
        super(ConfluenceBackend, self).__init__(output_dir)
        self.server = server.rstrip("/")
        self.space = space
        self.page = page
        self.parent_id = parent_id
        self.token = token
        self.username = username
        self.password = password
        self.url = None

    # -- HTTP plumbing ------------------------------------------------------

    def _headers(self):
        import base64
        headers = {"Content-Type": "application/json"}
        if self.token:
            headers["Authorization"] = "Bearer %s" % self.token
        elif self.username is not None:
            headers["Authorization"] = "Basic %s" % base64.b64encode(
                ("%s:%s" % (self.username, self.password or ""))
                .encode()).decode()
        return headers

    def _request(self, method, path, payload=None, headers=None,
                 body=None):
        import urllib.request
        data = body
        if payload is not None:
            data = json.dumps(payload).encode()
        req = urllib.request.Request(
            self.server + path, data=data, method=method,
            headers={**self._headers(), **(headers or {})})
        with urllib.request.urlopen(req, timeout=15) as resp:
            return json.loads(resp.read() or b"{}")

    # -- storage-format rendering -------------------------------------------

    @staticmethod
    def _table(headers, rows):
        import html
        head = "".join("<th>%s</th>" % html.escape(str(h))
                       for h in headers)
        body = "".join(
            "<tr>%s</tr>" % "".join(
                "<td>%s</td>" % html.escape(str(c)) for c in row)
            for row in rows)
        return "<table><tbody><tr>%s</tr>%s</tbody></table>" % (
            head, body)

    def render_storage(self, info):
        import html
        parts = [
            "<p>date: %s<br/>checksum: <code>%s</code><br/>"
            "epochs: %s</p>" % (html.escape(str(info["date"])),
                                html.escape(str(info["checksum"])),
                                html.escape(str(info["epochs"]))),
            "<h2>Metrics</h2>",
            self._table(("split", "value"),
                        [(s, info["metrics"].get(s))
                         for s in ("test", "validation", "train",
                                   "best")]),
            "<h2>Dataset</h2>",
            self._table(("split", "samples"),
                        [(s, info["dataset"].get(s))
                         for s in ("test", "validation", "train")]),
            "<h2>Unit run times</h2>",
            self._table(("unit", "runs", "seconds"),
                        [(u["name"], u["runs"], "%.4f" % u["time"])
                         for u in info["units"]]),
        ]
        if info.get("results"):
            parts += [
                "<h2>Results</h2>",
                "<ac:structured-macro ac:name=\"code\"><ac:plain-text-"
                "body><![CDATA[%s]]></ac:plain-text-body>"
                "</ac:structured-macro>" % json.dumps(
                    info["results"], indent=1, default=repr),
            ]
        return "".join(parts)

    # -- Confluence REST calls ----------------------------------------------

    def _find_page(self, title):
        import urllib.parse
        found = self._request(
            "GET", "/rest/api/content?spaceKey=%s&title=%s"
            "&expand=version" % (
                urllib.parse.quote(self.space),
                urllib.parse.quote(title)))
        results = found.get("results", [])
        return results[0] if results else None

    def _attach(self, page_id, filename, data):
        import urllib.parse
        boundary = "veles-tpu-attachment"
        body = (
            "--%s\r\nContent-Disposition: form-data; name=\"file\"; "
            "filename=\"%s\"\r\nContent-Type: application/octet-stream"
            "\r\n\r\n" % (boundary, filename)).encode() + data + \
            ("\r\n--%s--\r\n" % boundary).encode()
        headers = {"Content-Type":
                   "multipart/form-data; boundary=%s" % boundary,
                   "X-Atlassian-Token": "nocheck"}
        # re-publishing must version an existing attachment, not POST a
        # duplicate filename (Confluence rejects those with 400)
        existing = self._request(
            "GET", "/rest/api/content/%s/child/attachment?filename=%s"
            % (page_id, urllib.parse.quote(filename))).get("results", [])
        if existing:
            self._request(
                "POST", "/rest/api/content/%s/child/attachment/%s/data"
                % (page_id, existing[0]["id"]),
                headers=headers, body=body)
        else:
            self._request(
                "POST", "/rest/api/content/%s/child/attachment" % page_id,
                headers=headers, body=body)

    def render(self, info):
        # de-duplicate the title exactly like the reference: first free
        # "name", "name (1)", ... unless an explicit page was given
        # (then it is updated in place with a version bump)
        title = self.page
        existing = None
        if title is None:
            title = info["name"]
            index = 1
            while self._find_page(title) is not None:
                title = "%s (%d)" % (info["name"], index)
                index += 1
        else:
            existing = self._find_page(title)
        content = self.render_storage(info)
        payload = {
            "type": "page", "title": title,
            "space": {"key": self.space},
            "body": {"storage": {"value": content,
                                 "representation": "storage"}},
        }
        if self.parent_id:
            payload["ancestors"] = [{"id": self.parent_id}]
        if existing is None:
            created = self._request(
                "POST", "/rest/api/content", payload)
        else:
            payload["version"] = {
                "number": existing.get(
                    "version", {}).get("number", 1) + 1}
            created = self._request(
                "PUT", "/rest/api/content/%s" % existing["id"], payload)
        page_id = created["id"]
        self.url = "%s/pages/%s" % (self.server, page_id)
        if info.get("graph_dot"):
            self._attach(page_id, "workflow.dot",
                         info["graph_dot"].encode())
        plots_dir = info.get("plots_dir")
        if plots_dir and os.path.isdir(plots_dir):
            for fname in sorted(os.listdir(plots_dir)):
                if fname.endswith(".png"):
                    with open(os.path.join(plots_dir, fname),
                              "rb") as fin:
                        self._attach(page_id, fname, fin.read())
        self.page = title
        return self.url
