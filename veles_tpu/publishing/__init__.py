"""Report publishing after training (reference veles/publishing/:
publisher gathers workflow info + plots; backends render it)."""

from veles_tpu.publishing.publisher import Publisher  # noqa: F401
from veles_tpu.publishing.backends import (  # noqa: F401
    ConfluenceBackend, MarkdownBackend, HTMLBackend, PDFBackend)
