"""Device backends.

TPU-native counterpart of reference veles/backends.py:166,184,190-197.
The registry/priority/auto-selection design is preserved; the devices are:

- :class:`TPUDevice` — JAX on TPU.  The unit of execution is a jitted XLA
  computation, not a hand-launched kernel; ``device`` here mostly carries
  placement (which ``jax.Device`` / mesh), dtype policy, and the autotune
  table for Pallas kernels.
- :class:`CPUDevice` — JAX on host CPU.  Same code path as TPU (XLA:CPU +
  Pallas interpreter), used for tests and as the portable fallback.
- :class:`NumpyDevice` — pure-numpy pseudo-device, always available;
  units run their ``numpy_*`` methods (reference: backends.py:918).

Selection: ``Device(backend="tpu"|"cpu"|"numpy"|"auto")`` or the
``VELES_BACKEND`` env var / ``root.common.engine.backend`` config.  ``auto``
picks the highest-priority available backend (tpu 30 > cpu 20 > numpy 10),
mirroring the reference's cuda 30 > ocl 20 > numpy 10 ladder.
"""

import json
import os
import threading

import numpy

from veles_tpu.config import root
from veles_tpu.distributable import Pickleable

__all__ = ["Device", "TPUDevice", "CPUDevice", "NumpyDevice",
           "BackendRegistry"]


class BackendRegistry(type):
    backends = {}
    _demotion_warned = False

    def __init__(cls, name, bases, namespace):
        super(BackendRegistry, cls).__init__(name, bases, namespace)
        backend = namespace.get("BACKEND")
        if backend is not None:
            BackendRegistry.backends[backend] = cls


class Device(Pickleable, metaclass=BackendRegistry):
    """Base device; ``Device(backend=...)`` dispatches to a subclass."""

    BACKEND = None
    PRIORITY = 0

    def __new__(cls, *args, **kwargs):
        if cls is not Device:
            return super(Device, cls).__new__(cls)
        backend = kwargs.get("backend")
        if backend is None:
            backend = os.environ.get("VELES_BACKEND") or \
                root.common.engine.get("backend", "auto")
        if backend == "auto":
            chosen = None
            skipped = []
            for sub in sorted(BackendRegistry.backends.values(),
                              key=lambda c: -c.PRIORITY):
                if sub.available():
                    chosen = sub
                    break
                skipped.append(sub.__name__)
            if chosen is None:
                raise RuntimeError("no available backend")
            if skipped and not BackendRegistry._demotion_warned:
                # a transiently-failing accelerator (e.g. a tunneled
                # chip mid-restart) must not demote the run silently;
                # once per process — a CPU-only host would otherwise
                # repeat this for every Device() and drown the signal
                BackendRegistry._demotion_warned = True
                import logging
                logging.getLogger("Device").warning(
                    "auto backend selected %s; higher-priority "
                    "backend(s) unavailable: %s", chosen.__name__,
                    ", ".join(skipped))
            return super(Device, chosen).__new__(chosen)
        try:
            sub = BackendRegistry.backends[backend]
        except KeyError:
            raise ValueError("unknown backend %r (known: %s)" % (
                backend, sorted(BackendRegistry.backends)))
        return super(Device, sub).__new__(sub)

    def __init__(self, **kwargs):
        kwargs.pop("backend", None)
        super(Device, self).__init__(**kwargs)
        self._computing_power = None

    @classmethod
    def available(cls):
        return False

    @property
    def backend_name(self):
        return self.BACKEND

    @property
    def exists(self):
        """True when real accelerated hardware backs this device."""
        return False

    @property
    def is_async(self):
        """True when execution is asynchronous (needs explicit sync for
        honest timings — the reference's --sync-run concern)."""
        return False

    def sync(self):
        pass

    def thread_pool_attach(self, pool):
        """Per-thread attach hook (reference pushes CUDA contexts here;
        JAX needs nothing, kept for unit-compat)."""

    def thread_pool_detach(self):
        pass

    @property
    def max_group_size(self):
        return 1

    @property
    def computing_power(self):
        """Benchmark-derived rating used for job load balancing
        (reference: accelerated_units.py:768-778)."""
        if self._computing_power is None:
            self._computing_power = self._measure_power()
        return self._computing_power

    def _measure_power(self):
        import time
        size = 1024
        a = numpy.random.RandomState(13).rand(size, size).astype(numpy.float32)
        fn = self.matmul_fn()
        fn(a, a)  # warm-up / compile
        # perf_counter: this rating feeds the master's load balancing;
        # a wall-clock NTP step here would misweight the slave for the
        # whole session
        start = time.perf_counter()
        for _ in range(3):
            result = fn(a, a)
        self.sync_result(result)
        elapsed = (time.perf_counter() - start) / 3
        return 1000.0 / max(elapsed, 1e-9)

    def matmul_fn(self):
        return lambda a, b: numpy.dot(a, b)

    def sync_result(self, result):
        pass

    def __repr__(self):
        return "<%s backend=%s>" % (type(self).__name__, self.BACKEND)


_HOST_CPU_DEVICE = None


def host_compute_context(device=None):
    """Context manager pinning jax ops to the in-process host CPU.

    The numpy backend's unit fallbacks evaluate the same jax math the
    device path jits — but an unpinned eager op (or jit dispatch) runs
    on jax's DEFAULT backend, which on a tunneled-TPU host is a remote
    chip costing ~0.15 s of round trip PER OP: a 4 s host-side MLP
    epoch measured ~45 s when left unpinned.  Every numpy-path call
    site wraps itself in this context so "numpy backend" really means
    "this host".

    Pins when ``device`` is None or the numpy backend.  No-op for
    real accelerator devices: the nn-unit call sites then take their
    device-array paths instead, while host-array units (Kohonen, RBM)
    deliberately dispatch to the accelerator and pay a transfer per
    call — that is their accelerated mode, not an oversight.
    """
    import contextlib
    global _HOST_CPU_DEVICE
    if device is not None and not isinstance(device, NumpyDevice):
        return contextlib.nullcontext()
    if _HOST_CPU_DEVICE is None:
        try:
            import jax
            _HOST_CPU_DEVICE = jax.local_devices(backend="cpu")[0]
        except Exception:
            return contextlib.nullcontext()
    import jax
    return jax.default_device(_HOST_CPU_DEVICE)


_COMPILE_CACHE_SET = False


def _enable_persistent_compile_cache():
    """Point JAX's persistent compilation cache at the veles cache dir
    (unless the user configured one).  On a remote-compile TPU tunnel
    a cold conv-net program costs 20-40 s to compile; the persistent
    cache makes every later process reuse it (analog of the
    reference's kernel binary cache, accelerated_units.py:605-636)."""
    global _COMPILE_CACHE_SET
    if _COMPILE_CACHE_SET:
        return
    _COMPILE_CACHE_SET = True
    try:
        import jax
        if jax.config.jax_compilation_cache_dir:
            return  # user already chose one
        path = os.path.join(root.common.dirs.get("cache", "/tmp"),
                            "jax_cache")
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass  # cache is an optimisation, never a requirement


class _JaxDevice(Device):
    """Shared implementation for JAX-backed devices."""

    PLATFORM = None

    def __init__(self, **kwargs):
        self.device_index = kwargs.pop("device_index", 0)
        super(_JaxDevice, self).__init__(**kwargs)
        _enable_persistent_compile_cache()
        self.init_unpickled()

    def init_unpickled(self):
        super(_JaxDevice, self).init_unpickled()
        self._jax_device_ = None

    @classmethod
    def available(cls):
        try:
            import jax
            return len(jax.devices(cls.PLATFORM)) > 0
        except Exception:
            return False

    @property
    def jax_device(self):
        if self._jax_device_ is None:
            import jax
            self._jax_device_ = jax.devices(self.PLATFORM)[self.device_index]
        return self._jax_device_

    @property
    def exists(self):
        return True

    @property
    def is_async(self):
        return True

    def sync(self):
        import jax
        try:
            jax.effects_barrier()
        except Exception:
            pass

    def sync_result(self, result):
        if hasattr(result, "block_until_ready"):
            result.block_until_ready()

    def matmul_fn(self):
        import jax
        import jax.numpy as jnp

        @jax.jit
        def mm(a, b):
            return jnp.dot(a, b)

        def run(a, b):
            return mm(jax.device_put(a, self.jax_device),
                      jax.device_put(b, self.jax_device))
        return run

    def put(self, array):
        import jax
        return jax.device_put(array, self.jax_device)

    def __getstate__(self):
        state = super(_JaxDevice, self).__getstate__()
        state["_computing_power"] = None
        return state


class TPUDevice(_JaxDevice):
    """JAX on TPU.  Fulfils the north-star role of BASELINE.json: the
    backend that compiles accelerated units to XLA computations."""

    BACKEND = "tpu"
    PRIORITY = 30
    PLATFORM = None  # default platform = accelerator when present

    @classmethod
    def available(cls):
        try:
            import jax
            return jax.default_backend() not in ("cpu",)
        except Exception:
            return False

    @property
    def jax_device(self):
        if self._jax_device_ is None:
            import jax
            self._jax_device_ = jax.devices()[self.device_index]
        return self._jax_device_


class CPUDevice(_JaxDevice):
    """JAX on host CPU — test/interpreter backend, same code path."""

    BACKEND = "cpu"
    PRIORITY = 20
    PLATFORM = "cpu"

    def put(self, array):
        """XLA:CPU ``device_put`` adopts aligned host buffers ZERO-COPY
        with immutable semantics, and does NOT keep them valid against
        later reuse (measured: a post-put write to the numpy buffer
        changes the jax.Array's contents, and training over recycled
        gather-window/minibatch buffers was nondeterministic).  Take a
        device-side copy and block until it has read the source, so the
        returned array is XLA-owned and the caller may reuse or free
        its buffer immediately — matching real-transfer backends.
        (Handing ``device_put`` a TEMPORARY numpy copy instead
        reproducibly corrupted the process heap — glibc "corrupted
        double-linked list" — so the source must stay alive, which the
        caller guarantees for the duration of this call.)"""
        import jax
        dev = jax.device_put(array, self.jax_device)
        if isinstance(array, numpy.ndarray):
            dev = jax.numpy.copy(dev)
            dev.block_until_ready()
        return dev


class NumpyDevice(Device):
    """Pure numpy pseudo-device; always available."""

    BACKEND = "numpy"
    PRIORITY = 10

    @classmethod
    def available(cls):
        return True


class DeviceInfo(object):
    """Per-chip autotune table for Pallas kernel tile sizes.

    TPU analog of the reference's ``devices/device_infos.json`` block-size
    database (reference: backends.py:88-143).  Keyed by device kind and
    op signature; persisted under the cache dir.
    """

    _lock = threading.Lock()

    def __init__(self, device_kind):
        self.device_kind = device_kind
        self.table = {}
        self._path = os.path.join(
            root.common.dirs.get("cache", "/tmp"), "device_infos.json")
        self._load()

    #: shipped autotune tables (analog of the reference's checked-in
    #: devices/device_infos.json) — consulted when the cache is cold
    SHIPPED_PATH = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "devices", "device_infos.json")

    def _load(self):
        self.table = {}
        for path in (self.SHIPPED_PATH, self._path):
            try:
                with open(path) as fin:
                    data = json.load(fin)
                self.table.update(data.get(self.device_kind, {}))
            except (OSError, ValueError):
                pass

    def get(self, op_key, default=None):
        return self.table.get(op_key, default)

    def put(self, op_key, value):
        self.table[op_key] = value
        self._save()

    def _save(self):
        with DeviceInfo._lock:
            data = {}
            try:
                with open(self._path) as fin:
                    data = json.load(fin)
            except (OSError, ValueError):
                pass
            data[self.device_kind] = self.table
            os.makedirs(os.path.dirname(self._path), exist_ok=True)
            tmp = self._path + ".tmp"
            with open(tmp, "w") as fout:
                json.dump(data, fout, indent=1, sort_keys=True)
            os.replace(tmp, self._path)
