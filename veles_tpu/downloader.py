"""Dataset downloader (reference veles/downloader.py:56): fetch an
archive from a URL into the data directory and unpack it, skipping the
work when the target already exists.  Supports file:// URLs (used by
tests; production clusters usually pre-stage data anyway) and honors
zero-egress environments by failing with a clear message instead of
hanging."""

import os
import tarfile
import urllib.request
import zipfile

from veles_tpu.units import Unit

__all__ = ["Downloader"]


class Downloader(Unit):
    def __init__(self, workflow, **kwargs):
        super(Downloader, self).__init__(workflow, **kwargs)
        self.url = kwargs["url"]
        self.directory = kwargs.get("directory", ".")
        self.files = list(kwargs.get("files", ()))  # expected outputs

    @property
    def satisfied(self):
        return self.files and all(
            os.path.exists(os.path.join(self.directory, f))
            for f in self.files)

    def initialize(self, **kwargs):
        super(Downloader, self).initialize(**kwargs)
        if not self.satisfied:
            self.download()
        return True

    def download(self):
        os.makedirs(self.directory, exist_ok=True)
        name = os.path.basename(self.url.split("?")[0]) or "dataset"
        archive = os.path.join(self.directory, name)
        if not os.path.exists(archive):
            self.info("fetching %s", self.url)
            try:
                with urllib.request.urlopen(self.url, timeout=60) as r, \
                        open(archive, "wb") as out:
                    while True:
                        chunk = r.read(1 << 20)
                        if not chunk:
                            break
                        out.write(chunk)
            except OSError as exc:
                raise RuntimeError(
                    "download of %s failed (no network egress?): %s" %
                    (self.url, exc))
        self.unpack(archive)

    def unpack(self, archive):
        if tarfile.is_tarfile(archive):
            with tarfile.open(archive) as tar:
                tar.extractall(self.directory, filter="data")
        elif zipfile.is_zipfile(archive):
            with zipfile.ZipFile(archive) as z:
                z.extractall(self.directory)

    def run(self):
        pass
