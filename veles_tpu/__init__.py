"""VELES-TPU: a TPU-native distributed deep-learning platform.

A ground-up rebuild of the capabilities of Samsung VELES
(https://github.com/devbib/veles) designed for TPUs: JAX/XLA for the
compute path, Pallas for custom kernels, ``jax.sharding`` meshes and ICI
collectives for scale-out, with the reference's unit/workflow graph UX,
loaders, snapshots, services, and meta-optimization preserved on top.

Quick start::

    import veles_tpu
    veles_tpu.run(MyWorkflow, config)          # like `veles wf.py cfg.py`

Reference parity citations throughout the tree point at file:line in the
upstream checkout (mounted read-only during development).
"""

__version__ = "0.1.0"
__license__ = "Apache 2.0"

from veles_tpu.config import root  # noqa: F401
from veles_tpu.mutable import Bool, LinkableAttribute  # noqa: F401
from veles_tpu.units import Unit, IUnit  # noqa: F401
from veles_tpu.workflow import Workflow, NoMoreJobs  # noqa: F401
from veles_tpu.distributable import (  # noqa: F401
    Distributable, IDistributable, Pickleable, TriviallyDistributable)


def run(workflow_class, config=None, **kwargs):
    """Programmatic entry point (reference: veles/__init__.py:142)."""
    from veles_tpu.__main__ import Main
    return Main().run_workflow(workflow_class, config, **kwargs)


def load_plugins(paths=None):
    """Discover and import plugin packages (reference
    veles/__init__.py:294-306: packages shipping a ``.veles`` marker
    register their units on import via the UnitRegistry metaclass).

    A plugin is any importable top-level package whose directory
    contains a ``.veles_tpu`` marker file.  Returns the imported
    modules.  Scans ``paths`` (default sys.path) once per process.
    """
    import importlib
    import os
    import sys

    if load_plugins._loaded is not None and paths is None:
        return load_plugins._loaded
    found = []
    for entry in (paths if paths is not None else sys.path):
        try:
            names = os.listdir(entry or ".")
        except OSError:
            continue
        for name in names:
            pkg_dir = os.path.join(entry or ".", name)
            if not os.path.exists(os.path.join(pkg_dir, ".veles_tpu")):
                continue
            try:
                found.append(importlib.import_module(name))
            except Exception as exc:
                import logging
                logging.getLogger("veles_tpu").warning(
                    "plugin %s failed to import: %s", name, exc)
    if paths is None:
        load_plugins._loaded = found
    return found


load_plugins._loaded = None


def _make_module_callable():
    """``import veles_tpu; veles_tpu(MyWorkflow, config)`` — the
    reference's callable-module magic (veles/__init__.py:126)."""
    import sys
    import types

    mod = sys.modules[__name__]

    class _CallableModule(types.ModuleType):
        def __call__(self, workflow_class, config=None, **kwargs):
            return run(workflow_class, config, **kwargs)

    mod.__class__ = _CallableModule


_make_module_callable()
