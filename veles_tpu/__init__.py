"""VELES-TPU: a TPU-native distributed deep-learning platform.

A ground-up rebuild of the capabilities of Samsung VELES
(https://github.com/devbib/veles) designed for TPUs: JAX/XLA for the
compute path, Pallas for custom kernels, ``jax.sharding`` meshes and ICI
collectives for scale-out, with the reference's unit/workflow graph UX,
loaders, snapshots, services, and meta-optimization preserved on top.

Quick start::

    import veles_tpu
    veles_tpu.run(MyWorkflow, config)          # like `veles wf.py cfg.py`

Reference parity citations throughout the tree point at file:line in the
upstream checkout (mounted read-only during development).
"""

__version__ = "0.1.0"
__license__ = "Apache 2.0"

from veles_tpu.config import root  # noqa: F401
from veles_tpu.mutable import Bool, LinkableAttribute  # noqa: F401
from veles_tpu.units import Unit, IUnit  # noqa: F401
from veles_tpu.workflow import Workflow, NoMoreJobs  # noqa: F401
from veles_tpu.distributable import (  # noqa: F401
    Distributable, IDistributable, Pickleable, TriviallyDistributable)


def run(workflow_class, config=None, **kwargs):
    """Programmatic entry point (reference: veles/__init__.py:142)."""
    from veles_tpu.__main__ import Main
    return Main().run_workflow(workflow_class, config, **kwargs)
