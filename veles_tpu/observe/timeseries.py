"""Time-series rollups: bounded rings of fixed-interval buckets.

The storage layer of the fleet telemetry plane (docs/observability.md
"Fleet telemetry").  A :class:`SeriesRing` samples the process
MetricsRegistry at heartbeat cadence into fixed-interval buckets —
counter -> per-second rate (plus the exact delta), gauge -> last
value, histogram -> a mergeable log-binned digest of the observations
that arrived since the previous tick — bounded in memory and
serializable as plain JSON.

Slaves and serve hosts ship not-yet-shipped buckets as bounded chunks
over the links that already carry trace chunks (the client/server
``series_chunk`` frame beside ``trace_chunk``, the fleet link's
``telemetry`` op beside its keepalive pings); the master/router-side
:class:`FleetTelemetry` aligns per-host buckets onto the LOCAL clock
with the observe/cluster.py NTP-style offsets and merges them into
fleet rollups with kind-true semantics:

- **counters sum** — rates (and deltas) add across hosts;
- **latency digests merge** — bin-wise, so a fleet percentile is
  recovered from the union of every host's observations rather than
  averaged from per-host percentiles (which has no meaning);
- **gauges take the max** — queue depth: the worst host is the one a
  burn-rate alert must see.

Everything here is stdlib-only and never raises into a caller's job
cycle: malformed chunks are counted and dropped whole, exactly the
TraceCollector discipline.
"""

import collections
import math
import os
import threading
import time

__all__ = ["SERIES_SCHEMA_VERSION", "DIGEST_BASE", "digest_values",
           "merge_digests", "digest_percentiles", "SeriesRing",
           "FleetTelemetry", "fleet_summary", "series"]

SERIES_SCHEMA_VERSION = 1

#: Log-spaced digest bin edges: ``edge(i) = DIGEST_BASE ** i``.  Base
#: 2**0.25 puts 4 bins per octave — a recovered percentile is off by
#: at most ~19% relative, bin keys stay small integers over the whole
#: microsecond..hour latency range, and two digests merge by adding
#: bin counts (the property per-host percentiles can never have).
DIGEST_BASE = 2.0 ** 0.25
_LOG_BASE = math.log(DIGEST_BASE)
#: Non-positive observations (rate floors, zero durations) land in
#: the dedicated "z" bin whose edge is 0.0.
_ZERO_BIN = "z"


def _bin_key(value):
    if value <= 0.0:
        return _ZERO_BIN
    # ceil puts a value at its UPPER edge's bin: edge(i-1) < v <= edge(i)
    return str(int(math.ceil(math.log(value) / _LOG_BASE - 1e-12)))


def _bin_edge(key):
    if key == _ZERO_BIN:
        return 0.0
    return DIGEST_BASE ** int(key)


def digest_values(values):
    """Digest a batch of observations into a mergeable summary:
    ``{"count", "sum", "min", "max", "bins": {key: n}}``.  Non-finite
    values are skipped — a NaN latency must not poison a fleet
    percentile."""
    count = 0
    total = 0.0
    lo = hi = None
    bins = {}
    for value in values:
        value = float(value)
        if not math.isfinite(value):
            continue
        count += 1
        total += value
        if lo is None or value < lo:
            lo = value
        if hi is None or value > hi:
            hi = value
        key = _bin_key(value)
        bins[key] = bins.get(key, 0) + 1
    return {"count": count, "sum": total, "min": lo, "max": hi,
            "bins": bins}


def merge_digests(digests):
    """Bin-wise merge — the percentile-merge half of the rollup
    contract.  Tolerates None / malformed entries (a host's bucket
    may simply lack the histogram this round)."""
    out = {"count": 0, "sum": 0.0, "min": None, "max": None, "bins": {}}
    for digest in digests:
        if not isinstance(digest, dict):
            continue
        try:
            out["count"] += int(digest.get("count") or 0)
            out["sum"] += float(digest.get("sum") or 0.0)
        except (TypeError, ValueError):
            continue
        for bound, pick in (("min", min), ("max", max)):
            val = digest.get(bound)
            if isinstance(val, (int, float)) and math.isfinite(val):
                out[bound] = val if out[bound] is None \
                    else pick(out[bound], val)
        raw = digest.get("bins")
        if isinstance(raw, dict):
            for key, n in raw.items():
                try:
                    out["bins"][key] = out["bins"].get(key, 0) + int(n)
                except (TypeError, ValueError):
                    continue
    return out


def digest_percentiles(digest, ps=(50, 95, 99)):
    """Nearest-rank percentiles recovered from a digest: each bin
    answers with its UPPER edge (pessimistic by at most one bin
    width, ~19%), clamped into the digest's exact [min, max]."""
    if not isinstance(digest, dict):
        return {}
    bins = digest.get("bins") or {}
    items = sorted((_bin_edge(key), int(n)) for key, n in bins.items()
                   if n)
    total = sum(n for _, n in items)
    if not total:
        return {}
    lo, hi = digest.get("min"), digest.get("max")
    out = {}
    for p in ps:
        rank = max(1, min(total, int(math.ceil(p / 100.0 * total))))
        cum = 0
        value = items[-1][0]
        for edge, n in items:
            cum += n
            if cum >= rank:
                value = edge
                break
        if isinstance(hi, (int, float)):
            value = min(value, hi)
        if isinstance(lo, (int, float)):
            value = max(value, lo)
        out["p%d" % p] = value
    return out


class SeriesRing(object):
    """Bounded ring of fixed-interval buckets over one
    MetricsRegistry.

    ``tick()`` closes one bucket: counter values become {delta, rate}
    against the previous tick, gauges report their last (finite
    numeric) value, histograms digest exactly the observations that
    arrived since the previous tick (count delta against the window
    ring — see ``Histogram.recent``).  The FIRST tick only primes the
    counter baselines and emits no bucket: a ring attached to a
    long-running registry must not open with a since-boot "rate".

    ``maybe_tick()`` is the pull-cadence entry for callers without a
    heartbeat thread (the serve transport answering a telemetry poll,
    the slave shipping beside an update): it ticks only once
    ``interval_s`` has elapsed, so heartbeat and link cadences share
    one ring without double-sampling.  Rates always divide by the
    ACTUAL elapsed time, so a late tick stays correct.
    """

    def __init__(self, interval_s=5.0, capacity=240, registry=None,
                 label=None):
        from veles_tpu.observe import metrics as _metrics
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.label = label
        self._metrics_mod = _metrics
        self._registry = registry if registry is not None \
            else _metrics.registry
        self._lock = threading.Lock()
        self._buckets = collections.deque(maxlen=self.capacity)
        self._last_counters = None     # None = unprimed
        self._last_hist_counts = {}
        self._last_tick = None         # monotonic
        self._seq = 0
        self._shipped_seq = 0          # take_chunk cursor

    def __len__(self):
        with self._lock:
            return len(self._buckets)

    def maybe_tick(self, now=None, wall=None):
        """Tick if (and only if) the interval elapsed — or prime on
        first call.  Returns the new bucket or None."""
        now = time.monotonic() if now is None else now
        with self._lock:
            last = self._last_tick
        if last is not None and now - last < self.interval_s:
            return None
        return self.tick(now=now, wall=wall)

    def tick(self, now=None, wall=None):
        """Close one bucket from the registry's current state.
        Returns the bucket dict, or None on the priming tick."""
        _m = self._metrics_mod
        now = time.monotonic() if now is None else now
        wall = time.time() if wall is None else wall
        counters = {}
        gauges = {}
        hists = {}
        cur_counters = {}
        cur_hist_counts = {}
        pairs = self._registry.items()
        with self._lock:
            primed = self._last_tick is not None and \
                self._last_counters is not None
            dur = (now - self._last_tick) if primed else None
            for name, metric in pairs:
                if isinstance(metric, _m.Counter):
                    cur_counters[name] = value = metric.value
                    if not primed:
                        continue
                    delta = value - self._last_counters.get(name, 0)
                    if delta < 0:
                        # registry reset between ticks (bench A/B
                        # legs): the whole lifetime value is new
                        delta = value
                    counters[name] = {
                        "delta": delta,
                        "rate": delta / max(dur, 1e-9)}
                elif isinstance(metric, _m.Gauge):
                    value = metric.value
                    if isinstance(value, bool) or not \
                            isinstance(value, (int, float)):
                        continue
                    if not math.isfinite(value):
                        continue
                    if primed:
                        gauges[name] = value
                elif isinstance(metric, _m.Histogram):
                    cur_hist_counts[name] = count = metric.count
                    if not primed:
                        continue
                    delta = count - self._last_hist_counts.get(name, 0)
                    if delta < 0:
                        delta = count
                    if delta <= 0:
                        continue
                    values = metric.recent(delta)
                    digest = digest_values(values)
                    if delta > len(values):
                        # window ring overran between ticks: the
                        # digest covers the newest `window` values;
                        # name the loss instead of hiding it
                        digest["dropped"] = delta - len(values)
                    hists[name] = digest
            self._last_counters = cur_counters
            self._last_hist_counts = cur_hist_counts
            self._last_tick = now
            if not primed:
                return None
            bucket = {"seq": self._seq, "ts": wall,
                      "dur_s": round(dur, 6),
                      "counters": counters, "gauges": gauges,
                      "hists": hists}
            self._seq += 1
            self._buckets.append(bucket)
        try:
            self._registry.gauge("telemetry.buckets").set(
                len(self._buckets))
        except Exception:
            pass
        return bucket

    def buckets(self, last=None):
        """The newest ``last`` buckets (all when None), oldest first."""
        with self._lock:
            out = list(self._buckets)
        if last is not None and last > 0:
            out = out[-int(last):]
        return out

    def last_bucket(self):
        with self._lock:
            return self._buckets[-1] if self._buckets else None

    def snapshot(self, last=None, label=None):
        """Serializable, mergeable view: the wire/export format every
        consumer (telemetry polls, ``observe fleet`` files,
        FleetTelemetry.add_chunk) shares."""
        return {"kind": "series", "schema": SERIES_SCHEMA_VERSION,
                "interval_s": self.interval_s,
                "label": label if label is not None else self.label,
                "buckets": self.buckets(last=last)}

    def take_chunk(self, max_buckets=32, label=None):
        """Pop a bounded chunk of NOT-yet-shipped buckets (the trace
        ``take_chunk`` contract): returns a snapshot-shaped dict or
        None when nothing new accrued.  Single-consumer — the
        master-link shipper; fan-out readers use ``snapshot`` (the
        receiving FleetTelemetry dedups by seq either way)."""
        with self._lock:
            fresh = [b for b in self._buckets
                     if b["seq"] >= self._shipped_seq]
            fresh = fresh[:max(1, int(max_buckets))]
            if not fresh:
                return None
            self._shipped_seq = fresh[-1]["seq"] + 1
        try:
            self._registry.counter("telemetry.chunks_shipped").inc()
        except Exception:
            pass
        return {"kind": "series", "schema": SERIES_SCHEMA_VERSION,
                "interval_s": self.interval_s,
                "label": label if label is not None else self.label,
                "buckets": fresh}

    def heartbeat_block(self):
        """The compact ``series`` block a v3 heartbeat line carries:
        ring shape plus the newest bucket (the full ring ships over
        the chunk paths, not the heartbeat file)."""
        with self._lock:
            held = len(self._buckets)
            last = self._buckets[-1] if self._buckets else None
        return {"schema": SERIES_SCHEMA_VERSION,
                "interval_s": self.interval_s,
                "buckets_held": held,
                "last": last}

    def clear(self):
        """Reset buckets AND baselines (test isolation / bench legs)."""
        with self._lock:
            self._buckets.clear()
            self._last_counters = None
            self._last_hist_counts = {}
            self._last_tick = None
            self._seq = 0
            self._shipped_seq = 0


class FleetTelemetry(object):
    """Master/router-side store: bounded per-host bucket series plus
    clock offsets, merged on demand into fleet rollups.

    Offsets follow the trace-merge convention (observe/cluster.py):
    ``host_wall + offset = local_wall``, fed either directly from the
    slave's ``clock_report`` (``set_offset``) or from raw NTP probe
    samples the fleet link's telemetry polls piggyback
    (``add_probe`` -> ``estimate_offset``, min-delay sample wins).

    ``add_chunk`` validates-and-drops like TraceCollector: a
    malformed chunk is counted, never raised; re-shipped buckets
    (snapshot-mode producers overlap on purpose) dedup by per-host
    ``seq`` so a rollup never double-counts."""

    def __init__(self, interval_s=5.0, max_buckets_per_host=240):
        self.interval_s = float(interval_s)
        self.max_buckets = int(max_buckets_per_host)
        self._lock = threading.Lock()
        self._hosts = {}       # label -> deque of buckets
        self._last_seq = {}    # label -> newest seq accepted
        self._offsets = {}     # label -> (offset_s, delay_s)
        self._probes = {}      # label -> deque of NTP samples
        self.chunks = 0
        self.dropped = 0

    # -- clock alignment ----------------------------------------------------

    def set_offset(self, host, offset, delay=None):
        try:
            offset = float(offset)
        except (TypeError, ValueError):
            return
        if not math.isfinite(offset):
            return
        with self._lock:
            self._offsets[str(host)] = (offset, delay)

    def offset(self, host):
        with self._lock:
            entry = self._offsets.get(str(host))
        return entry[0] if entry else 0.0

    def add_probe(self, host, sample):
        """Feed one (t0, t1, t2, t3) wall-clock probe; the offset
        estimate is refreshed from the newest 8 samples (min-delay
        wins — the cluster.estimate_offset discipline)."""
        from veles_tpu.observe.cluster import estimate_offset
        try:
            t0, t1, t2, t3 = (float(v) for v in sample)
        except (TypeError, ValueError):
            return
        if not all(math.isfinite(v) for v in (t0, t1, t2, t3)):
            return
        host = str(host)
        with self._lock:
            ring = self._probes.setdefault(
                host, collections.deque(maxlen=8))
            ring.append((t0, t1, t2, t3))
            samples = list(ring)
        try:
            offset, delay = estimate_offset(samples)
        except (ValueError, ZeroDivisionError):
            return
        self.set_offset(host, offset, delay)

    # -- ingest -------------------------------------------------------------

    def add_chunk(self, host, chunk):
        """Ingest one series chunk for ``host``; False (and counted)
        when malformed.  Never raises."""
        if not isinstance(chunk, dict) or \
                chunk.get("schema") != SERIES_SCHEMA_VERSION or \
                not isinstance(chunk.get("buckets"), list):
            self.dropped += 1
            return False
        host = str(host)
        accepted = 0
        with self._lock:
            ring = self._hosts.setdefault(
                host, collections.deque(maxlen=self.max_buckets))
            last_seq = self._last_seq.get(host)
            for bucket in chunk["buckets"]:
                if not isinstance(bucket, dict) or not \
                        isinstance(bucket.get("ts"), (int, float)):
                    continue
                seq = bucket.get("seq")
                if isinstance(seq, int):
                    if last_seq is not None and seq <= last_seq:
                        continue  # overlap re-ship: already held
                    last_seq = seq
                ring.append(bucket)
                accepted += 1
            if last_seq is not None:
                self._last_seq[host] = last_seq
            self.chunks += 1
        return accepted > 0

    def hosts(self):
        with self._lock:
            return sorted(self._hosts)

    def host_buckets(self, host):
        with self._lock:
            return list(self._hosts.get(str(host), ()))

    # -- rollup -------------------------------------------------------------

    def rollup(self, window=None):
        """Merge per-host buckets onto the local clock: bucket cell =
        ``floor((ts + offset) / interval_s)``.  Returns merged
        buckets oldest first (the newest ``window`` cells when set),
        each carrying the contributing host list."""
        with self._lock:
            hosts = {h: list(ring) for h, ring in self._hosts.items()}
            offsets = {h: entry[0]
                       for h, entry in self._offsets.items()}
        cells = {}
        for host, buckets in hosts.items():
            off = offsets.get(host, 0.0)
            for bucket in buckets:
                key = int(math.floor(
                    (bucket["ts"] + off) / self.interval_s))
                cell = cells.get(key)
                if cell is None:
                    cell = cells[key] = {
                        "hosts": set(), "counters": {},
                        "gauges": {}, "hists": {}}
                cell["hosts"].add(host)
                for name, c in (bucket.get("counters") or {}).items():
                    if not isinstance(c, dict):
                        continue
                    agg = cell["counters"].setdefault(
                        name, {"delta": 0, "rate": 0.0})
                    try:
                        agg["delta"] += c.get("delta") or 0
                        agg["rate"] += c.get("rate") or 0.0
                    except TypeError:
                        continue
                for name, value in (bucket.get("gauges") or {}).items():
                    if not isinstance(value, (int, float)):
                        continue
                    prev = cell["gauges"].get(name)
                    cell["gauges"][name] = value if prev is None \
                        else max(prev, value)
                for name, digest in (bucket.get("hists") or {}).items():
                    cell["hists"].setdefault(name, []).append(digest)
        keys = sorted(cells)
        if window is not None and window > 0:
            keys = keys[-int(window):]
        out = []
        for key in keys:
            cell = cells[key]
            out.append({
                "ts": key * self.interval_s,
                "dur_s": self.interval_s,
                "hosts": sorted(cell["hosts"]),
                "counters": cell["counters"],
                "gauges": cell["gauges"],
                "hists": {name: merge_digests(ds)
                          for name, ds in cell["hists"].items()},
            })
        return out

    def series(self, name, kind="counter", field="rate", window=None):
        """One metric's per-bucket values over the rollup tail:
        counters -> ``field`` ("rate"/"delta", 0.0 when absent),
        gauges -> value-or-None, hists -> digest-or-None."""
        out = []
        for bucket in self.rollup(window=window):
            if kind == "counter":
                entry = bucket["counters"].get(name)
                out.append((entry or {}).get(field, 0.0)
                           if entry else 0.0)
            elif kind == "gauge":
                out.append(bucket["gauges"].get(name))
            else:
                out.append(bucket["hists"].get(name))
        return out

    def snapshot(self):
        """Plain-data view for /healthz and the ``observe fleet``
        CLI."""
        with self._lock:
            hosts = {
                host: {
                    "buckets": len(ring),
                    "offset_s": self._offsets.get(host, (0.0,))[0],
                    "last_ts": ring[-1]["ts"] if ring else None,
                }
                for host, ring in self._hosts.items()}
        return {"schema": SERIES_SCHEMA_VERSION,
                "interval_s": self.interval_s,
                "hosts": hosts, "chunks": self.chunks,
                "dropped": self.dropped}

    def clear(self):
        with self._lock:
            self._hosts.clear()
            self._last_seq.clear()
            self._offsets.clear()
            self._probes.clear()
            self.chunks = 0
            self.dropped = 0


def fleet_summary(buckets):
    """Collapse rollup buckets (or any bucket list) into one
    per-metric table — the ``observe fleet`` CLI body, the /healthz
    digest, and what soak receipts compare against per-host evidence:
    counters -> total delta + mean rate, gauges -> max, histograms ->
    merged-digest count/p50/p95/p99."""
    buckets = list(buckets)
    counters, gauges, hist_digests = {}, {}, {}
    hosts = set()
    for bucket in buckets:
        for host in bucket.get("hosts") or ():
            hosts.add(host)
        for name, entry in (bucket.get("counters") or {}).items():
            if not isinstance(entry, dict):
                continue
            agg = counters.setdefault(name, {"delta": 0, "rates": []})
            agg["delta"] += entry.get("delta") or 0
            rate = entry.get("rate")
            if isinstance(rate, (int, float)):
                agg["rates"].append(float(rate))
        for name, value in (bucket.get("gauges") or {}).items():
            if not isinstance(value, (int, float)):
                continue
            prev = gauges.get(name)
            gauges[name] = value if prev is None else max(prev, value)
        for name, digest in (bucket.get("hists") or {}).items():
            hist_digests.setdefault(name, []).append(digest)
    out_counters = {
        name: {"delta": agg["delta"],
               "rate": round(sum(agg["rates"]) / len(agg["rates"]), 4)
               if agg["rates"] else 0.0}
        for name, agg in counters.items()}
    out_hists = {}
    for name, digests in hist_digests.items():
        merged = merge_digests(digests)
        row = {"count": merged["count"]}
        row.update(digest_percentiles(merged))
        out_hists[name] = row
    return {"buckets": len(buckets), "hosts": sorted(hosts),
            "counters": out_counters, "gauges": gauges,
            "hists": out_hists}


def _default_interval_s():
    """``VELES_SERIES_INTERVAL_S`` overrides the global ring's 5 s
    bucket width — how a soak driver runs its subprocess hosts at
    soak-scale cadence without a config file."""
    try:
        value = float(os.environ.get("VELES_SERIES_INTERVAL_S", ""))
    except ValueError:
        return 5.0
    return value if value > 0 else 5.0


#: The process-wide ring every producer feeds: the Heartbeat ticks it
#: at metrics cadence, the slave's update shipping and the serve
#: transport's telemetry polls ``maybe_tick`` it as a fallback, and
#: every shipper chunks from it.
series = SeriesRing(interval_s=_default_interval_s())
