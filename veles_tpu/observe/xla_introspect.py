"""XLA-layer introspection: recompiles, device memory, achieved MFU.

The runtime above XLA is otherwise blind to three failure/perf modes
the TPU-systems literature calls out as the ones that matter:

- **recompile storms** — a shape or donation mistake that silently
  recompiles the step every iteration costs orders of magnitude more
  than any kernel win.  :class:`CompileWatcher` counts backend
  compilation events via ``jax.monitoring`` (``compile.count`` /
  ``compile.seconds`` registry metrics) and tracks the jit-cache size
  of registered functions (the fused step, the eval dispatch), warning
  the first time a watched function recompiles past its expected
  signature count;
- **device-memory growth** — :func:`device_memory_gauges` publishes
  ``memory_stats()`` per device where the backend provides it (TPU),
  falling back to a live-array census (``jax.live_arrays()``) where it
  does not (CPU), as ``xla.mem.*`` gauges;
- **achieved MFU** — :func:`set_step_flops` records the XLA cost
  model's FLOP count for the compiled fused step (the same
  ``cost_analysis()`` number bench.py reports), and
  :func:`mfu_snapshot` divides by the recent median step time and the
  chip's peak to publish a live ``xla.mfu_pct`` gauge the heartbeat
  and web-status health block carry — cross-checkable against
  ``bench.py``'s offline ``MFU.json``.

Everything here imports jax lazily and is called OFF the step path
(compile time, heartbeat thread, decision class end), preserving the
observe-package invariant that telemetry never adds a host sync.
"""

import os
import threading

from veles_tpu.observe.metrics import percentiles
from veles_tpu.observe.metrics import registry as _registry

__all__ = ["CompileWatcher", "watcher", "ensure_installed", "watch",
           "poll_recompiles", "device_memory_gauges", "set_step_flops",
           "set_fwd_flops", "set_step_dtype", "step_dtype",
           "peak_flops", "mfu_snapshot", "bwd_snapshot",
           "compile_snapshot", "compile_delta", "PEAK_BF16_TFLOPS",
           "PEAK_INT8_TFLOPS"]

#: bf16 MXU peak TFLOP/s by device-kind substring (public spec sheets);
#: bench.py shares this table for its offline MFU context.
PEAK_BF16_TFLOPS = (
    ("v6", 918.0), ("v5p", 459.0), ("v5", 197.0), ("v4", 275.0),
    ("v3", 123.0), ("v2", 45.0),
)

#: int8 MXU peak TOP/s by device-kind substring: v5e/v5p/v6 run int8 at
#: 2x the bf16 rate (spec sheets); v2-v4 have no separate 8-bit mode —
#: their entries equal bf16 so an int8 MFU there is merely conservative,
#: never inflated.  The quantized serve engine's MFU/attribution
#: ceiling (docs/serving.md "Quantized ladder") — dividing an int8
#: step by the bf16 peak would double-count the headroom the MXU's
#: 8-bit mode actually provides.
PEAK_INT8_TFLOPS = (
    ("v6", 1836.0), ("v5p", 918.0), ("v5", 394.0), ("v4", 275.0),
    ("v3", 123.0), ("v2", 45.0),
)

_PEAK_TABLES = {"bf16": PEAK_BF16_TFLOPS, "int8": PEAK_INT8_TFLOPS}

#: the jax.monitoring duration event emitted once per XLA backend
#: compilation (jaxpr trace / MLIR lowering events are deliberately
#: not counted: only backend compiles cost real seconds at scale).
#: NOTE this event fires around ``compile_or_get_cached``, so a
#: persistent-cache HIT still bumps ``compile.count`` — the cache
#: events below are what separate "asked XLA for an executable" from
#: "actually built one" (serve engine cold/warm receipts key on it)
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"

#: jax.monitoring point events emitted by the persistent compilation
#: cache (jax/_src/compiler.py): a hit means the executable was
#: DESERIALIZED, not rebuilt, so real new compiles = count - hits
_CACHE_EVENT_COUNTERS = (
    ("/jax/compilation_cache/cache_hits", "compile.cache_hits"),
    ("/jax/compilation_cache/cache_misses", "compile.cache_misses"),
)


class CompileWatcher(object):
    """Count XLA compilations and detect per-function recompiles."""

    def __init__(self, registry=None, warn_after=2):
        self.registry = registry if registry is not None else _registry
        #: cache entries a watched function may legitimately grow to
        #: before a recompile warning (the fused step compiles once per
        #: dropout/poison signature, so 2 is the healthy ceiling)
        self.warn_after = warn_after
        self.installed = False
        self._lock = threading.Lock()
        self._watched = {}  # name -> [fn, last_size, warned]

    # -- global compile accounting ----------------------------------------

    def install(self):
        """Register the jax.monitoring listener (idempotent; a missing
        or old jax disables the counter, never the caller)."""
        with self._lock:
            if self.installed:
                return True
            try:
                from jax import monitoring
                monitoring.register_event_duration_secs_listener(
                    self._on_duration)
                monitoring.register_event_listener(self._on_event)
            except Exception:
                return False
            self.installed = True
            return True

    def _on_duration(self, event, duration, **kwargs):
        if not event.endswith(_COMPILE_EVENT_SUFFIX):
            return
        self.registry.counter("compile.count").inc()
        self.registry.counter("compile.seconds").inc(float(duration))
        from veles_tpu.observe.trace import tracer
        if tracer.active:
            tracer.instant("xla.compile", cat="xla",
                           seconds=round(float(duration), 4))

    def _on_event(self, event, **kwargs):
        for name, counter in _CACHE_EVENT_COUNTERS:
            if event == name:
                self.registry.counter(counter).inc()
                return

    # -- per-function recompile detection ----------------------------------

    def watch(self, fn, name):
        """Track a jitted function's compilation-cache size (pjit's
        ``_cache_size``); functions without one are ignored."""
        if not hasattr(fn, "_cache_size"):
            return False
        with self._lock:
            self._watched[name] = [fn, 0, False]
        return True

    def unwatch(self, name):
        with self._lock:
            self._watched.pop(name, None)

    def poll(self, warn=None):
        """Refresh watched cache sizes; returns {name: size}.  Called
        off the hot path (heartbeat thread, compile time).  The first
        time a function's cache exceeds ``warn_after`` entries a
        recompile-storm warning is logged and a ``compile.recompiles``
        counter bumped by the growth."""
        with self._lock:
            watched = list(self._watched.items())
        sizes = {}
        for name, entry in watched:
            fn, last, warned = entry
            try:
                size = int(fn._cache_size())
            except Exception:
                continue
            sizes[name] = size
            if size > last:
                if last:  # growth past the first compile = recompile
                    self.registry.counter(
                        "compile.recompiles").inc(size - last)
                entry[1] = size
            if size > self.warn_after and not warned:
                entry[2] = True
                import logging
                logging.getLogger("xla").warning(
                    "recompile storm suspected: %s has %d compiled "
                    "signatures (expected <= %d) — check for varying "
                    "shapes/dtypes or re-donated buffers",
                    name, size, self.warn_after)
                if warn is not None:
                    warn(name, size)
        return sizes


#: process-wide watcher (the fused trainer installs + registers into it)
watcher = CompileWatcher()


def ensure_installed():
    return watcher.install()


def watch(fn, name):
    return watcher.watch(fn, name)


def poll_recompiles():
    return watcher.poll()


def compile_snapshot(reg=None):
    """{"count", "seconds", "recompiles", "cache_hits", "cache_misses"}
    from the registry — always a complete dict (zeros before the first
    compile), so heartbeat consumers can rely on the keys existing.
    ``count`` includes persistent-cache hits (the backend event wraps
    the cache lookup); ``count - cache_hits`` is the number of
    executables XLA actually built, the serve engine's warm-restart
    receipt (docs/serving.md)."""
    reg = reg if reg is not None else _registry
    out = {}
    for key, name, cast in (
            ("count", "compile.count", int),
            ("seconds", "compile.seconds",
             lambda v: round(float(v), 4)),
            ("recompiles", "compile.recompiles", int),
            ("cache_hits", "compile.cache_hits", int),
            ("cache_misses", "compile.cache_misses", int)):
        metric = reg.peek(name)
        out[key] = cast(metric.value) if metric is not None else cast(0)
    return out


class compile_delta(object):
    """Context manager measuring backend-compile activity inside the
    block: ``with compile_delta() as d: ...`` then ``d.receipt`` is
    ``{"backend_compiles", "cache_hits", "new_compiles"}``.

    The decomposition mirrors the serve engine's warm-restart receipt:
    jax's monitoring event fires even on a persistent-cache hit, so
    ``new_compiles = requests - hits`` is what XLA actually built.
    Shared by ``AOTEngine.compile``, the serve hot-reload receipt (a
    same-digest reload must report 0) and the tests that assert it.
    """

    def __init__(self, reg=None):
        self._reg = reg
        self.receipt = None

    def __enter__(self):
        ensure_installed()
        self._before = compile_snapshot(self._reg)
        return self

    def __exit__(self, *exc_info):
        after = compile_snapshot(self._reg)
        requests = after["count"] - self._before["count"]
        hits = after["cache_hits"] - self._before["cache_hits"]
        self.receipt = {
            "backend_compiles": requests,
            "cache_hits": hits,
            "new_compiles": max(0, requests - hits),
        }
        return False


# -- device memory -----------------------------------------------------------


def device_memory_gauges(reg=None):
    """Publish per-device memory gauges; returns the flat dict.

    Prefers the backend's ``memory_stats()`` (TPU/GPU expose
    bytes_in_use / peak_bytes_in_use); where unavailable (CPU) falls
    back to a live-array census — the sum of ``nbytes`` over
    ``jax.live_arrays()`` — which tracks the same leak/growth signal
    with framework-side accounting."""
    reg = reg if reg is not None else _registry
    out = {}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return out
    have_stats = False
    for index, device in enumerate(devices):
        stats = None
        try:
            stats = device.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        have_stats = True
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                name = "xla.mem.%s.d%d" % (key, index)
                reg.gauge(name).set(int(stats[key]))
                out[name] = int(stats[key])
    if not have_stats:
        try:
            live = sum(int(getattr(arr, "nbytes", 0))
                       for arr in jax.live_arrays())
        except Exception:
            return out
        reg.gauge("xla.mem.live_bytes").set(live)
        out["xla.mem.live_bytes"] = live
    return out


# -- FLOPs / MFU -------------------------------------------------------------


def set_step_flops(flops, reg=None):
    """Record the cost-analysis FLOP count of ONE fused train step
    (published by the fused trainer right after compile)."""
    reg = reg if reg is not None else _registry
    reg.gauge("xla.step_flops").set(float(flops))


def set_fwd_flops(flops, reg=None):
    """Record the cost-analysis FLOP count of the FORWARD-only program
    (the fused trainer's eval dispatch — same layer composition as the
    train step's forward).  Together with ``xla.step_flops`` this is
    what lets :func:`bwd_snapshot` attribute the step between forward
    and backward+update (docs/kernels.md)."""
    reg = reg if reg is not None else _registry
    reg.gauge("xla.fwd_flops").set(float(flops))


_peak_cache = {}
_peak_lock = threading.Lock()
_step_dtype = ["bf16"]


def set_step_dtype(name, reg=None):
    """Record the DOMINANT arithmetic dtype of the measured step
    ("bf16" covers the f32/bf16 ladder — one MXU rate; "int8" the
    quantized level), so :func:`mfu_snapshot` divides by the matching
    peak instead of always the bf16 ceiling.  Set by the quantized
    serve engine at compile; training paths keep the default."""
    if name not in _PEAK_TABLES:
        raise ValueError("unknown step dtype %r (have %s)" %
                         (name, sorted(_PEAK_TABLES)))
    with _peak_lock:
        _step_dtype[0] = name
    reg = reg if reg is not None else _registry
    reg.gauge("xla.step_dtype_int8").set(1 if name == "int8" else 0)


def step_dtype():
    """The recorded dominant step dtype ("bf16" default)."""
    with _peak_lock:
        return _step_dtype[0]


def _measured_peak():
    """Fallback peak for chips without a spec-table entry (host CPU
    under JAX_PLATFORMS=cpu): the achieved FLOP/s of a small f32
    matmul, measured once and cached.  MFU against a measured matmul
    ceiling is the honest definition available on such backends — and
    it keeps ``mfu_pct`` live (non-null) on development runs so the
    plumbing is exercised before a TPU ever sees it."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy
    n = 384
    a = jnp.asarray(numpy.random.RandomState(7)
                    .rand(n, n).astype(numpy.float32))
    matmul = jax.jit(lambda x, y: x @ y)
    jax.block_until_ready(matmul(a, a))  # compile outside the timing
    best = None
    for _ in range(3):
        start = time.perf_counter()
        jax.block_until_ready(matmul(a, a))
        elapsed = time.perf_counter() - start
        if best is None or elapsed < best:
            best = elapsed
    return 2.0 * n * n * n / max(best, 1e-9)


def peak_flops(dtype=None):
    """This process's peak FLOP/s reference for MFU, resolved once per
    dtype: ``VELES_PEAK_TFLOPS`` env override -> the device-kind spec
    table for ``dtype`` (``None`` -> the recorded :func:`step_dtype`,
    so a quantized engine's steps rate against the int8 peak) ->
    measured matmul ceiling (CPU dev runs, one ceiling for every
    dtype — the interpreter has no 8-bit mode to rate against).  None
    when jax itself is unusable."""
    if dtype is None:
        dtype = step_dtype()
    table = _PEAK_TABLES.get(dtype, PEAK_BF16_TFLOPS)
    key = ("peak", dtype)
    with _peak_lock:
        if key in _peak_cache:
            return _peak_cache[key]
        peak = None
        env = os.environ.get("VELES_PEAK_TFLOPS", "")
        if env:
            try:
                peak = float(env) * 1e12
            except ValueError:
                peak = None
        if peak is None:
            try:
                import jax
                kind = jax.local_devices()[0].device_kind.lower()
                for kind_key, tflops in table:
                    if kind_key in kind:
                        peak = tflops * 1e12
                        break
            except Exception:
                pass
        if peak is None:
            try:
                peak = _peak_cache.get(("measured",))
                if peak is None:
                    peak = _measured_peak()
                    _peak_cache[("measured",)] = peak
            except Exception:
                peak = None
        _peak_cache[key] = peak
        return peak


def mfu_snapshot(reg=None):
    """Live achieved-MFU percentage, or None when the inputs are not
    yet published (no compiled step, no timed steps).  Publishes the
    ``xla.mfu_pct`` gauge as a side effect so health_snapshot and the
    web-status dashboard pick it up.  Uses the p50 of the recent
    step-time window: MFU is a steady-state number and a median
    ignores the compile-step outlier by construction."""
    reg = reg if reg is not None else _registry
    # the backward attribution refreshes on the same tick (heartbeat /
    # web-status reporter both call mfu_snapshot), so the fwd/bwd
    # split can never lag the whole-step number it decomposes.  It
    # runs FIRST: bwd.step_ms needs only the train/eval histograms,
    # so it must survive this function's own early returns (no FLOPs
    # gauge, no peak rating)
    bwd_snapshot(reg)
    flops_gauge = reg.peek("xla.step_flops")
    hist = reg.peek("step.train_s")
    if flops_gauge is None or flops_gauge.value is None or hist is None:
        return None
    window = hist.window_values()
    if not window:
        return None
    step_s = percentiles(window, ps=(50,)).get("p50")
    if not step_s or step_s <= 0:
        return None
    peak = peak_flops()
    if not peak:
        return None
    mfu = 100.0 * float(flops_gauge.value) / step_s / peak
    mfu = round(mfu, 3)
    reg.gauge("xla.mfu_pct").set(mfu)
    return mfu


def bwd_snapshot(reg=None):
    """Backward+update attribution (docs/kernels.md): ``bwd.step_ms``
    and ``bwd.mfu_pct`` gauges next to the whole-step ``xla.mfu_pct``,
    so heartbeats and web_status carry the fwd/bwd split — the offline
    MFU.json ``backward_attribution`` block, live.

    Derived, no new host syncs: the eval dispatch IS the forward-only
    program and its ``step.eval_s`` histogram is already measured, so
    bwd time = p50(train step) - p50(eval step) and bwd FLOPs =
    ``xla.step_flops`` - ``xla.fwd_flops`` (both published by the
    fused trainer's one-time cost analysis).  Approximation caveat:
    the eval forward skips dropout masking and the loss tail, so the
    split attributes those few percent to the backward side.  Returns
    {"bwd_step_ms", "bwd_mfu_pct"} or None while any input is missing
    (no eval steps yet, cost analysis unavailable)."""
    reg = reg if reg is not None else _registry
    train_hist = reg.peek("step.train_s")
    eval_hist = reg.peek("step.eval_s")
    step_gauge = reg.peek("xla.step_flops")
    fwd_gauge = reg.peek("xla.fwd_flops")
    if train_hist is None or eval_hist is None:
        return None
    train_win = train_hist.window_values()
    eval_win = eval_hist.window_values()
    if not train_win or not eval_win:
        return None
    train_s = percentiles(train_win, ps=(50,)).get("p50")
    eval_s = percentiles(eval_win, ps=(50,)).get("p50")
    if not train_s or not eval_s or train_s <= eval_s:
        return None
    bwd_s = train_s - eval_s
    out = {"bwd_step_ms": round(bwd_s * 1e3, 3)}
    reg.gauge("bwd.step_ms").set(out["bwd_step_ms"])
    peak = peak_flops()
    if (peak and step_gauge is not None and fwd_gauge is not None
            and step_gauge.value and fwd_gauge.value
            and step_gauge.value > fwd_gauge.value):
        bwd_flops = float(step_gauge.value) - float(fwd_gauge.value)
        out["bwd_mfu_pct"] = round(
            100.0 * bwd_flops / bwd_s / peak, 3)
        reg.gauge("bwd.mfu_pct").set(out["bwd_mfu_pct"])
    return out
