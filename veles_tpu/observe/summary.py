"""Textual digests of trace files and flight dumps.

CI logs and bug reports cannot attach a Perfetto UI; this module turns
a trace (``--trace`` output or a merged cluster trace) or a flight
dump into a few lines of text: per-track top-N spans by SELF time
(span duration minus the duration of spans nested inside it — the
number that says where time is actually spent, not merely enclosed)
plus the last value of every counter track.

``python -m veles_tpu.observe summary <trace.json|flight.json>`` is
the CLI; :func:`digest_line` is the one-liner bench.py appends to its
output when ``VELES_TRACE`` is set.
"""

import json

__all__ = ["load", "summarize", "summarize_trace", "summarize_flight",
           "summarize_heartbeats", "render", "digest_line",
           "request_digest_line"]


def load(path):
    """A trace file, a flight dump, or a heartbeat JSONL file
    (``--metrics-path`` output) — JSONL is detected by failing the
    single-document parse and folded into a ``heartbeats`` doc."""
    with open(path) as fin:
        text = fin.read()
    try:
        return json.loads(text)
    except ValueError:
        records = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # a torn final line from a killed process
        if not records:
            raise
        return {"kind": "heartbeats", "records": records}


def _self_times(events):
    """Per-(pid,tid) self time: sweep sorted complete events with a
    stack (the same nesting walk validate_trace does), subtracting each
    child's duration from its parent."""
    per_track = {}
    for event in events:
        if event.get("ph") != "X":
            continue
        per_track.setdefault(
            (event.get("pid"), event.get("tid")), []).append(event)
    out = {}  # track -> {name: [self_us, total_us, count]}
    for track, spans in per_track.items():
        spans.sort(key=lambda e: (e["ts"], -e["dur"]))
        stats = out.setdefault(track, {})
        stack = []  # [end_us, name]
        for event in spans:
            end = event["ts"] + event["dur"]
            while stack and stack[-1][0] <= event["ts"] + 1.0:
                stack.pop()
            if stack:
                parent = stats.get(stack[-1][1])
                if parent is not None:
                    parent[0] -= event["dur"]
            entry = stats.setdefault(event["name"], [0.0, 0.0, 0])
            entry[0] += event["dur"]
            entry[1] += event["dur"]
            entry[2] += 1
            stack.append([end, event["name"]])
    return out


def _track_names(events):
    """(pid,tid) -> "process/thread" display names from metadata."""
    procs, threads = {}, {}
    for event in events:
        if event.get("ph") != "M":
            continue
        args = event.get("args") or {}
        if event.get("name") == "process_name":
            procs[event.get("pid")] = args.get("name", "")
        elif event.get("name") == "thread_name":
            threads[(event.get("pid"), event.get("tid"))] = \
                args.get("name", "")
    out = {}
    for key, name in threads.items():
        pid = key[0]
        proc = procs.get(pid)
        out[key] = "%s/%s" % (proc, name) if proc else \
            "pid%s/%s" % (pid, name)
    return out, procs


def summarize_trace(doc, top=10):
    events = doc.get("traceEvents", [])
    names, procs = _track_names(events)
    tracks = {}
    for track, stats in _self_times(events).items():
        label = names.get(track) or (
            "%s/tid%s" % (procs.get(track[0], "pid%s" % track[0]),
                          track[1]))
        rows = sorted(
            ((name, s[0] / 1e6, s[1] / 1e6, s[2])
             for name, s in stats.items()),
            key=lambda row: -row[1])[:top]
        tracks[label] = [
            {"name": name, "self_s": round(self_s, 6),
             "total_s": round(total_s, 6), "count": count}
            for name, self_s, total_s, count in rows]
    counters = {}
    for event in events:
        if event.get("ph") == "C":
            counters[event["name"]] = (
                event.get("args") or {}).get("value")
    return {"kind": "trace", "tracks": tracks, "counters": counters,
            "events": sum(1 for e in events if e.get("ph") != "M")}


def summarize_flight(doc, top=10):
    tracks = {}
    counters = {}
    instants = {}
    for event in doc.get("events", ()):
        kind = event.get("kind")
        thread = event.get("thread", "?")
        if kind == "span":
            stats = tracks.setdefault(thread, {})
            entry = stats.setdefault(event["name"], [0.0, 0])
            entry[0] += float(event.get("dur_s") or 0.0)
            entry[1] += 1
        elif kind == "counter":
            counters[event["name"]] = (
                event.get("args") or {}).get("value")
        elif kind == "instant":
            instants[event["name"]] = instants.get(event["name"], 0) + 1
    rendered = {}
    for thread, stats in tracks.items():
        rows = sorted(((name, s[0], s[1]) for name, s in stats.items()),
                      key=lambda row: -row[1])[:top]
        rendered[thread] = [
            {"name": name, "self_s": round(total, 6),
             "total_s": round(total, 6), "count": count}
            for name, total, count in rows]
    return {"kind": "flight", "reason": doc.get("reason"),
            "tracks": rendered, "counters": counters,
            "instants": instants,
            "events": len(doc.get("events", ()))}


def summarize_heartbeats(doc, top=10):
    """Digest a heartbeat JSONL file: schema v2 lines (pre-telemetry)
    and v3 lines (``series`` + ``alerts`` blocks) side by side —
    counter RATES derived from consecutive lines' cumulative values,
    published under the measure.py filter-passes discipline, plus the
    last line's health and any alerts the file recorded."""
    from veles_tpu.observe.profile import validate_heartbeat
    from veles_tpu.tune.measure import (filter_passes,
                                        positive_majority_median)
    lines, schemas, invalid = [], {}, 0
    for record in doc.get("records", ()):
        try:
            validate_heartbeat(record)
        except ValueError:
            invalid += 1
            continue
        lines.append(record)
        schema = record["schema"]
        schemas[schema] = schemas.get(schema, 0) + 1
    samples = {}
    prev = None
    for record in lines:
        if prev is not None and record["ts"] > prev["ts"] and \
                record["session"] == prev["session"]:
            dt = record["ts"] - prev["ts"]
            for name, value in record["counters"].items():
                delta = value - prev["counters"].get(name, 0)
                if delta >= 0:  # a reset between lines is not a rate
                    samples.setdefault(name, []).append(delta / dt)
        prev = record
    rates = {}
    for name, rate_samples in samples.items():
        med = positive_majority_median(filter_passes(rate_samples))
        if med is not None:
            rates[name] = round(med, 3)
    ranked = sorted(rates.items(), key=lambda kv: -kv[1])[:top]
    last = lines[-1] if lines else {}
    alert_names = set()
    for record in lines:
        for entry in (record.get("alerts") or {}).get("history", ()):
            if entry.get("state") == "firing":
                alert_names.add(entry.get("alert"))
    return {"kind": "heartbeats", "events": len(lines),
            "invalid": invalid, "schemas": schemas,
            "sessions": len({r["session"] for r in lines}),
            "rates": dict(ranked),
            "health": last.get("health") or {},
            "throughput_sps": last.get("throughput_sps"),
            "series": last.get("series") or {},
            "alerts_fired": sorted(a for a in alert_names if a),
            "tracks": {}, "counters": {}, "instants": {}}


def summarize(doc, top=10):
    """Dispatch on document shape: flight dump, heartbeat JSONL, or
    trace file."""
    if doc.get("kind") == "flight":
        return summarize_flight(doc, top=top)
    if doc.get("kind") == "heartbeats":
        return summarize_heartbeats(doc, top=top)
    return summarize_trace(doc, top=top)


def render(summary, out=None):
    """Human-readable multi-line rendering (the CLI's output)."""
    import sys
    out = out if out is not None else sys.stdout
    header = "%s digest: %d events" % (summary["kind"],
                                       summary["events"])
    if summary.get("reason"):
        header += " (reason: %s)" % summary["reason"]
    print(header, file=out)
    if summary["kind"] == "heartbeats":
        print("  lines: %d valid (%d invalid), schemas %s, "
              "%d session(s)"
              % (summary["events"], summary["invalid"],
                 ",".join("v%d x%d" % (s, n) for s, n in
                          sorted(summary["schemas"].items())),
                 summary["sessions"]), file=out)
        if summary.get("throughput_sps") is not None:
            print("  last throughput: %.3f samples/s"
                  % summary["throughput_sps"], file=out)
        if summary.get("rates"):
            print("  steady-state rates (per second):", file=out)
            for name, rate in sorted(summary["rates"].items()):
                print("    %-32s %s" % (name, rate), file=out)
        series = summary.get("series") or {}
        if series.get("schema"):
            print("  series ring: %s buckets @ %ss"
                  % (series.get("buckets_held"),
                     series.get("interval_s")), file=out)
        if summary.get("alerts_fired"):
            print("  alerts fired: %s"
                  % ", ".join(summary["alerts_fired"]), file=out)
        return
    for label in sorted(summary["tracks"]):
        rows = summary["tracks"][label]
        if not rows:
            continue
        print("  track %s:" % label, file=out)
        for row in rows:
            print("    %-32s self %10.4fs  total %10.4fs  x%d" %
                  (row["name"], row["self_s"], row["total_s"],
                   row["count"]), file=out)
    if summary.get("counters"):
        print("  counters (last values):", file=out)
        for name in sorted(summary["counters"]):
            print("    %-32s %s" % (name, summary["counters"][name]),
                  file=out)
    if summary.get("instants"):
        print("  instants:", file=out)
        for name in sorted(summary["instants"]):
            print("    %-32s x%d" % (name, summary["instants"][name]),
                  file=out)


def request_digest_line(doc, top=3):
    """One line of per-request-segment attribution when the document
    carries request-scoped spans or exemplars (observe/requests.py);
    None otherwise — ``observe summary`` and :func:`digest_line`
    append it so CI logs show WHERE request time went."""
    from veles_tpu.observe import requests as reqtrace
    records, counts = reqtrace.extract_requests(doc)
    if not records:
        return None
    report = reqtrace.analyze(records, counts, top=top)
    segs = sorted(report["segments"].items(),
                  key=lambda kv: -kv[1]["p99_ms"])[:top]
    parts = ", ".join("%s p99 %.3f ms" % (name, row["p99_ms"])
                      for name, row in segs)
    return "request segments: %d requests, %d legs; %s" % (
        report["requests"], report["legs"], parts or "no segments")


def digest_line(doc, top=3):
    """One line: the global top-N spans by self time — what bench.py
    appends to CI logs when VELES_TRACE is set."""
    summary = summarize(doc, top=top)
    merged = {}
    for rows in summary["tracks"].values():
        for row in rows:
            entry = merged.setdefault(row["name"], [0.0, 0])
            entry[0] += row["self_s"]
            entry[1] += row["count"]
    ranked = sorted(merged.items(), key=lambda kv: -kv[1][0])[:top]
    spans = ", ".join("%s %.3fs x%d" % (name, s, c)
                      for name, (s, c) in ranked) or "no spans"
    line = "trace digest: %d events; top self-time: %s" % (
        summary["events"], spans)
    req = request_digest_line(doc, top=top)
    return line if req is None else "%s; %s" % (line, req)
