"""Unified telemetry layer: span tracing, metrics, profiler hooks.

One measurement substrate for the whole system (docs/observability.md),
replacing the three ad-hoc timer systems that grew organically: the
workflow's method/unit wall timers, pipeline_input's per-stage
perf_counter deltas, and the health watchdog's decision-unit-only lazy
device counters.  Three pieces:

- :mod:`veles_tpu.observe.trace` — a thread-safe span tracer with a
  context-manager + decorator API emitting Chrome trace-event JSON
  (loadable in Perfetto / chrome://tracing) with per-thread tracks and
  zero overhead when disabled;
- :mod:`veles_tpu.observe.metrics` — a registry of counters, gauges
  and windowed histograms (step-time percentiles, throughput, health
  counts, queue depths).  Device scalars enter the registry only at
  the EXISTING lazy-metric sync points (decision class end,
  snapshotter rollback, server quarantine) — the registry never adds a
  host sync to the hot path;
- :mod:`veles_tpu.observe.profile` — ``jax.profiler`` start/stop
  around a configurable step window (``VELES_PROFILE=dir`` /
  ``VELES_PROFILE_WINDOW=start:stop``) and the periodic JSONL
  heartbeat (``--metrics-interval N``) consumed by web_status.py
  dashboards and offline tooling.

Cluster scope (PR 5) adds four more:

- :mod:`veles_tpu.observe.flight` — the always-on black-box ring of
  recent events, dumped on divergence/rollback/quarantine/crash;
- :mod:`veles_tpu.observe.cluster` — NTP-style clock-offset
  estimation and the master-side collector for slave trace chunks;
- :mod:`veles_tpu.observe.merge` — per-process traces -> one
  offset-corrected Perfetto timeline (also ``python -m
  veles_tpu.observe merge``);
- :mod:`veles_tpu.observe.xla_introspect` — recompile counting,
  device-memory gauges, and the live ``mfu_pct`` from the compiled
  step's cost analysis (jax imported lazily, off the hot path).

The serve tier (PR 19) adds:

- :mod:`veles_tpu.observe.requests` — request-scoped serve tracing:
  trace ids, per-segment timelines, the tail-exemplar ring dumped on
  SLO violations, and the ``python -m veles_tpu.observe requests``
  critical-path analyzer.

The fleet telemetry plane (PR 20) adds the decisions layer:

- :mod:`veles_tpu.observe.timeseries` — fixed-interval bucket rings
  fed from the registry (counter->rate, gauge->last, histogram->
  mergeable digest), shipped as bounded chunks over the trace-chunk
  links, merged fleet-side with the PR 5 clock offsets
  (``FleetTelemetry``; ``python -m veles_tpu.observe fleet``);
- :mod:`veles_tpu.observe.alerts` — declarative multi-window
  burn-rate + EMA-spike alert rules over those series,
  edge-triggered with flight + exemplar evidence dumps;
- :mod:`veles_tpu.observe.baseline` — the perf-regression sentinel:
  bench compact records + steady-state rates vs the committed
  ``PERF_BASELINE.json`` (``bench.py --gate``; ``python -m
  veles_tpu.observe regress``).

Everything here is stdlib-only and import-light, so hot modules
(units, pipeline_input, compiler-adjacent code) can import it without
dragging in jax.
"""

from veles_tpu.observe.alerts import (ALERTS_SCHEMA_VERSION,
                                      AlertManager, BurnRateRule,
                                      EmaSpikeRule, alerts,
                                      default_rules)
from veles_tpu.observe.baseline import (gate, load_baseline,
                                        steady_state_rates)
from veles_tpu.observe.cluster import (TraceCollector, estimate_offset,
                                       probe_sample)
from veles_tpu.observe.flight import (FLIGHT_SCHEMA_VERSION,
                                      FlightRecorder, flight,
                                      validate_flight)
from veles_tpu.observe.metrics import (Counter, Gauge, Histogram,
                                       MetricsRegistry, health_snapshot,
                                       percentiles, registry)
from veles_tpu.observe.profile import (HEARTBEAT_SCHEMA_VERSION, Heartbeat,
                                       ProfilerHook, install_profiler,
                                       profiler_step, uninstall_profiler,
                                       validate_heartbeat)
from veles_tpu.observe.requests import (ExemplarRing, analyze_files,
                                        exemplars, mint_trace_id,
                                        normalize_trace_id,
                                        render_requests)
from veles_tpu.observe.timeseries import (SERIES_SCHEMA_VERSION,
                                          FleetTelemetry, SeriesRing,
                                          digest_percentiles,
                                          digest_values,
                                          fleet_summary,
                                          merge_digests, series)
from veles_tpu.observe.trace import (CHUNK_SCHEMA_VERSION, SpanTracer,
                                     instant, span, traced, tracer,
                                     validate_trace)

__all__ = [
    "SpanTracer", "tracer", "span", "instant", "traced", "validate_trace",
    "CHUNK_SCHEMA_VERSION",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "registry",
    "percentiles", "health_snapshot",
    "ProfilerHook", "install_profiler", "uninstall_profiler",
    "profiler_step", "Heartbeat", "validate_heartbeat",
    "HEARTBEAT_SCHEMA_VERSION",
    "FlightRecorder", "flight", "validate_flight",
    "FLIGHT_SCHEMA_VERSION",
    "TraceCollector", "estimate_offset", "probe_sample",
    "ExemplarRing", "exemplars", "mint_trace_id",
    "normalize_trace_id", "analyze_files", "render_requests",
    "SeriesRing", "FleetTelemetry", "series", "fleet_summary",
    "digest_values", "merge_digests", "digest_percentiles",
    "SERIES_SCHEMA_VERSION",
    "AlertManager", "BurnRateRule", "EmaSpikeRule", "alerts",
    "default_rules", "ALERTS_SCHEMA_VERSION",
    "gate", "load_baseline", "steady_state_rates",
]
