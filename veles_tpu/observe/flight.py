"""Black-box flight recorder: the last N telemetry events, always.

``--trace`` answers "where does the time go" when someone *planned* to
look; this module answers "what happened just before it died" when
nobody did.  A bounded ring buffer (``collections.deque(maxlen=N)``)
holds the most recent spans, instants, counters and heartbeat lines the
telemetry layer produced, at near-zero cost (one tuple build + one
GIL-atomic append per event, no locks, no serialization), and is dumped
as schema-versioned JSON when something goes wrong:

- training divergence (``models/decision.py`` watchdog trip);
- snapshot rollback (``snapshotter.py``);
- poisoned-update quarantine (``server.py``);
- an unhandled exception or fatal signal escaping the launcher's run
  scope (``launcher.py``).

The recorder is fed by the span tracer (``trace.py``): every
instrumented ``complete``/``instant``/``counter`` site routes a compact
record here even while full tracing is off, so the ring is populated in
ordinary production runs without anyone passing ``--trace``.  Chaos-
injected failures (docs/checkpointing.md, docs/health.md) therefore
leave a loadable timeline instead of demanding log archaeology.

Disable with ``VELES_FLIGHT=0``; resize with ``VELES_FLIGHT_CAPACITY``.
Dumps validate against :func:`validate_flight` (``schema: 1``) and are
readable by ``python -m veles_tpu.observe summary <dump.json>``.
"""

import collections
import json
import logging
import os
import threading
import time

__all__ = ["FlightRecorder", "flight", "validate_flight",
           "FLIGHT_SCHEMA_VERSION"]

FLIGHT_SCHEMA_VERSION = 1

_logger = logging.getLogger("flight")

#: required keys -> allowed types of one flight dump document
_FLIGHT_REQUIRED = {
    "kind": str, "schema": int, "reason": str, "ts": (int, float),
    "mono": (int, float), "pid": int, "host": str, "events": list,
}

#: required keys of one serialized flight event
_EVENT_REQUIRED = ("ts", "mono", "thread", "kind", "name")


class FlightRecorder(object):
    """Bounded always-on ring of recent telemetry events + crash dump.

    The hot method is :meth:`record`: build one tuple, append to a
    maxlen deque — both effectively atomic under the GIL, so the hot
    path takes no lock (the lock guards only dumps, which snapshot the
    ring).  ``enabled`` is a plain bool; when False every method
    returns immediately.
    """

    def __init__(self, capacity=None, enabled=None, base_path=None):
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "VELES_FLIGHT_CAPACITY", 4096))
            except ValueError:
                capacity = 4096
        if enabled is None:
            enabled = os.environ.get("VELES_FLIGHT", "1") not in (
                "0", "false", "no", "off")
        self.enabled = bool(enabled)
        self.capacity = max(16, int(capacity))
        #: dump destination stem; dumps land at
        #: ``<base_path>.<reason>.<seq>.json`` (launcher points this
        #: next to ``--trace`` when one is set)
        self.base_path = base_path or "veles_flight"
        self.dumps = 0
        self.last_dump_path = None
        self._buf = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    # -- recording (hot) ---------------------------------------------------

    def record(self, kind, name, cat=None, wall=None, dur=None,
               args=None):
        """Append one event: ``kind`` is span/instant/counter/heartbeat,
        ``wall`` the event's wall-clock time (now when omitted),
        ``dur`` seconds for spans, ``args`` a small plain-data dict."""
        if not self.enabled:
            return
        self._buf.append((
            time.time() if wall is None else wall,
            time.perf_counter(),
            threading.current_thread().name,
            kind, name, cat, dur, args))

    def __len__(self):
        return len(self._buf)

    def clear(self):
        with self._lock:
            self._buf.clear()

    # -- dumping -----------------------------------------------------------

    def snapshot(self):
        """The ring as serializable event dicts, oldest first.

        The lock acquire is BOUNDED: dumps run from failure paths —
        including a signal handler interrupting the very thread that
        holds the lock — and a black box that deadlocks the dying
        process is worse than a marginally racy copy (list(deque) is
        a single GIL-atomic operation either way)."""
        locked = self._lock.acquire(timeout=2.0)
        try:
            raw = list(self._buf)
        finally:
            if locked:
                self._lock.release()
        events = []
        for wall, mono, thread, kind, name, cat, dur, args in raw:
            event = {"ts": wall, "mono": mono, "thread": thread,
                     "kind": kind, "name": name}
            if cat is not None:
                event["cat"] = cat
            if dur is not None:
                event["dur_s"] = dur
            if args:
                event["args"] = args
            events.append(event)
        return events

    def document(self, reason="", extra=None):
        """``extra`` merges additional top-level blocks into the dump
        (e.g. the request-tracing exemplar timelines,
        observe/requests.py); required schema keys always win —
        validate_flight tolerates the additions."""
        from veles_tpu import logger as _vlogger
        doc = {
            "kind": "flight",
            "schema": FLIGHT_SCHEMA_VERSION,
            "reason": reason or "dump",
            "ts": time.time(),
            "mono": time.perf_counter(),
            "pid": os.getpid(),
            "host": os.uname().nodename,
            "session": getattr(_vlogger, "session_id", ""),
            "capacity": self.capacity,
            "events": self.snapshot(),
        }
        if extra:
            for key, value in extra.items():
                doc.setdefault(key, value)
        return doc

    def dump(self, reason="", path=None, extra=None):
        """Write the ring to ``path`` (default: sequenced next to
        ``base_path``) atomically.  NEVER raises — the recorder runs on
        failure paths where a second fault must not mask the first.
        Returns the written path, or None."""
        if not self.enabled:
            return None
        try:
            doc = self.document(reason, extra=extra)
            if path is None:
                locked = self._lock.acquire(timeout=2.0)
                try:
                    seq, self.dumps = self.dumps, self.dumps + 1
                finally:
                    if locked:
                        self._lock.release()
                path = "%s.%s.%d.json" % (
                    self.base_path,
                    (reason or "dump").replace(" ", "_").replace(
                        os.sep, "_"),
                    seq)
            directory = os.path.dirname(os.path.abspath(path))
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as fout:
                json.dump(doc, fout, default=repr)
            os.replace(tmp, path)
            self.last_dump_path = path
            _logger.warning("flight recorder dumped %d events to %s "
                            "(reason: %s)", len(doc["events"]), path,
                            doc["reason"])
            return path
        except Exception as exc:
            _logger.error("flight dump failed: %s", exc)
            return None


def validate_flight(doc):
    """Schema check of a loaded flight dump; raises ValueError.  The
    contract tests and external post-mortem tooling rely on."""
    if not isinstance(doc, dict):
        raise ValueError("flight dump is not an object")
    for key, types in _FLIGHT_REQUIRED.items():
        if key not in doc:
            raise ValueError("flight dump missing %r" % key)
        if not isinstance(doc[key], types):
            raise ValueError("flight dump %r has type %s" %
                             (key, type(doc[key]).__name__))
    if doc["kind"] != "flight":
        raise ValueError("kind must be 'flight'")
    if doc["schema"] != FLIGHT_SCHEMA_VERSION:
        raise ValueError("unknown flight schema %r" % doc["schema"])
    for i, event in enumerate(doc["events"]):
        if not isinstance(event, dict):
            raise ValueError("flight event %d is not an object" % i)
        for key in _EVENT_REQUIRED:
            if key not in event:
                raise ValueError("flight event %d missing %r" % (i, key))
    return doc


#: The process-wide recorder the tracer feeds and failure paths dump.
flight = FlightRecorder()
