"""Cluster-scope trace correlation: clock offsets + chunk collection.

A master and its slaves each record spans against their own process
clock; to read one job's ``proto.job_out -> slave step -> proto.
update_in`` path as a single flame graph the timelines must share a
clock.  Two pieces make that possible:

- :func:`estimate_offset` — an NTP-style offset estimate from the
  four-timestamp probe exchange the client runs at join time
  (``clock_probe`` / ``clock_probe_ack`` protocol messages).  The
  classic formulation: for each probe ``(t0, t1, t2, t3)`` (client
  send, server receive, server reply, client receive — all wall
  clock), offset = ((t1 - t0) + (t2 - t3)) / 2 and round-trip delay =
  (t3 - t0) - (t2 - t1).  The estimate from the MINIMUM-delay probe
  wins: queueing noise only ever inflates delay, so the fastest
  exchange is the one where the symmetric-path assumption is most
  honest.  Error is bounded by delay/2 under path asymmetry.

- :class:`TraceCollector` — the master-side store for the bounded
  trace chunks slaves ship back with their updates (or at session
  end).  Chunks keep their per-process wall anchors; the collector
  attaches the estimated clock offset and a stable track label per
  slave, which is exactly the shape :mod:`veles_tpu.observe.merge`
  consumes.

Stdlib-only and import-light, like the rest of the observe package.
"""

import threading

from veles_tpu.observe.trace import CHUNK_SCHEMA_VERSION

__all__ = ["estimate_offset", "probe_sample", "TraceCollector"]


def probe_sample(t0, t1, t2, t3):
    """One probe -> (offset_s, delay_s): positive offset means the
    SERVER clock is ahead of the client clock."""
    return ((t1 - t0) + (t2 - t3)) / 2.0, (t3 - t0) - (t2 - t1)


def estimate_offset(samples):
    """Best (offset_s, delay_s) over probe tuples ``(t0, t1, t2, t3)``.

    Picks the minimum-delay sample (see module docstring); raises
    ValueError on an empty sample set.  The returned offset converts a
    client wall timestamp to the server's clock as ``t + offset``.
    """
    if not samples:
        raise ValueError("no clock probe samples")
    best = None
    for sample in samples:
        offset, delay = probe_sample(*sample)
        if best is None or delay < best[1]:
            best = (offset, delay)
    return best


class TraceCollector(object):
    """Bounded per-slave store of shipped trace chunks + clock offsets.

    Keys are the slave's stable machine-process id (``mid``), so a
    slave that reconnects (quarantine TTL, network blip) keeps
    accumulating into the same logical track.  Memory is bounded by
    ``max_events`` across all slaves; past it new chunks are counted
    in ``dropped_events`` instead of growing the store — the master's
    observability must never become the master's OOM."""

    def __init__(self, max_events=500000):
        self._lock = threading.Lock()
        self._max_events = int(max_events)
        self._chunks = {}       # key -> [chunk, ...]
        self._offsets = {}      # key -> (offset_s, delay_s)
        self.total_events = 0
        self.dropped_events = 0

    def set_offset(self, key, offset, delay=None):
        """Record a slave's estimated clock offset (slave clock +
        offset = master clock at merge time; the protocol reports the
        server-ahead convention, see :func:`estimate_offset`)."""
        with self._lock:
            self._offsets[key] = (float(offset),
                                  None if delay is None else float(delay))

    def offset(self, key):
        pair = self._offsets.get(key)
        return pair[0] if pair else 0.0

    def add_chunk(self, key, chunk):
        """Store one shipped chunk; returns the number of events kept.
        Malformed or unknown-schema chunks are dropped whole (counted),
        never raised — a misbehaving slave must not take the master's
        event loop down."""
        if (not isinstance(chunk, dict)
                or chunk.get("schema") != CHUNK_SCHEMA_VERSION
                or not isinstance(chunk.get("events"), list)):
            with self._lock:
                self.dropped_events += (
                    len(chunk["events"])
                    if isinstance(chunk, dict)
                    and isinstance(chunk.get("events"), list) else 1)
            return 0
        events = chunk["events"]
        with self._lock:
            room = self._max_events - self.total_events
            if room <= 0:
                self.dropped_events += len(events)
                return 0
            if len(events) > room:
                self.dropped_events += len(events) - room
                chunk = dict(chunk, events=events[:room])
                events = chunk["events"]
            self._chunks.setdefault(key, []).append(chunk)
            self.total_events += len(events)
            return len(events)

    def keys(self):
        with self._lock:
            return list(self._chunks)

    def parts(self):
        """The merge-ready view: one part per slave — ``{"label",
        "offset_s", "chunks"}`` (see :func:`veles_tpu.observe.merge.
        merge_parts`)."""
        with self._lock:
            out = []
            for key, chunks in self._chunks.items():
                label = chunks[0].get("label") or "slave:%s" % key
                out.append({
                    "label": label,
                    "offset_s": self.offset(key),
                    "chunks": list(chunks),
                })
            return out
