"""Span tracer: Chrome trace-event JSON, viewable in Perfetto.

Records *complete* events ("ph": "X") with microsecond timestamps on a
``time.perf_counter`` base — the same clock the unit/pipeline timers
use, so a span's ``dur`` agrees with the accumulated timer it rides on.
Each thread gets its own track (a ``thread_name`` metadata event is
emitted on first sight), so the prefetch worker's fill/H2D spans render
on a separate lane from the graph thread's unit-run spans and the
overlap is visible directly.

Design rules:

- **zero overhead when disabled**: ``tracer.enabled`` is a plain bool;
  hot call sites guard on it (one attribute load) and every public
  method returns immediately when tracing is off.  ``span()`` returns
  a shared no-op context manager;
- **no locks on the hot path**: event dicts are appended to a plain
  list (``list.append`` is atomic under the GIL); the lock guards only
  start/save and first-sight thread registration;
- **bounded memory**: past ``max_events`` new events are counted as
  dropped instead of growing the buffer without bound.

The module-level :data:`tracer` singleton is the instance the whole
system instruments against; ``--trace PATH`` (launcher.py) starts it
and saves the file at run end.
"""

import functools
import json
import os
import threading
import time

from veles_tpu.observe.flight import flight as _global_flight

__all__ = ["SpanTracer", "tracer", "span", "instant", "traced",
           "validate_trace", "CHUNK_SCHEMA_VERSION"]

#: schema of the bounded trace chunks slaves ship to the master
#: (observe/cluster.py collects them, observe/merge.py stitches them)
CHUNK_SCHEMA_VERSION = 1


class _NullSpan(object):
    """Shared no-op context manager returned while tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span(object):
    __slots__ = ("_tracer", "_name", "_cat", "_args", "_start")

    def __init__(self, owner, name, cat, args):
        self._tracer = owner
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer.complete(
            self._name, self._start, time.perf_counter() - self._start,
            cat=self._cat, args=self._args)
        return False


class SpanTracer(object):
    """Thread-safe trace-event recorder with a Perfetto-loadable dump."""

    def __init__(self, max_events=1000000, flight=None, label=None):
        self.enabled = False
        self.dropped = 0
        #: process/track label used by cross-process merge (e.g.
        #: "master" / "slave:<mid>"); defaults to pid at merge time
        self.label = label
        self._max_events = max_events
        self._events = []
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()
        # wall-clock anchor taken at the SAME instant as the
        # perf_counter epoch: event ts (µs since epoch) + this anchor
        # maps any event onto the wall clock, which is what cross-host
        # trace merging needs (offset-corrected wall time is the only
        # shared timeline two processes have)
        self._epoch_wall = time.time()
        self._pid = os.getpid()
        self._tids = {}
        self._tid_names = {}
        self._flight = flight if flight is not None else _global_flight

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Clear any previous events and begin recording."""
        with self._lock:
            self._events = []
            self._tids = {}
            self._tid_names = {}
            self.dropped = 0
            self._epoch = time.perf_counter()
            self._epoch_wall = time.time()
            self.enabled = True
        return self

    def stop(self):
        self.enabled = False
        return self

    @property
    def active(self):
        """True when an instrumented site should call in: full tracing
        is on, OR the always-on flight recorder wants the event.  Hot
        sites guard on this instead of ``enabled`` so the flight ring
        stays populated in ordinary (untraced) runs."""
        return self.enabled or self._flight.enabled

    @property
    def events(self):
        return list(self._events)

    def wall_time(self, when):
        """Map a perf_counter reading onto the wall clock via the
        start() anchor (cross-process correlation currency)."""
        return self._epoch_wall + (when - self._epoch)

    # -- recording ---------------------------------------------------------

    def _tid(self):
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            name = threading.current_thread().name
            with self._lock:
                tid = self._tids.get(ident)
                if tid is None:
                    tid = len(self._tids) + 1
                    self._tids[ident] = tid
                    self._tid_names[tid] = name
            self._append({
                "name": "thread_name", "ph": "M", "pid": self._pid,
                "tid": tid, "args": {"name": name}})
        return tid

    def tids_for(self, idents):
        """Map thread idents -> this tracer's track ids (idents never
        seen record no events, so they are simply absent)."""
        return {self._tids[i] for i in idents if i in self._tids}

    def request_track(self, key, label):
        """Allocate (or reuse) a dedicated track for one request leg
        (observe/requests.py).  Request-scoped spans cannot share the
        recording thread's track: one batch completes many requests
        whose queue spans overlap without nesting, and one hedged
        request's legs run concurrently — each leg gets its own lane,
        keyed by an arbitrary hashable (id, leg discriminator) and
        labeled with the request id so legs group visually."""
        key = ("req", key)
        tid = self._tids.get(key)
        if tid is None:
            with self._lock:
                tid = self._tids.get(key)
                if tid is None:
                    tid = len(self._tids) + 1
                    self._tids[key] = tid
                    self._tid_names[tid] = label
            self._append({
                "name": "thread_name", "ph": "M", "pid": self._pid,
                "tid": tid, "args": {"name": label}})
        return tid

    def _append(self, event):
        if len(self._events) >= self._max_events:
            self.dropped += 1
            return
        self._events.append(event)

    def _ts(self, when):
        return (when - self._epoch) * 1e6

    def complete(self, name, start, dur, cat="span", args=None,
                 tid=None):
        """Record a complete ("X") event from perf_counter timings —
        the primitive every instrumented timer calls, so the trace and
        the accumulated timers always report the SAME measurement.
        Always feeds the flight recorder's ring (compact tuple, no
        serialization) so post-mortem dumps work without ``--trace``.
        ``tid`` overrides the recording thread's track — request-
        scoped spans land on their :meth:`request_track` lane."""
        flt = self._flight
        if flt.enabled:
            flt.record("span", name, cat, self.wall_time(start), dur,
                       args)
        if not self.enabled:
            return
        event = {"name": name, "cat": cat, "ph": "X",
                 "ts": self._ts(start), "dur": dur * 1e6,
                 "pid": self._pid,
                 "tid": self._tid() if tid is None else tid}
        if args:
            event["args"] = args
        self._append(event)

    def span(self, name, cat="span", **args):
        """Context manager recording one complete event around a block."""
        if not self.enabled and not self._flight.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args or None)

    def traced(self, name=None, cat="span"):
        """Decorator form of :meth:`span` (label defaults to the
        function's qualified name)."""
        def decorate(fn):
            label = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                if not self.enabled and not self._flight.enabled:
                    return fn(*a, **kw)
                start = time.perf_counter()
                try:
                    return fn(*a, **kw)
                finally:
                    self.complete(label, start,
                                  time.perf_counter() - start, cat=cat)
            return wrapper
        return decorate

    def instant(self, name, cat="event", **args):
        """Record a point event (protocol messages, faults, rollbacks)."""
        flt = self._flight
        if flt.enabled:
            flt.record("instant", name, cat, args=args or None)
        if not self.enabled:
            return
        event = {"name": name, "cat": cat, "ph": "i", "s": "t",
                 "ts": self._ts(time.perf_counter()),
                 "pid": self._pid, "tid": self._tid()}
        if args:
            event["args"] = args
        self._append(event)

    def counter(self, name, value, cat="counter"):
        """Record a counter sample (renders as a filled track)."""
        flt = self._flight
        if flt.enabled:
            flt.record("counter", name, cat, args={"value": value})
        if not self.enabled:
            return
        self._append({"name": name, "cat": cat, "ph": "C",
                      "ts": self._ts(time.perf_counter()),
                      "pid": self._pid, "tid": self._tid(),
                      "args": {"value": value}})

    # -- cross-process shipping --------------------------------------------

    def take_chunk(self, max_events=4096, idents=None, extra=None):
        """Pop up to ``max_events`` recorded events into a bounded,
        self-describing chunk a slave can ship to its master
        (docs/observability.md, distributed tracing).

        ``idents`` (optional) restricts the chunk to events recorded by
        those thread idents — the in-process two-node tests use it to
        keep a shared tracer's master and slave events separable; real
        one-process-per-role deployments ship everything.  Thread-name
        metadata is carried as a ``threads`` map (the popped "M" events
        may have shipped in an earlier chunk).  Returns None when there
        is nothing to ship."""
        with self._lock:
            # the hot path appends WITHOUT this lock, so the buffer
            # object must never be rebound here: examine a fixed-length
            # prefix and splice it in place — concurrent appends land
            # past index n on the SAME list and survive untouched
            n = len(self._events)
            if not n:
                return None
            tids = None if idents is None else self.tids_for(idents)
            taken, kept = [], []
            for index in range(n):
                event = self._events[index]
                # thread metadata never ships (the chunk's ``threads``
                # map replaces it — popped "M" events would leave later
                # chunks nameless); scoped chunks also keep foreign
                # threads' events behind
                if (len(taken) < max_events and event["ph"] != "M"
                        and (tids is None or event["tid"] in tids)):
                    taken.append(event)
                else:
                    kept.append(event)
            self._events[:n] = kept
            if not taken:
                return None
            threads = {str(e["tid"]): self._tid_names.get(e["tid"], "")
                       for e in taken}
            chunk = {
                "schema": CHUNK_SCHEMA_VERSION,
                "pid": self._pid,
                "label": self.label,
                "wall_epoch": self._epoch_wall,
                "threads": threads,
                "events": taken,
            }
            if extra:
                chunk.update(extra)
            return chunk

    # -- output ------------------------------------------------------------

    def save(self, path):
        """Write ``{"traceEvents": [...]}`` atomically — the JSON
        object form Perfetto and chrome://tracing both load."""
        # bounded acquire: save() also runs from the launcher's fatal-
        # signal hook, which may interrupt the very thread holding the
        # lock (take_chunk/save) — a dying process must still get its
        # trace out (list() of the buffer is GIL-atomic regardless)
        locked = self._lock.acquire(timeout=2.0)
        try:
            doc = {"traceEvents": list(self._events),
                   "displayTimeUnit": "ms",
                   "otherData": {"tool": "veles_tpu.observe",
                                 "dropped_events": self.dropped,
                                 # merge anchors: wall time of ts=0 and
                                 # this process's identity, so a saved
                                 # per-process file can join a merged
                                 # cross-host timeline offline
                                 "wall_epoch": self._epoch_wall,
                                 "pid": self._pid,
                                 "label": self.label}}
        finally:
            if locked:
                self._lock.release()
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fout:
            json.dump(doc, fout)
        os.replace(tmp, path)
        return path


def validate_trace(doc):
    """Structural check of a loaded trace document; raises ValueError.

    Verifies the Perfetto-loadable shape (``traceEvents`` list, known
    phases, required fields per phase) and that the complete events on
    each thread track NEST — overlapping non-nested spans on one track
    mean a broken instrumentation site (e.g. a span closed on a
    different thread than it opened on).  Used by tests and available
    to external consumers of ``--trace`` output.
    """
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("trace must be {'traceEvents': [...]}")
    per_track = {}
    for i, event in enumerate(doc["traceEvents"]):
        if not isinstance(event, dict):
            raise ValueError("event %d is not an object" % i)
        ph = event.get("ph")
        if ph not in ("X", "M", "i", "C"):
            raise ValueError("event %d: unknown phase %r" % (i, ph))
        for key in ("name", "pid", "tid"):
            if key not in event:
                raise ValueError("event %d: missing %r" % (i, key))
        if ph == "X":
            ts, dur = event.get("ts"), event.get("dur")
            if not isinstance(ts, (int, float)) or \
                    not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    "event %d: complete event needs numeric ts/dur" % i)
            per_track.setdefault(
                (event["pid"], event["tid"]), []).append(event)
    epsilon = 1.0  # microsecond slack for float rounding
    for track, events in per_track.items():
        events.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for event in events:
            end = event["ts"] + event["dur"]
            while stack and stack[-1] <= event["ts"] + epsilon:
                stack.pop()
            if stack and end > stack[-1] + epsilon:
                raise ValueError(
                    "track %r: span %r [%f..%f] overlaps but does not "
                    "nest within its enclosing span (ends %f)" %
                    (track, event["name"], event["ts"], end, stack[-1]))
            stack.append(end)
    # request-span contract (observe/requests.py): every request-
    # scoped event carries its id, one track never mixes requests,
    # and segment spans ride under a serve.request parent
    for i, event in enumerate(doc["traceEvents"]):
        if event.get("cat") != "req" or event.get("ph") not in \
                ("X", "i"):
            continue
        trace_id = (event.get("args") or {}).get("trace")
        if not isinstance(trace_id, str) or not trace_id:
            raise ValueError(
                "event %d: request-scoped event %r has no args.trace "
                "id (orphan)" % (i, event.get("name")))
    for track, events in per_track.items():
        req_events = [e for e in events if e.get("cat") == "req"]
        if not req_events:
            continue
        ids = {(e.get("args") or {}).get("trace")
               for e in req_events}
        if len(ids) > 1:
            raise ValueError(
                "track %r: request track mixes trace ids %r" %
                (track, sorted(ids)))
        if any(e["name"].startswith("serve.req.")
               for e in req_events) and \
                not any(e["name"] == "serve.request"
                        for e in req_events):
            raise ValueError(
                "track %r: segment spans for trace %r without an "
                "enclosing serve.request span" %
                (track, next(iter(ids))))
    return doc


#: The process-wide tracer every subsystem instruments against.
tracer = SpanTracer()


def span(name, cat="span", **args):
    return tracer.span(name, cat=cat, **args)


def instant(name, cat="event", **args):
    return tracer.instant(name, cat=cat, **args)


def traced(name=None, cat="span"):
    return tracer.traced(name, cat=cat)
