"""Span tracer: Chrome trace-event JSON, viewable in Perfetto.

Records *complete* events ("ph": "X") with microsecond timestamps on a
``time.perf_counter`` base — the same clock the unit/pipeline timers
use, so a span's ``dur`` agrees with the accumulated timer it rides on.
Each thread gets its own track (a ``thread_name`` metadata event is
emitted on first sight), so the prefetch worker's fill/H2D spans render
on a separate lane from the graph thread's unit-run spans and the
overlap is visible directly.

Design rules:

- **zero overhead when disabled**: ``tracer.enabled`` is a plain bool;
  hot call sites guard on it (one attribute load) and every public
  method returns immediately when tracing is off.  ``span()`` returns
  a shared no-op context manager;
- **no locks on the hot path**: event dicts are appended to a plain
  list (``list.append`` is atomic under the GIL); the lock guards only
  start/save and first-sight thread registration;
- **bounded memory**: past ``max_events`` new events are counted as
  dropped instead of growing the buffer without bound.

The module-level :data:`tracer` singleton is the instance the whole
system instruments against; ``--trace PATH`` (launcher.py) starts it
and saves the file at run end.
"""

import functools
import json
import os
import threading
import time

__all__ = ["SpanTracer", "tracer", "span", "instant", "traced",
           "validate_trace"]


class _NullSpan(object):
    """Shared no-op context manager returned while tracing is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span(object):
    __slots__ = ("_tracer", "_name", "_cat", "_args", "_start")

    def __init__(self, owner, name, cat, args):
        self._tracer = owner
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tracer.complete(
            self._name, self._start, time.perf_counter() - self._start,
            cat=self._cat, args=self._args)
        return False


class SpanTracer(object):
    """Thread-safe trace-event recorder with a Perfetto-loadable dump."""

    def __init__(self, max_events=1000000):
        self.enabled = False
        self.dropped = 0
        self._max_events = max_events
        self._events = []
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()
        self._pid = os.getpid()
        self._tids = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Clear any previous events and begin recording."""
        with self._lock:
            self._events = []
            self._tids = {}
            self.dropped = 0
            self._epoch = time.perf_counter()
            self.enabled = True
        return self

    def stop(self):
        self.enabled = False
        return self

    @property
    def events(self):
        return list(self._events)

    # -- recording ---------------------------------------------------------

    def _tid(self):
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._lock:
                tid = self._tids.get(ident)
                if tid is None:
                    tid = len(self._tids) + 1
                    self._tids[ident] = tid
            self._append({
                "name": "thread_name", "ph": "M", "pid": self._pid,
                "tid": tid,
                "args": {"name": threading.current_thread().name}})
        return tid

    def _append(self, event):
        if len(self._events) >= self._max_events:
            self.dropped += 1
            return
        self._events.append(event)

    def _ts(self, when):
        return (when - self._epoch) * 1e6

    def complete(self, name, start, dur, cat="span", args=None):
        """Record a complete ("X") event from perf_counter timings —
        the primitive every instrumented timer calls, so the trace and
        the accumulated timers always report the SAME measurement."""
        if not self.enabled:
            return
        event = {"name": name, "cat": cat, "ph": "X",
                 "ts": self._ts(start), "dur": dur * 1e6,
                 "pid": self._pid, "tid": self._tid()}
        if args:
            event["args"] = args
        self._append(event)

    def span(self, name, cat="span", **args):
        """Context manager recording one complete event around a block."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args or None)

    def traced(self, name=None, cat="span"):
        """Decorator form of :meth:`span` (label defaults to the
        function's qualified name)."""
        def decorate(fn):
            label = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                if not self.enabled:
                    return fn(*a, **kw)
                start = time.perf_counter()
                try:
                    return fn(*a, **kw)
                finally:
                    self.complete(label, start,
                                  time.perf_counter() - start, cat=cat)
            return wrapper
        return decorate

    def instant(self, name, cat="event", **args):
        """Record a point event (protocol messages, faults, rollbacks)."""
        if not self.enabled:
            return
        event = {"name": name, "cat": cat, "ph": "i", "s": "t",
                 "ts": self._ts(time.perf_counter()),
                 "pid": self._pid, "tid": self._tid()}
        if args:
            event["args"] = args
        self._append(event)

    def counter(self, name, value, cat="counter"):
        """Record a counter sample (renders as a filled track)."""
        if not self.enabled:
            return
        self._append({"name": name, "cat": cat, "ph": "C",
                      "ts": self._ts(time.perf_counter()),
                      "pid": self._pid, "tid": self._tid(),
                      "args": {"value": value}})

    # -- output ------------------------------------------------------------

    def save(self, path):
        """Write ``{"traceEvents": [...]}`` atomically — the JSON
        object form Perfetto and chrome://tracing both load."""
        with self._lock:
            doc = {"traceEvents": list(self._events),
                   "displayTimeUnit": "ms",
                   "otherData": {"tool": "veles_tpu.observe",
                                 "dropped_events": self.dropped}}
        directory = os.path.dirname(os.path.abspath(path))
        os.makedirs(directory, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fout:
            json.dump(doc, fout)
        os.replace(tmp, path)
        return path


def validate_trace(doc):
    """Structural check of a loaded trace document; raises ValueError.

    Verifies the Perfetto-loadable shape (``traceEvents`` list, known
    phases, required fields per phase) and that the complete events on
    each thread track NEST — overlapping non-nested spans on one track
    mean a broken instrumentation site (e.g. a span closed on a
    different thread than it opened on).  Used by tests and available
    to external consumers of ``--trace`` output.
    """
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError("trace must be {'traceEvents': [...]}")
    per_track = {}
    for i, event in enumerate(doc["traceEvents"]):
        if not isinstance(event, dict):
            raise ValueError("event %d is not an object" % i)
        ph = event.get("ph")
        if ph not in ("X", "M", "i", "C"):
            raise ValueError("event %d: unknown phase %r" % (i, ph))
        for key in ("name", "pid", "tid"):
            if key not in event:
                raise ValueError("event %d: missing %r" % (i, key))
        if ph == "X":
            ts, dur = event.get("ts"), event.get("dur")
            if not isinstance(ts, (int, float)) or \
                    not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    "event %d: complete event needs numeric ts/dur" % i)
            per_track.setdefault(
                (event["pid"], event["tid"]), []).append(event)
    epsilon = 1.0  # microsecond slack for float rounding
    for track, events in per_track.items():
        events.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for event in events:
            end = event["ts"] + event["dur"]
            while stack and stack[-1] <= event["ts"] + epsilon:
                stack.pop()
            if stack and end > stack[-1] + epsilon:
                raise ValueError(
                    "track %r: span %r [%f..%f] overlaps but does not "
                    "nest within its enclosing span (ends %f)" %
                    (track, event["name"], event["ts"], end, stack[-1]))
            stack.append(end)
    return doc


#: The process-wide tracer every subsystem instruments against.
tracer = SpanTracer()


def span(name, cat="span", **args):
    return tracer.span(name, cat=cat, **args)


def instant(name, cat="event", **args):
    return tracer.instant(name, cat=cat, **args)


def traced(name=None, cat="span"):
    return tracer.traced(name, cat=cat)
