"""Profiler hooks and the periodic JSONL heartbeat.

Two run-scoped services on top of the tracer/registry:

- :class:`ProfilerHook` starts/stops ``jax.profiler`` around a
  configurable window of fused train steps (``VELES_PROFILE=dir``
  enables it from the environment, ``VELES_PROFILE_WINDOW=start:stop``
  picks the window, default 5:25 — past the compile so the trace shows
  steady state, short so the dump stays small).  The per-step call
  (:func:`profiler_step`) is a module-global None check when no hook
  is installed — the healthy path pays nothing;
- :class:`Heartbeat` writes one JSON line every ``interval`` seconds
  (``--metrics-interval N`` / ``--metrics-path PATH``): registry
  snapshot, health counters, epoch/metrics from the decision unit, and
  samples/sec throughput derived from the ``train.samples`` counter
  delta.  web_status.py surfaces the same health block in its status
  posts; bench.py and offline tools consume the file.
"""

import json
import math
import os
import threading
import time

from veles_tpu.observe.metrics import health_snapshot
from veles_tpu.observe.metrics import registry as _registry

__all__ = ["ProfilerHook", "install_profiler", "uninstall_profiler",
           "profiler_step", "Heartbeat", "validate_heartbeat",
           "HEARTBEAT_SCHEMA_VERSION", "HEARTBEAT_SCHEMAS"]

HEARTBEAT_SCHEMA_VERSION = 3

#: Schemas ``validate_heartbeat`` accepts: v2 files (pre-telemetry)
#: stay readable by ``observe summary``/``merge`` forever; v3 adds
#: the ``series`` rollup block and the ``alerts`` block.
HEARTBEAT_SCHEMAS = (2, 3)


class ProfilerHook(object):
    """Drive ``jax.profiler`` around a window of train steps."""

    def __init__(self, logdir, start_step=None, stop_step=None):
        if start_step is None or stop_step is None:
            env_start, env_stop = self._window_from_env()
            start_step = env_start if start_step is None else start_step
            stop_step = env_stop if stop_step is None else stop_step
        self.logdir = logdir
        self.start_step = max(0, int(start_step))
        self.stop_step = max(self.start_step + 1, int(stop_step))
        self.steps = 0
        self.state = "idle"  # -> "tracing" -> "done"

    @staticmethod
    def _window_from_env(environ=None):
        environ = environ if environ is not None else os.environ
        window = environ.get("VELES_PROFILE_WINDOW", "")
        try:
            start, stop = window.split(":", 1)
            return int(start), int(stop)
        except ValueError:
            return 5, 25

    @classmethod
    def from_env(cls, environ=None):
        """A hook when ``VELES_PROFILE`` names a log dir, else None."""
        environ = environ if environ is not None else os.environ
        logdir = environ.get("VELES_PROFILE", "")
        if not logdir:
            return None
        start, stop = cls._window_from_env(environ)
        return cls(logdir, start, stop)

    def step(self):
        """Account one train step; start/stop the profiler at the
        window edges.  Cheap outside the edges: one int compare."""
        self.steps += 1
        if self.state == "idle" and self.steps > self.start_step:
            self._start()
        elif self.state == "tracing" and self.steps > self.stop_step:
            self.stop()

    def _start(self):
        try:
            import jax
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
        except Exception:
            # a missing/old jax.profiler must never kill training;
            # "done" also stops the per-step retry storm
            self.state = "done"
            return
        self.state = "tracing"

    def stop(self):
        """Idempotent: stop tracing if the window is still open."""
        if self.state != "tracing":
            self.state = "done"
            return
        self.state = "done"
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception:
            pass


_hook = None
_hook_lock = threading.Lock()


def install_profiler(hook):
    """Make ``hook`` the process profiler (replacing and stopping any
    previous one)."""
    global _hook
    with _hook_lock:
        previous, _hook = _hook, hook
    if previous is not None:
        previous.stop()
    return hook


def uninstall_profiler():
    global _hook
    with _hook_lock:
        hook, _hook = _hook, None
    if hook is not None:
        hook.stop()
    return hook


def profiler_step():
    """Per-train-step tick (called by the fused trainer); a plain None
    check when no profiler is installed."""
    hook = _hook
    if hook is not None:
        hook.step()


# -- heartbeat ---------------------------------------------------------------

#: required keys -> allowed types of one heartbeat line.  Schema v2:
#: lines carry BOTH clocks — ``ts`` (wall, cross-host correlatable,
#: NTP-adjustable) and ``mono`` (monotonic, for in-process deltas that
#: must never go backwards) — plus the XLA ``compile`` block.
_HEARTBEAT_REQUIRED = {
    "kind": str, "schema": int, "ts": (int, float),
    "mono": (int, float), "elapsed_s": (int, float), "session": str,
    "counters": dict, "gauges": dict, "histograms": dict, "health": dict,
}


def _jsonsafe(value):
    """Recursively replace non-finite floats with None: a bare NaN
    token (json.dumps' allow_nan default) is not RFC-8259 JSON and
    breaks every non-Python consumer of the heartbeat file."""
    if isinstance(value, float):
        return value if math.isfinite(value) else None
    if isinstance(value, dict):
        return {key: _jsonsafe(item) for key, item in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonsafe(item) for item in value]
    return value


def validate_heartbeat(record):
    """Schema check for one parsed heartbeat line; raises ValueError.
    The contract tested by the observe smoke test and relied on by
    external consumers of ``--metrics-path`` files."""
    if not isinstance(record, dict):
        raise ValueError("heartbeat line is not an object")
    for key, types in _HEARTBEAT_REQUIRED.items():
        if key not in record:
            raise ValueError("heartbeat missing %r" % key)
        if not isinstance(record[key], types):
            raise ValueError("heartbeat %r has type %s" %
                             (key, type(record[key]).__name__))
    if record["kind"] != "heartbeat":
        raise ValueError("kind must be 'heartbeat'")
    if record["schema"] not in HEARTBEAT_SCHEMAS:
        raise ValueError("unknown heartbeat schema %r" % record["schema"])
    if record["schema"] >= 3:
        # v3: the telemetry-plane blocks are part of the contract
        for key in ("series", "alerts"):
            if not isinstance(record.get(key), dict):
                raise ValueError(
                    "schema 3 heartbeat needs a %r block" % key)
        if "schema" not in record["series"]:
            raise ValueError("series block lacks a schema")
    if "mfu_pct" in record and record["mfu_pct"] is not None and \
            not isinstance(record["mfu_pct"], (int, float)):
        raise ValueError("mfu_pct must be numeric or null")
    if "compile" in record and not isinstance(record["compile"], dict):
        raise ValueError("compile block must be an object")
    for name, hist in record["histograms"].items():
        if not isinstance(hist, dict) or "count" not in hist:
            raise ValueError("histogram %r lacks a count" % name)
    return record


class Heartbeat(object):
    """Append one status JSON line to ``path`` every ``interval`` s on
    a daemon thread; a final line is written at stop so even runs
    shorter than the interval leave a record."""

    def __init__(self, path, interval=5.0, workflow=None, registry=None):
        self.path = path
        self.interval = max(0.05, float(interval))
        self.workflow = workflow
        self.registry = registry if registry is not None else _registry
        self._stop = threading.Event()
        self._thread = None
        self._t0 = time.monotonic()
        self._last_sample = (self._t0, self._samples())

    def _samples(self):
        counter = self.registry.peek("train.samples")
        return counter.value if counter is not None else 0

    def line(self):
        """One heartbeat record (plain data, json-serializable)."""
        from veles_tpu import logger
        now = time.monotonic()
        # XLA introspection (docs/observability.md) refreshes FIRST so
        # the one snapshot below already carries this tick's recompile
        # counts, memory gauges and mfu — a recompile storm must show
        # on the line that observed it, not one interval late.  Gated
        # on runs that actually compiled something: a dummy/unit-test
        # heartbeat must not drag jax in.
        xla = None
        mfu = None
        if self.registry.peek("compile.count") is not None or \
                self.registry.peek("xla.step_flops") is not None:
            try:
                from veles_tpu.observe import xla_introspect as xla
                xla.poll_recompiles()
                xla.device_memory_gauges(self.registry)
                mfu = xla.mfu_snapshot(self.registry)
            except Exception:
                xla = None
        snap = self.registry.snapshot()
        record = {
            "kind": "heartbeat",
            "schema": HEARTBEAT_SCHEMA_VERSION,
            "ts": time.time(),
            "mono": now,
            "elapsed_s": round(now - self._t0, 3),
            "session": logger.session_id,
            "counters": snap["counters"],
            "gauges": snap["gauges"],
            "histograms": snap["histograms"],
            "health": health_snapshot(self.registry),
        }
        if xla is not None:
            record["compile"] = xla.compile_snapshot(self.registry)
            record["mfu_pct"] = mfu
        # the telemetry plane rides the heartbeat cadence: tick the
        # process-global series ring against the SAME wall stamp this
        # line carries, then embed the compact v3 blocks (the full
        # buckets ship over links, not the JSONL file)
        try:
            from veles_tpu.observe.alerts import alerts
            from veles_tpu.observe.timeseries import series
            series.maybe_tick(now=now, wall=record["ts"])
            if alerts.rules:
                # single-process alerting rides the heartbeat: the
                # same rules a fleet router sweeps over rollups run
                # here over the local ring (edge-triggered, so a
                # persisting breach costs one firing, not one per
                # heartbeat line)
                alerts.evaluate(series.buckets(last=32),
                                wall=record["ts"])
            record["series"] = series.heartbeat_block()
            record["alerts"] = alerts.snapshot(history=4)
        except Exception:
            record["series"] = {"schema": 0}
            record["alerts"] = {"schema": 0, "active": [],
                                "firing": [], "fired_total": 0,
                                "history": []}
        last_t, last_samples = self._last_sample
        samples = self._samples()
        if now > last_t:
            record["throughput_sps"] = round(
                (samples - last_samples) / (now - last_t), 3)
        self._last_sample = (now, samples)
        workflow = self.workflow
        if workflow is not None:
            record["workflow"] = type(workflow).__name__
            decision = getattr(workflow, "decision", None)
            if decision is not None:
                epoch = getattr(decision, "epoch_number", None)
                if epoch is not None:
                    record["epoch"] = int(epoch)
                record["metrics"] = getattr(
                    decision, "epoch_metrics", None)
        return record

    def write_line(self):
        directory = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(directory, exist_ok=True)
        record = _jsonsafe(self.line())
        with open(self.path, "a") as fout:
            fout.write(json.dumps(record, default=repr,
                                  allow_nan=False) + "\n")
        # the flight recorder keeps a condensed copy: a post-mortem
        # dump then shows throughput/health context around the failure
        from veles_tpu.observe.flight import flight
        if flight.enabled:
            flight.record(
                "heartbeat", "heartbeat", wall=record.get("ts"),
                args={key: record.get(key) for key in
                      ("elapsed_s", "throughput_sps", "epoch",
                       "health", "mfu_pct", "compile")
                      if record.get(key) is not None})

    def _loop(self):
        try:
            while not self._stop.wait(self.interval):
                try:
                    self.write_line()
                except OSError:
                    pass  # a full disk must not take training down
        finally:
            try:
                self.write_line()  # final state, even for short runs
            except OSError:
                pass

    def start(self):
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="heartbeat")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10)
