"""Metrics registry: counters, gauges, and windowed histograms.

The numeric side of the telemetry layer: step-time percentiles,
samples/sec throughput, skip/rollback/quarantine counts, queue depths.
Observing a value is a lock + a few attribute writes (sub-microsecond),
so instrumented hot paths stay hot; reading never blocks a writer for
longer than one observation.

Device-scalar rule (docs/observability.md): values that live on the
accelerator (skip counters, grad norms) enter the registry ONLY at the
existing lazy-metric sync points — the decision unit's class-end sync,
the snapshotter's rollback, the server's quarantine check — as the
plain Python numbers those paths already concretized.  The registry
itself never calls ``int()``/``float()`` on a device array, so it can
never add a host sync to the step path.
"""

import math
import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "registry", "percentiles", "health_snapshot",
           "snapshot_keys"]


def percentiles(samples, ps=(50, 95, 99)):
    """Nearest-rank percentiles of a sequence as ``{"p50": ...}``.

    Plain-Python so import-light callers (bench.py's slope spreads, the
    histogram snapshots) share ONE definition; on tiny sample sets the
    nearest-rank convention degrades gracefully (p95/p99 of 5 samples
    are both the max) instead of inventing interpolated values.
    """
    if not samples:
        return {}
    data = sorted(samples)
    n = len(data)
    return {"p%d" % p:
            data[max(0, min(n, int(math.ceil(p / 100.0 * n))) - 1)]
            for p in ps}


class Counter(object):
    """Monotonic counter (events, samples, protocol messages)."""

    __slots__ = ("name", "_lock", "_value")

    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge(object):
    """Last-value metric (queue depth, budget remaining, epoch)."""

    __slots__ = ("name", "_value")

    def __init__(self, name):
        self.name = name
        self._value = None

    def set(self, value):
        self._value = value

    @property
    def value(self):
        return self._value


class Histogram(object):
    """Windowed distribution: lifetime count/sum plus a ring buffer of
    the most recent ``window`` observations for percentile queries."""

    __slots__ = ("name", "_lock", "_window", "_buf", "_pos",
                 "count", "total", "min", "max")

    def __init__(self, name, window=1024):
        self.name = name
        self._lock = threading.Lock()
        self._window = max(1, int(window))
        self.reset()

    def reset(self):
        with self._lock:
            self._buf = []
            self._pos = 0
            self.count = 0
            self.total = 0.0
            self.min = None
            self.max = None

    def observe(self, value):
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value
            if len(self._buf) < self._window:
                self._buf.append(value)
            else:
                self._buf[self._pos] = value
                self._pos = (self._pos + 1) % self._window

    def window_values(self):
        with self._lock:
            return list(self._buf)

    def recent(self, n):
        """The last ``min(n, window)`` observations in CHRONOLOGICAL
        order — the timeseries bucketizer (observe/timeseries.py)
        digests exactly the values that arrived since its previous
        tick, which the count delta names and the ring still holds as
        long as the tick interval outpaces ``window`` observations."""
        if n <= 0:
            return []
        with self._lock:
            if len(self._buf) < self._window:
                buf = list(self._buf)
            else:
                buf = self._buf[self._pos:] + self._buf[:self._pos]
        return buf[-n:]

    def snapshot(self):
        """{"count","mean","min","max","p50","p95","p99"} — count/mean
        over the lifetime, percentiles over the recent window."""
        with self._lock:
            buf = list(self._buf)
            count, total = self.count, self.total
            lo, hi = self.min, self.max
        out = {"count": count,
               "mean": (total / count) if count else None,
               "min": lo, "max": hi}
        out.update(percentiles(buf))
        return out


class MetricsRegistry(object):
    """Named get-or-create store for the three metric kinds."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get(self, name, factory, kind):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = self._metrics[name] = factory()
            elif not isinstance(metric, kind):
                raise TypeError(
                    "metric %r already registered as %s" %
                    (name, type(metric).__name__))
            return metric

    def counter(self, name):
        return self._get(name, lambda: Counter(name), Counter)

    def gauge(self, name):
        return self._get(name, lambda: Gauge(name), Gauge)

    def histogram(self, name, window=1024):
        return self._get(
            name, lambda: Histogram(name, window=window), Histogram)

    def peek(self, name):
        """The metric if it was ever registered, else None — readers
        (health_snapshot, dashboards) must not create empty metrics."""
        return self._metrics.get(name)

    def items(self):
        """Stable (name, metric) pairs of the LIVE objects — the
        timeseries bucketizer needs them (histogram count deltas +
        ``recent``), not the plain-data snapshot."""
        with self._lock:
            return sorted(self._metrics.items())

    def snapshot(self):
        """Plain-data view: {"counters": {...}, "gauges": {...},
        "histograms": {name: {count, mean, p50, ...}}}."""
        with self._lock:
            metrics = dict(self._metrics)
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, metric in sorted(metrics.items()):
            if isinstance(metric, Counter):
                out["counters"][name] = metric.value
            elif isinstance(metric, Gauge):
                if metric.value is not None:
                    out["gauges"][name] = metric.value
            else:
                out["histograms"][name] = metric.snapshot()
        return out

    def reset(self):
        """Drop every metric (tests / bench A-B legs start clean)."""
        with self._lock:
            self._metrics.clear()


#: The process-wide registry every subsystem publishes into.
registry = MetricsRegistry()

#: Health keys surfaced to dashboards: registry name -> short name.
_HEALTH_KEYS = (
    ("health.skip_count", "skip_count"),
    ("health.consecutive_skips", "consecutive_skips"),
    ("health.rollbacks_remaining", "rollbacks_remaining"),
    ("health.rollbacks", "rollbacks"),
    ("server.blacklist_size", "blacklist_size"),
    ("server.quarantined", "quarantined"),
    # elastic-fleet state (veles_tpu/elastic.py): membership epoch and
    # live fleet size ride heartbeats so a post-mortem can line up
    # divergence/skip events against membership changes; the full
    # fleet block (speculation + exactly-once accounting) is
    # elastic.fleet_snapshot() on the dashboard
    ("elastic.membership_epoch", "membership_epoch"),
    ("elastic.fleet_live", "fleet_live"),
    ("elastic.speculative_inflight", "speculative_inflight"),
    # multi-replica serving (veles_tpu/serve/router.py): replica count,
    # aggregate queue depth and hot-reload count ride heartbeats so a
    # post-mortem can line up latency cliffs against reloads/cascades;
    # the full per-replica block is serve_snapshot() on the dashboard
    ("serve.replicas", "serve_replicas"),
    ("serve.queue_depth", "serve_queue_depth"),
    ("serve.reloads", "serve_reloads"),
    # train-to-serve freshness loop (veles_tpu/serve/freshness.py):
    # publish/candidate/promotion/rollback/poison accounting rides
    # heartbeats so a post-mortem can line up a latency cliff or a
    # quality regression against the cutover that shipped it
    ("serve.freshness.published", "freshness_published"),
    ("serve.freshness.candidates", "freshness_candidates"),
    ("serve.freshness.promotions", "freshness_promotions"),
    ("serve.freshness.rollbacks", "freshness_rollbacks"),
    ("serve.freshness.poisoned_rejected", "freshness_poisoned"),
    # multi-host serve tier (veles_tpu/serve/fleet.py): host
    # membership and the hedging/exactly-once accounting ride
    # heartbeats so a post-mortem can line up a p99 cliff against the
    # host loss (or the hedge storm) that caused it; the full
    # per-host block is FleetRouter.snapshot() on the dashboard
    ("serve.fleet.hosts_live", "fleet_hosts_live"),
    ("serve.fleet.membership_epoch", "fleet_membership_epoch"),
    ("serve.fleet.requeues", "fleet_requeues"),
    ("serve.hedge.fired", "hedges_fired"),
    ("serve.hedge.wins", "hedge_wins"),
    ("serve.hedge.duplicates_dropped", "hedge_duplicates_dropped"),
    # multi-tenant QoS (veles_tpu/serve/qos.py): per-class served/shed
    # accounting and the hedge-budget exhaustion count ride heartbeats
    # so a post-mortem can see WHO an overload was shed onto — the
    # contract is all sheds land on best_effort/batch before a single
    # interactive request is touched; the full per-class block (with
    # latency percentiles) is serve_snapshot()["tenants"]
    ("serve.hedge.budget_exhausted", "hedge_budget_exhausted"),
    ("serve.tenant.interactive.requests", "tenant_interactive_requests"),
    ("serve.tenant.interactive.shed", "tenant_interactive_shed"),
    ("serve.tenant.batch.requests", "tenant_batch_requests"),
    ("serve.tenant.batch.shed", "tenant_batch_shed"),
    ("serve.tenant.best_effort.requests", "tenant_best_effort_requests"),
    ("serve.tenant.best_effort.shed", "tenant_best_effort_shed"),
    # request-scoped tracing (observe/requests.py): span-sampled and
    # tail-exemplar volume ride heartbeats so a p99 cliff can be lined
    # up against the request timelines captured for it; the full
    # per-segment latency block is serve_snapshot()["segments"]
    ("serve.reqtrace.sampled", "reqtrace_sampled"),
    ("serve.reqtrace.exemplars", "reqtrace_exemplars"),
    # fleet canary (veles_tpu/serve/freshness.py FleetCanaryController):
    # host-sliced mirror volume and promote/rollback outcomes
    ("serve.fleet.canary.mirrors", "fleet_canary_mirrors"),
    ("serve.fleet.canary.promotions", "fleet_canary_promotions"),
    ("serve.fleet.canary.rollbacks", "fleet_canary_rollbacks"),
    # XLA introspection (observe/xla_introspect.py): live achieved-MFU
    # and compile accounting ride the same health surface
    ("xla.mfu_pct", "mfu_pct"),
    # backward attribution (docs/kernels.md): the fwd/bwd split next
    # to the whole-step MFU, refreshed by the same mfu_snapshot tick
    ("bwd.mfu_pct", "bwd_mfu_pct"),
    ("bwd.step_ms", "bwd_step_ms"),
    ("compile.count", "compiles"),
    ("compile.recompiles", "recompiles"),
    # schedule autotuner (veles_tpu/tune/): cache traffic + candidate
    # evaluations ride heartbeats so a tuning run (or a cold cache on
    # a fresh pod) is visible in the same post-mortem surface; the
    # per-generation detail is the tune.generation trace spans
    ("tune.cache_hits", "tune_cache_hits"),
    ("tune.cache_misses", "tune_cache_misses"),
    ("tune.evals", "tune_evals"),
    # fleet schedule bank receipts: publishes (trainer), merges picked
    # up (serve/CLI), entries adopted across all merges
    ("tune.bank_published", "tune_bank_published"),
    ("tune.bank_merged", "tune_bank_merged"),
    ("tune.bank_entries", "tune_bank_entries"),
    # int8 quantized serving (veles_tpu/quant/, docs/serving.md
    # "Quantized ladder"): whether this process serves a quantized
    # engine, and the calibration clip fraction — a clip fraction
    # drifting up between calibrations means the activation
    # distribution moved and the published scales are stale
    ("serve.quantized", "serve_quantized"),
    ("serve.quant.clip_fraction", "quant_clip_fraction"),
    # elastic device mesh (parallel.mesh.MeshManager, docs/
    # distributed.md "Elastic mesh contract"): current mesh size and
    # epoch, lifetime reshard count, and cumulative bytes of train
    # state moved — bytes_moved growing faster than reshards * the
    # changed-owner fraction means ownership is churning more than the
    # membership changes justify
    ("mesh.size", "mesh_size"),
    ("mesh.epoch", "mesh_epoch"),
    ("mesh.reshards", "mesh_reshards"),
    ("mesh.bytes_moved", "mesh_bytes_moved"),
    # fleet telemetry plane (observe/timeseries.py + alerts.py):
    # alert volume rides heartbeats so a post-mortem can line a
    # latency cliff up against the burn-rate firing that announced
    # it; the full alert-history ring is alerts.snapshot() on
    # /healthz and the dashboard
    ("alerts.fired", "alerts_fired"),
    ("alerts.active", "alerts_active"),
    ("telemetry.buckets", "telemetry_buckets"),
    ("telemetry.chunks_shipped", "telemetry_chunks_shipped"),
)


def snapshot_keys(keys, reg=None):
    """Flatten (registry name -> short name) pairs into a plain dict
    of published values.  Metrics never registered (peek keeps readers
    from creating empties) or still None are omitted — the shared
    backbone of health_snapshot and elastic.fleet_snapshot."""
    reg = reg if reg is not None else registry
    out = {}
    for name, short in keys:
        metric = reg.peek(name)
        if metric is not None and metric.value is not None:
            out[short] = metric.value
    return out


def health_snapshot(reg=None):
    """The PR-3 numerics-health counters as a flat dict for the
    web-status posts and the heartbeat line: skip counts published by
    the decision unit at its class-end sync, rollback budget remaining
    by the snapshotter, blacklist/quarantine sizes by the server.
    Only counters that were actually published appear."""
    return snapshot_keys(_HEALTH_KEYS, reg)
