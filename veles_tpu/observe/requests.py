"""Request-scoped serve-tier tracing: ids, timelines, tail exemplars.

The serve tier (docs/serving.md) reports aggregate histograms; when
p99 spikes they cannot say WHERE the tail lives — admission, batching
delay, H2D staging, device compute, or the wire.  This module is the
per-request substrate (docs/observability.md "Request tracing"):

- **Trace ids.**  A request id is a short plain string minted at the
  front door (``mint_trace_id``) or supplied by the client (HTTP
  ``X-Trace-Id`` / body field, binary-transport hello default +
  per-frame override).  Ids cross the wire as bounded JSON strings —
  ``normalize_trace_id`` enforces charset/length so the serve port's
  never-unpickle trust boundary is unchanged.
- **Segment marks.**  Serve components stamp cheap ``perf_counter``
  marks on their existing request objects as ``(segment, start,
  dur)`` tuples — the canonical taxonomy is :data:`SEGMENTS`.  Marks
  are on for EVERY request while :data:`enabled` (the
  ``VELES_REQTRACE=0`` kill switch exists for the bench.py
  ``trace_overhead`` A/B), because tail exemplars need the timeline
  of requests that only turn out slow at completion.
- **Sampled span emission.**  Full request-track spans go to the
  :mod:`veles_tpu.observe.trace` tracer only for *sampled* requests.
  Sampling is DETERMINISTIC in the id (crc32 hash, no RNG) so the two
  legs of one hedged request — on two hosts, two processes — make the
  same keep/drop decision and stitch under one id in the merged
  timeline (observe/merge.py).
- **Tail exemplars.**  Every non-shadow request past its class SLO
  budget (serve/qos.py) or above the rolling p99 keeps its complete
  segment timeline in a bounded ring (:class:`ExemplarRing`), dumped
  with the flight recorder on ``serve.slo_violation`` so a violation
  always carries the offending request's breakdown.
- **Critical-path analyzer.**  ``python -m veles_tpu.observe
  requests trace.json host0.json ... [--offset label=secs]`` — a
  per-segment p50/p99 table, dominant-segment tail attribution, and
  hedge win/loss + requeue accounting over saved traces, flight
  dumps, and merged documents, reusing merge.py's offset-corrected
  timeline so cross-host legs land on one clock.

Stdlib-only and import-light, like the rest of the observe layer.
"""

import collections
import itertools
import json
import os
import re
import sys
import threading
import time
import zlib

from veles_tpu.observe.metrics import registry as _registry

__all__ = [
    "SEGMENTS", "REQUEST_SPAN", "SEGMENT_PREFIX", "LEG_SPAN",
    "enabled", "sample_rate", "mint_trace_id", "normalize_trace_id",
    "sampled", "timeline", "emit_spans", "ExemplarRing", "exemplars",
    "extract_requests", "analyze", "analyze_files", "render_requests",
]

# Canonical segment taxonomy (docs/observability.md).  admit: front-
# door admission (quota wait, chaos, decode gating); queue: enqueue ->
# batch assembly start; assemble: gather/pad rows into the staging
# buffer; h2d: host->device transfer; device: compiled dispatch;
# d2h: result sync back to host; wire_rx/wire_tx: transport frame
# decode/reply.  "leg" is reserved for fleet hedge-leg spans.
SEGMENTS = ("admit", "queue", "assemble", "h2d", "device", "d2h",
            "wire_rx", "wire_tx")

REQUEST_SPAN = "serve.request"
SEGMENT_PREFIX = "serve.req."
LEG_SPAN = SEGMENT_PREFIX + "leg"

_TRUTHY = ("1", "true", "on", "yes")

# Kill switch for the whole per-request path: marks, exemplars, span
# emission.  bench.py trace_overhead flips this module attribute for
# its stamps-on vs fully-off A/B.
enabled = os.environ.get("VELES_REQTRACE", "1").strip().lower() \
    in _TRUTHY

# Span-emission sampling rate in [0, 1]; marks/exemplars ignore it.
sample_rate = float(os.environ.get("VELES_REQTRACE_SAMPLE", "1.0"))

_ID_RE = re.compile(r"[A-Za-z0-9_.:-]{1,64}\Z")
_ids = itertools.count(1)
_ID_PREFIX = "%08x" % (zlib.crc32(
    ("%d.%.9f" % (os.getpid(), time.time())).encode()) & 0xffffffff)


def mint_trace_id():
    """Cheap process-unique id: <boot-hash>-<counter>.  A few hundred
    ns — safe to mint per request on the serve hot path."""
    return "%s-%x" % (_ID_PREFIX, next(_ids))


def normalize_trace_id(value):
    """Validate an id that crossed a trust boundary (wire frame, HTTP
    header).  Returns the id or None; never raises.  Plain bounded
    string only — the serve port never unpickles, and trace ids do
    not change that."""
    if not isinstance(value, str):
        return None
    value = value.strip()
    if _ID_RE.fullmatch(value) is None:
        return None
    return value


def sampled(trace_id, rate=None):
    """Deterministic keep/drop for span emission: both hedge legs of
    one request hash the same id, so they sample together."""
    rate = sample_rate if rate is None else rate
    if not trace_id or rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    bucket = zlib.crc32(trace_id.encode("utf-8", "replace")) & 0xffff
    return bucket < int(rate * 65536.0)


def timeline(marks, t0):
    """Marks [(segment, start_perf, dur_s)] -> plain-data timeline
    with offsets relative to the request's arrival t0."""
    return [{"seg": name, "start_s": round(start - t0, 6),
             "dur_s": round(max(0.0, dur), 6)}
            for name, start, dur in marks]


def emit_spans(tr, trace_id, start, end, marks, args=None):
    """Emit one request's timeline as spans on a dedicated request
    track: a ``serve.request`` parent covering [start, end] plus one
    ``serve.req.<segment>`` child per mark.  Each leg gets its OWN
    track (keyed by (id, start)) so concurrent hedge legs in one
    process never overlap-without-nesting on a shared lane; the track
    label repeats the id, which is how legs visually group."""
    tid = tr.request_track((trace_id, start), "req:%s" % trace_id)
    _registry.counter("serve.reqtrace.sampled").inc()
    top = {"trace": trace_id}
    if args:
        top.update(args)
    tr.complete(REQUEST_SPAN, start, max(0.0, end - start),
                cat="req", args=top, tid=tid)
    for name, seg_start, dur in marks:
        tr.complete(SEGMENT_PREFIX + name, seg_start, max(0.0, dur),
                    cat="req", args={"trace": trace_id}, tid=tid)


class ExemplarRing:
    """Bounded ring of complete segment timelines for tail requests.

    A request is kept when it exceeds its class SLO budget (the
    caller passes ``budget_s`` from serve/qos.py) or lands strictly
    above the rolling p99 of recent latencies.  Shadow/mirror traffic is
    excluded — canary mirrors are tagged but never exemplars.  The
    ring is dumped with the flight recorder on ``serve.slo_violation``
    so a violation always carries a breakdown."""

    def __init__(self, capacity=None, window=256, min_samples=32):
        if capacity is None:
            capacity = int(os.environ.get(
                "VELES_REQTRACE_EXEMPLARS", "64"))
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=max(1, int(capacity)))
        self._window = collections.deque(maxlen=int(window))
        self._min_samples = int(min_samples)
        self._p99 = None
        self._notes = 0
        self.seen = 0
        self.kept = 0

    @property
    def capacity(self):
        return self._ring.maxlen

    def rolling_p99(self):
        with self._lock:
            return self._p99

    def note(self, trace, latency_s, marks=(), t0=0.0, slo_class=None,
             budget_s=None, kind="host", shadow=False, extra=None):
        """Consider one completed request; returns True if kept."""
        if shadow:
            return False
        with self._lock:
            self.seen += 1
            self._window.append(latency_s)
            self._notes += 1
            # nearest-rank p99 over the window, refreshed every 32
            # notes — a sort of <=256 floats, off by default cadence
            if (self._p99 is None or self._notes % 32 == 0) and \
                    len(self._window) >= self._min_samples:
                ranked = sorted(self._window)
                self._p99 = ranked[min(len(ranked) - 1,
                                       int(0.99 * len(ranked)))]
            over_budget = budget_s is not None and latency_s > budget_s
            # strictly ABOVE the rolling p99: a uniform-latency steady
            # state ties everything at p99 and ">=" would keep (and pay
            # the timeline build for) every single request
            over_p99 = self._p99 is not None and latency_s > self._p99
            if not (over_budget or over_p99):
                return False
            entry = {
                "trace": trace,
                "class": slo_class,
                "kind": kind,
                "latency_s": round(latency_s, 6),
                "over": "budget" if over_budget else "p99",
                "budget_s": budget_s,
                "ts": time.time(),
                "timeline": timeline(marks, t0),
            }
            if extra:
                entry.update(extra)
            self._ring.append(entry)
            self.kept += 1
        _registry.counter("serve.reqtrace.exemplars").inc()
        return True

    def snapshot(self):
        with self._lock:
            return {"capacity": self._ring.maxlen, "seen": self.seen,
                    "kept": self.kept,
                    "rolling_p99_s": self._p99,
                    "entries": list(self._ring)}

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._window.clear()
            self._p99 = None
            self._notes = 0
            self.seen = 0
            self.kept = 0

    def dump(self, reason="serve.slo_violation", path=None):
        """Flight-recorder dump carrying the exemplar timelines.
        Never raises (flight.dump's contract)."""
        from veles_tpu.observe.flight import flight
        return flight.dump(reason, path=path,
                           extra={"exemplars": self.snapshot()})


exemplars = ExemplarRing()


# ---------------------------------------------------------------- #
# critical-path analyzer                                           #
# ---------------------------------------------------------------- #

_HEDGE_FIRED = "serve.hedge.fired"
_HEDGE_WIN = "serve.hedge.win"
_REQUEUE = "serve.fleet.requeue"


def _new_record():
    return {"segments": {}, "legs": [], "class": None, "hedges": 0,
            "requeues": 0, "total_s": 0.0, "spans": 0,
            "exemplar": False}


def _new_counts():
    return {"hedge_fired": 0, "hedge_wins": 0, "requeues": 0,
            "exemplars": 0}


def _fold_span(records, trace, name, dur_s, args, start_s=None):
    rec = records.setdefault(trace, _new_record())
    rec["spans"] += 1
    if name == REQUEST_SPAN:
        rec["total_s"] = max(rec["total_s"], dur_s)
        rec["class"] = args.get("slo_class") or rec["class"]
        for key in ("hedges", "requeues"):
            try:
                rec[key] = max(rec[key], int(args.get(key) or 0))
            except (TypeError, ValueError):
                pass
        if args.get("tier") == "host" or args.get("host"):
            rec["legs"].append({"host": args.get("host"),
                                "start_s": start_s, "dur_s": dur_s})
    elif name == LEG_SPAN:
        rec["legs"].append({"host": args.get("host"),
                            "start_s": start_s, "dur_s": dur_s,
                            "hedge": bool(args.get("hedge"))})
    elif name.startswith(SEGMENT_PREFIX):
        seg = name[len(SEGMENT_PREFIX):]
        rec["segments"].setdefault(seg, []).append(dur_s)


def _extract_trace(doc, records, counts):
    for event in doc.get("traceEvents", ()):
        ph = event.get("ph")
        args = event.get("args") or {}
        if ph == "X":
            trace = args.get("trace")
            if not trace:
                continue
            _fold_span(records, trace, event.get("name", ""),
                       float(event.get("dur") or 0.0) / 1e6, args,
                       start_s=float(event.get("ts") or 0.0) / 1e6)
        elif ph == "i":
            name = event.get("name")
            if name == _HEDGE_FIRED:
                counts["hedge_fired"] += 1
            elif name == _HEDGE_WIN:
                counts["hedge_wins"] += 1
            elif name == _REQUEUE:
                counts["requeues"] += 1


def _extract_flight(doc, records, counts):
    for event in doc.get("events", ()):
        kind = event.get("kind")
        args = event.get("args") or {}
        if kind == "span" and args.get("trace"):
            _fold_span(records, args["trace"], event.get("name", ""),
                       float(event.get("dur_s") or 0.0), args,
                       start_s=event.get("mono"))
        elif kind == "instant":
            name = event.get("name")
            if name == _HEDGE_FIRED:
                counts["hedge_fired"] += 1
            elif name == _HEDGE_WIN:
                counts["hedge_wins"] += 1
            elif name == _REQUEUE:
                counts["requeues"] += 1
    block = doc.get("exemplars") or {}
    for index, entry in enumerate(block.get("entries", ())):
        counts["exemplars"] += 1
        trace = entry.get("trace") or "untraced-%d" % index
        rec = records.setdefault(trace, _new_record())
        rec["exemplar"] = True
        rec["class"] = entry.get("class") or rec["class"]
        rec["total_s"] = max(rec["total_s"],
                             float(entry.get("latency_s") or 0.0))
        for item in entry.get("timeline", ()):
            seg = item.get("seg")
            if not seg or seg == "leg":
                continue
            rec["segments"].setdefault(seg, []).append(
                float(item.get("dur_s") or 0.0))


def extract_requests(doc, records=None, counts=None):
    """Fold one document — saved trace, merged trace, or flight dump
    — into per-trace-id request records.  Pass the same ``records``/
    ``counts`` across calls to accumulate over many files."""
    records = {} if records is None else records
    counts = _new_counts() if counts is None else counts
    if doc.get("kind") == "flight":
        _extract_flight(doc, records, counts)
    else:
        _extract_trace(doc, records, counts)
    return records, counts


def _request_total(rec):
    if rec["total_s"] > 0.0:
        return rec["total_s"]
    return sum(sum(durs) for durs in rec["segments"].values())


def _dominant_segment(rec):
    best, best_dur = None, -1.0
    for seg, durs in rec["segments"].items():
        total = sum(durs)
        if total > best_dur:
            best, best_dur = seg, total
    return best


def analyze(records, counts, top=5):
    """Records -> the critical-path report: per-segment p50/p99,
    dominant-segment tail attribution, hedge/requeue accounting."""
    from veles_tpu.observe.metrics import percentiles
    seg_durs = {}
    totals = []
    classes = {}
    legs = 0
    for rec in records.values():
        totals.append(_request_total(rec))
        legs += len(rec["legs"])
        if rec["class"]:
            classes[rec["class"]] = classes.get(rec["class"], 0) + 1
        for seg, durs in rec["segments"].items():
            seg_durs.setdefault(seg, []).extend(durs)
    segments = {}
    for seg, durs in seg_durs.items():
        pct = percentiles(durs, ps=(50, 99))
        segments[seg] = {
            "count": len(durs),
            "p50_ms": round(pct.get("p50", 0.0) * 1e3, 3),
            "p99_ms": round(pct.get("p99", 0.0) * 1e3, 3),
            "max_ms": round(max(durs) * 1e3, 3) if durs else 0.0,
        }
    tail = {"count": 0, "threshold_ms": None, "dominant": {},
            "worst": None}
    if totals:
        ranked = sorted(totals)
        threshold = ranked[min(len(ranked) - 1,
                               int(0.99 * len(ranked)))]
        tail["threshold_ms"] = round(threshold * 1e3, 3)
        worst_total = -1.0
        for trace, rec in records.items():
            total = _request_total(rec)
            if total < threshold:
                continue
            tail["count"] += 1
            dom = _dominant_segment(rec)
            if dom:
                tail["dominant"][dom] = tail["dominant"].get(dom, 0) + 1
            if total > worst_total:
                worst_total = total
                tail["worst"] = {
                    "trace": trace,
                    "latency_ms": round(total * 1e3, 3),
                    "dominant": dom,
                    "legs": len(rec["legs"]),
                    "class": rec["class"],
                }
    fired = counts["hedge_fired"]
    wins = counts["hedge_wins"]
    requeues = max(counts["requeues"],
                   sum(r["requeues"] for r in records.values()))
    hedged = sum(1 for r in records.values() if r["hedges"])
    report = {
        "kind": "requests",
        "requests": len(records),
        "legs": legs,
        "classes": classes,
        "segments": segments,
        "tail": tail,
        "hedge": {"fired": max(fired, sum(
            r["hedges"] for r in records.values())),
            "wins": wins, "losses": max(0, fired - wins),
            "hedged_requests": hedged},
        "requeues": requeues,
        "exemplars": counts["exemplars"],
        "top": top,
    }
    return report


def analyze_files(paths, offsets=None, top=5):
    """Load a mix of trace files and flight dumps; trace files are
    stitched through merge.py first (offset-corrected onto one clock,
    first file is the reference) so one hedged request's legs on two
    hosts fold into one record under its id."""
    from veles_tpu.observe import merge
    offsets = offsets or {}
    parts = []
    flight_docs = []
    labels = []
    for path in paths:
        with open(path) as fin:
            doc = json.load(fin)
        base = os.path.basename(path)
        if doc.get("kind") == "flight":
            flight_docs.append(doc)
            labels.append(base)
            continue
        label = (doc.get("otherData") or {}).get("label") or base
        offset = offsets.get(label, offsets.get(base, 0.0))
        parts.append(merge.part_from_doc(doc, label=label,
                                         offset_s=offset))
        labels.append(label)
    records, counts = {}, _new_counts()
    if parts:
        merged = merge.merge_parts(parts)
        extract_requests(merged, records, counts)
    for doc in flight_docs:
        extract_requests(doc, records, counts)
    report = analyze(records, counts, top=top)
    report["files"] = labels
    return report


def render_requests(report, out=None):
    """Human-readable rendering of :func:`analyze`'s report — the
    ``observe requests`` CLI output."""
    out = out if out is not None else sys.stdout
    print("request digest: %d requests, %d legs, %d exemplars" % (
        report["requests"], report["legs"], report["exemplars"]),
        file=out)
    if report.get("classes"):
        print("  classes: %s" % ", ".join(
            "%s x%d" % (name, count) for name, count in
            sorted(report["classes"].items())), file=out)
    if report["segments"]:
        print("  segment            count     p50 ms     p99 ms     "
              "max ms", file=out)
        known = [s for s in SEGMENTS if s in report["segments"]]
        extra = sorted(set(report["segments"]) - set(known))
        for seg in known + extra:
            row = report["segments"][seg]
            print("  %-16s %7d %10.3f %10.3f %10.3f" % (
                seg, row["count"], row["p50_ms"], row["p99_ms"],
                row["max_ms"]), file=out)
    tail = report["tail"]
    if tail["count"]:
        dom = ", ".join("%s x%d" % (seg, count) for seg, count in
                        sorted(tail["dominant"].items(),
                               key=lambda kv: -kv[1]))
        print("  tail (>= %.3f ms): %d requests; dominant: %s" % (
            tail["threshold_ms"], tail["count"], dom or "n/a"),
            file=out)
        worst = tail["worst"]
        if worst:
            print("    worst: %s  %.3f ms  dominant=%s  legs=%d" % (
                worst["trace"], worst["latency_ms"],
                worst["dominant"], worst["legs"]), file=out)
    hedge = report["hedge"]
    print("  hedges: fired %d, wins %d, losses %d "
          "(%d hedged requests); requeues: %d" % (
              hedge["fired"], hedge["wins"], hedge["losses"],
              hedge["hedged_requests"], report["requeues"]), file=out)
