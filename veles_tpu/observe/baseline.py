"""The perf-regression sentinel: bench records vs a committed baseline.

The third piece of the fleet telemetry plane
(docs/observability.md "Fleet telemetry"): the growing pile of
BENCH_*.json receipts finally compared run-over-run.  A committed
``PERF_BASELINE.json`` pins per-metric expectations — value,
direction, tolerance — and :func:`gate` compares a run's compact
bench record (bench.py's machine-readable last line) plus
heartbeat-derived steady-state rates against it, using the
``tune/measure.py`` filter-passes discipline (drop jitter-dominated
samples, never clamp).  ``bench.py --gate`` and ``observe regress``
front it; a failure names the regressed metric and, when a request
trace or flight dump is on hand, the dominant segment from the
critical-path analyzer (observe/requests.py).

Baseline format (``PERF_BASELINE.json``)::

    {"schema": 1, "source": "BENCH_r05.json",
     "metrics": {"bf16_tflops": {"value": 118.48,
                                 "direction": "higher",
                                 "tolerance_pct": 10.0}, ...}}

``direction`` names which way is BETTER; a metric regresses when it
moves the other way by more than ``tolerance_pct``.  A metric in the
baseline but absent from the run is reported ``missing`` (the run
did not cover it) and does not fail the gate; a MISSING BASELINE
passes the gate with status ``no_baseline`` — the sentinel cannot
regress against nothing, and first runs must not be red.
"""

import json
import math
import os

__all__ = ["BASELINE_SCHEMA_VERSION", "DEFAULT_BASELINE",
           "load_baseline", "steady_state_rates", "compare", "gate",
           "dominant_segment", "render_report"]

BASELINE_SCHEMA_VERSION = 1

#: Committed at the repo root; override with $VELES_PERF_BASELINE or
#: an explicit path argument.
DEFAULT_BASELINE = "PERF_BASELINE.json"


def _default_path():
    env = os.environ.get("VELES_PERF_BASELINE")
    if env:
        return env
    if os.path.exists(DEFAULT_BASELINE):
        return DEFAULT_BASELINE
    # fall back to the repo root the package sits in (bench runs from
    # arbitrary cwds)
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(root, DEFAULT_BASELINE)


def load_baseline(path=None):
    """The parsed baseline, or ``None`` when there is none to hold a
    run against (missing file, unreadable JSON, wrong shape)."""
    path = path or _default_path()
    try:
        with open(path) as fh:
            base = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(base, dict) or \
            not isinstance(base.get("metrics"), dict):
        return None
    base.setdefault("schema", BASELINE_SCHEMA_VERSION)
    base["path"] = path
    return base


def steady_state_rates(buckets, names=None):
    """Steady-state per-second rates from telemetry buckets, one per
    counter, under the measure.py discipline: per-bucket rate samples
    filtered through ``filter_passes`` (a zero-rate bucket during
    warmup or drain measures the weather, not the program) and
    published as ``positive_majority_median`` — ``None``-valued
    metrics (no positive majority) are omitted."""
    from veles_tpu.tune.measure import (filter_passes,
                                        positive_majority_median)
    samples = {}
    for bucket in buckets:
        for name, entry in (bucket.get("counters") or {}).items():
            rate = (entry or {}).get("rate")
            if isinstance(rate, (int, float)) and \
                    not isinstance(rate, bool) and math.isfinite(rate):
                samples.setdefault(name, []).append(float(rate))
    out = {}
    for name, rates in samples.items():
        if names is not None and name not in names:
            continue
        med = positive_majority_median(filter_passes(rates))
        if med is not None:
            out[name + ".rate"] = med
    return out


def _metric_values(record):
    """Flatten a compact bench record (or any {name: number} map)
    into comparable scalars; the headline quadruple's metric/value
    pair is folded in under its own metric name."""
    values = {}
    if not isinstance(record, dict):
        return values
    headline = record.get("metric")
    for key, value in record.items():
        if isinstance(value, bool) or \
                not isinstance(value, (int, float)):
            continue
        if not math.isfinite(float(value)):
            continue
        values[key] = float(value)
    if headline and isinstance(values.get("value"), float):
        values[str(headline)] = values.pop("value")
    return values


def compare(record, baseline):
    """Per-metric verdicts of a run against a baseline.  Returns a
    list of ``{"metric", "status", "value", "baseline", "direction",
    "tolerance_pct", "delta_pct"}`` — status one of ``ok``,
    ``improved``, ``regressed``, ``missing`` (metric not in the run).
    Metrics the RUN has but the baseline does not are ignored: the
    baseline is the contract, new metrics join it by being
    committed."""
    values = _metric_values(record)
    results = []
    for name, spec in sorted((baseline.get("metrics") or {}).items()):
        base_value = spec.get("value")
        if not isinstance(base_value, (int, float)) or \
                isinstance(base_value, bool) or base_value == 0:
            continue
        direction = spec.get("direction", "higher")
        tolerance = float(spec.get("tolerance_pct", 10.0))
        entry = {"metric": name, "baseline": float(base_value),
                 "direction": direction, "tolerance_pct": tolerance}
        value = values.get(name)
        if value is None:
            entry.update(status="missing", value=None,
                         delta_pct=None)
            results.append(entry)
            continue
        delta_pct = 100.0 * (value - base_value) / abs(base_value)
        # signed so that POSITIVE means better: a lower-is-better
        # metric improving shrinks, so flip its sign
        gain_pct = delta_pct if direction == "higher" else -delta_pct
        if gain_pct < -tolerance:
            status = "regressed"
        elif gain_pct > tolerance:
            status = "improved"
        else:
            status = "ok"
        entry.update(status=status, value=value,
                     delta_pct=round(delta_pct, 2))
        results.append(entry)
    return results


def dominant_segment(analysis):
    """The segment that dominates the p99 tail in a PR 19 analyzer
    report (observe/requests.py ``analyze``), or ``None``."""
    if not isinstance(analysis, dict):
        return None
    dominant = ((analysis.get("tail") or {}).get("dominant")) or {}
    if not dominant:
        return None
    return max(sorted(dominant), key=lambda seg: dominant[seg])


def gate(record, baseline_path=None, analysis=None, rates=None):
    """The go/no-go verdict: ``(ok, report)``.

    ``record`` is a compact bench record (or any flat metric map);
    ``rates`` optionally folds in :func:`steady_state_rates` output;
    ``analysis`` optionally attaches the analyzer report so a failure
    can name the dominant tail segment.  A missing baseline passes
    with ``status: "no_baseline"`` — never red on first run."""
    baseline = load_baseline(baseline_path)
    if baseline is None:
        return True, {"kind": "perf_gate", "status": "no_baseline",
                      "path": baseline_path or _default_path(),
                      "results": [], "regressed": []}
    merged = dict(record or {})
    for name, value in (rates or {}).items():
        merged.setdefault(name, value)
    results = compare(merged, baseline)
    regressed = [r for r in results if r["status"] == "regressed"]
    report = {"kind": "perf_gate",
              "status": "regressed" if regressed else "ok",
              "path": baseline.get("path"),
              "source": baseline.get("source"),
              "results": results,
              "regressed": [r["metric"] for r in regressed]}
    segment = dominant_segment(analysis)
    if segment:
        report["dominant_segment"] = segment
    return not regressed, report


def render_report(report):
    """Human lines for the CLI / bench footer."""
    lines = []
    status = report.get("status")
    if status == "no_baseline":
        lines.append("perf gate: no baseline at %s (pass; commit "
                     "PERF_BASELINE.json to arm the sentinel)"
                     % report.get("path"))
        return lines
    for entry in report.get("results", ()):
        if entry["status"] == "missing":
            lines.append("  %-34s missing from run (baseline %.6g)"
                         % (entry["metric"], entry["baseline"]))
            continue
        lines.append(
            "  %-34s %-9s %.6g vs %.6g (%+.2f%%, tol %.1f%% %s)"
            % (entry["metric"], entry["status"].upper(),
               entry["value"], entry["baseline"], entry["delta_pct"],
               entry["tolerance_pct"], entry["direction"]))
    if status == "regressed":
        head = "perf gate: REGRESSED — " + \
            ", ".join(report["regressed"])
        if report.get("dominant_segment"):
            head += " (dominant tail segment: %s)" \
                % report["dominant_segment"]
    else:
        head = "perf gate: ok (%d metrics vs %s)" \
            % (len(report.get("results", ())),
               report.get("source") or report.get("path"))
    lines.insert(0, head)
    return lines
