"""Observability CLI: ``python -m veles_tpu.observe <command>``.

Commands:

- ``merge -o OUT master.json slave.json [--offset label=secs] ...`` —
  stitch saved per-process trace files into one Perfetto document with
  per-process tracks and offset-corrected timestamps (the first file
  is the reference clock; see docs/observability.md).
- ``summary <trace.json|flight.json> [--top N]`` — print a textual
  digest (top spans by self time per track, counter last values) of a
  trace file or a flight-recorder dump, for CI logs and bug reports.
- ``requests trace.json host0.json ... [--offset label=secs]`` —
  request-scoped critical-path analysis over saved traces, flight
  dumps, and merged documents: per-segment p50/p99 table, dominant-
  segment tail attribution, hedge win/loss + requeue accounting.
  Trace files are offset-stitched like ``merge`` first, so one hedged
  request's legs on two hosts fold under one id (docs/observability.md
  "Request tracing").
"""

import argparse
import sys


def _parse_offsets(entries):
    offsets = {}
    for entry in entries or ():
        label, sep, value = entry.partition("=")
        if not sep:
            raise SystemExit(
                "--offset expects label=seconds, got %r" % entry)
        offsets[label] = float(value)
    return offsets


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m veles_tpu.observe",
        description="trace merging and digesting tools")
    sub = parser.add_subparsers(dest="command", required=True)

    pm = sub.add_parser("merge", help="merge per-process trace files")
    pm.add_argument("inputs", nargs="+", metavar="TRACE",
                    help="saved trace files; the first is the "
                         "reference clock")
    pm.add_argument("-o", "--output", required=True, metavar="OUT")
    pm.add_argument("--offset", action="append", default=[],
                    metavar="LABEL=SECS",
                    help="seconds to ADD to that process's clock to "
                         "land on the reference clock (repeatable); "
                         "defaults to the join-time estimate of 0")
    pm.add_argument("--trace-id", default=None)

    ps = sub.add_parser("summary",
                        help="digest a trace file or flight dump")
    ps.add_argument("input", metavar="TRACE_OR_FLIGHT")
    ps.add_argument("--top", type=int, default=10)

    pr = sub.add_parser(
        "requests",
        help="critical-path analysis of request-scoped traces")
    pr.add_argument("inputs", nargs="+", metavar="TRACE_OR_FLIGHT",
                    help="saved trace files and/or flight dumps; "
                         "trace files are offset-stitched first (the "
                         "first is the reference clock)")
    pr.add_argument("--offset", action="append", default=[],
                    metavar="LABEL=SECS",
                    help="clock offset for that process, as in merge")
    pr.add_argument("--top", type=int, default=5)
    pr.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")

    args = parser.parse_args(argv)
    if args.command == "merge":
        from veles_tpu.observe import merge
        merged = merge.merge_files(
            args.inputs, args.output,
            offsets=_parse_offsets(args.offset),
            trace_id=args.trace_id)
        for warning in merged["otherData"].get("warnings", ()):
            print("warning: %s" % warning, file=sys.stderr)
        print("merged %d events from %d processes -> %s" % (
            sum(1 for e in merged["traceEvents"]
                if e.get("ph") != "M"),
            len(merged["otherData"]["parts"]), args.output))
        return 0
    if args.command == "summary":
        from veles_tpu.observe import summary
        doc = summary.load(args.input)
        summary.render(summary.summarize(doc, top=args.top))
        line = summary.request_digest_line(doc, top=args.top)
        if line:
            print("  " + line)
        return 0
    if args.command == "requests":
        from veles_tpu.observe import requests as reqtrace
        report = reqtrace.analyze_files(
            args.inputs, offsets=_parse_offsets(args.offset),
            top=args.top)
        if args.json:
            import json
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            reqtrace.render_requests(report)
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
