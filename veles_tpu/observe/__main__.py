"""Observability CLI: ``python -m veles_tpu.observe <command>``.

Commands:

- ``merge -o OUT master.json slave.json [--offset label=secs] ...`` —
  stitch saved per-process trace files into one Perfetto document with
  per-process tracks and offset-corrected timestamps (the first file
  is the reference clock; see docs/observability.md).
- ``summary <trace.json|flight.json> [--top N]`` — print a textual
  digest (top spans by self time per track, counter last values) of a
  trace file or a flight-recorder dump, for CI logs and bug reports.
- ``requests trace.json host0.json ... [--offset label=secs]`` —
  request-scoped critical-path analysis over saved traces, flight
  dumps, and merged documents: per-segment p50/p99 table, dominant-
  segment tail attribution, hedge win/loss + requeue accounting.
  Trace files are offset-stitched like ``merge`` first, so one hedged
  request's legs on two hosts fold under one id (docs/observability.md
  "Request tracing").
- ``fleet series0.json series1.json [--offset label=secs] [--rules]``
  — merge per-host telemetry series snapshots (observe/timeseries.py)
  into offset-corrected fleet rollups and print the per-metric table;
  ``--rules`` evaluates the stock serve alert rules over the rollup
  (docs/observability.md "Fleet telemetry").
- ``regress record.json [--baseline PERF_BASELINE.json] [trace...]``
  — the perf-regression sentinel: compare a compact bench record
  against the committed baseline; exits 1 naming the regressed
  metric (and the dominant tail segment when traces are given).
"""

import argparse
import sys


def _parse_offsets(entries):
    offsets = {}
    for entry in entries or ():
        label, sep, value = entry.partition("=")
        if not sep:
            raise SystemExit(
                "--offset expects label=seconds, got %r" % entry)
        offsets[label] = float(value)
    return offsets


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m veles_tpu.observe",
        description="trace merging and digesting tools")
    sub = parser.add_subparsers(dest="command", required=True)

    pm = sub.add_parser("merge", help="merge per-process trace files")
    pm.add_argument("inputs", nargs="+", metavar="TRACE",
                    help="saved trace files; the first is the "
                         "reference clock")
    pm.add_argument("-o", "--output", required=True, metavar="OUT")
    pm.add_argument("--offset", action="append", default=[],
                    metavar="LABEL=SECS",
                    help="seconds to ADD to that process's clock to "
                         "land on the reference clock (repeatable); "
                         "defaults to the join-time estimate of 0")
    pm.add_argument("--trace-id", default=None)

    ps = sub.add_parser("summary",
                        help="digest a trace file or flight dump")
    ps.add_argument("input", metavar="TRACE_OR_FLIGHT")
    ps.add_argument("--top", type=int, default=10)

    pr = sub.add_parser(
        "requests",
        help="critical-path analysis of request-scoped traces")
    pr.add_argument("inputs", nargs="+", metavar="TRACE_OR_FLIGHT",
                    help="saved trace files and/or flight dumps; "
                         "trace files are offset-stitched first (the "
                         "first is the reference clock)")
    pr.add_argument("--offset", action="append", default=[],
                    metavar="LABEL=SECS",
                    help="clock offset for that process, as in merge")
    pr.add_argument("--top", type=int, default=5)
    pr.add_argument("--json", action="store_true",
                    help="emit the full report as JSON")

    pf = sub.add_parser(
        "fleet",
        help="merge per-host telemetry snapshots into fleet rollups")
    pf.add_argument("inputs", nargs="+", metavar="SERIES",
                    help="per-host series snapshot files "
                         "(observe/timeseries.py snapshot/take_chunk)")
    pf.add_argument("--offset", action="append", default=[],
                    metavar="LABEL=SECS",
                    help="clock offset to ADD to that host's stamps")
    pf.add_argument("--interval", type=float, default=None,
                    help="rollup bucket width (default: the first "
                         "snapshot's interval)")
    pf.add_argument("--rules", action="store_true",
                    help="evaluate the stock serve alert rules over "
                         "the rollup")
    pf.add_argument("--json", action="store_true")

    pg = sub.add_parser(
        "regress", help="perf-regression gate vs PERF_BASELINE.json")
    pg.add_argument("record", metavar="RECORD_JSON",
                    help="compact bench record (bench.py's last "
                         "line, saved as JSON)")
    pg.add_argument("traces", nargs="*", metavar="TRACE_OR_FLIGHT",
                    help="optional traces/flight dumps; a failing "
                         "gate then names the dominant tail segment")
    pg.add_argument("--baseline", default=None)
    pg.add_argument("--json", action="store_true")

    args = parser.parse_args(argv)
    if args.command == "merge":
        from veles_tpu.observe import merge
        merged = merge.merge_files(
            args.inputs, args.output,
            offsets=_parse_offsets(args.offset),
            trace_id=args.trace_id)
        for warning in merged["otherData"].get("warnings", ()):
            print("warning: %s" % warning, file=sys.stderr)
        print("merged %d events from %d processes -> %s" % (
            sum(1 for e in merged["traceEvents"]
                if e.get("ph") != "M"),
            len(merged["otherData"]["parts"]), args.output))
        return 0
    if args.command == "summary":
        from veles_tpu.observe import summary
        doc = summary.load(args.input)
        summary.render(summary.summarize(doc, top=args.top))
        line = summary.request_digest_line(doc, top=args.top)
        if line:
            print("  " + line)
        return 0
    if args.command == "requests":
        from veles_tpu.observe import requests as reqtrace
        report = reqtrace.analyze_files(
            args.inputs, offsets=_parse_offsets(args.offset),
            top=args.top)
        if args.json:
            import json
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            reqtrace.render_requests(report)
        return 0
    if args.command == "fleet":
        import json
        import os
        from veles_tpu.observe.timeseries import (FleetTelemetry,
                                                  fleet_summary)
        offsets = _parse_offsets(args.offset)
        fleet = None
        for path in args.inputs:
            with open(path) as fh:
                snap = json.load(fh)
            if snap.get("kind") != "series":
                raise SystemExit(
                    "%s is not a series snapshot (kind=%r)"
                    % (path, snap.get("kind")))
            host = snap.get("label") or \
                os.path.splitext(os.path.basename(path))[0]
            if fleet is None:
                fleet = FleetTelemetry(
                    interval_s=args.interval or
                    snap.get("interval_s") or 5.0)
            if host in offsets:
                fleet.set_offset(host, offsets[host])
            if not fleet.add_chunk(host, snap):
                print("warning: dropped malformed snapshot %s" % path,
                      file=sys.stderr)
        rollup = fleet.rollup()
        summary = fleet_summary(rollup)
        fired = []
        if args.rules:
            from veles_tpu.observe.alerts import (AlertManager,
                                                  default_rules)
            manager = AlertManager(default_rules())
            manager.evaluate(rollup, dump=False)
            fired = manager.history()
        if args.json:
            import json as _json
            print(_json.dumps({"summary": summary, "alerts": fired,
                               "fleet": fleet.snapshot()},
                              indent=2, sort_keys=True))
            return 0
        print("fleet rollup: %d buckets from %d host(s) %s"
              % (summary["buckets"], len(summary["hosts"]),
                 ",".join(summary["hosts"])))
        for name in sorted(summary["counters"]):
            row = summary["counters"][name]
            print("  counter %-32s total %-10s %s/s"
                  % (name, row["delta"], row["rate"]))
        for name in sorted(summary["gauges"]):
            print("  gauge   %-32s max %s"
                  % (name, summary["gauges"][name]))
        for name in sorted(summary["hists"]):
            row = summary["hists"][name]
            print("  hist    %-32s n=%-7d p50 %s p95 %s p99 %s"
                  % (name, row["count"], row.get("p50"),
                     row.get("p95"), row.get("p99")))
        for record in fired:
            print("  alert   %-32s %s %s" % (
                record["alert"], record["state"],
                record.get("reason", "")))
        return 0
    if args.command == "regress":
        import json
        from veles_tpu.observe import baseline
        with open(args.record) as fh:
            record = json.load(fh)
        analysis = None
        if args.traces:
            from veles_tpu.observe import requests as reqtrace
            analysis = reqtrace.analyze_files(args.traces)
        ok, report = baseline.gate(record,
                                   baseline_path=args.baseline,
                                   analysis=analysis)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            for line in baseline.render_report(report):
                print(line)
        return 0 if ok else 1
    return 1


if __name__ == "__main__":
    sys.exit(main())
