"""Burn-rate SLO alerting over telemetry rollups.

The decisions half of the fleet telemetry plane
(docs/observability.md "Fleet telemetry"): declarative alert rules
evaluated against the bucket series :mod:`observe.timeseries`
produces (a local SeriesRing's buckets or a FleetTelemetry rollup —
same shape, same rules).

Two rule kinds:

- :class:`BurnRateRule` — the multi-window burn-rate discipline for
  the per-class SLO budgets QoS defines (serve/qos.py).  The error
  budget is the fraction of requests ALLOWED over the class latency
  budget (``1 - objective``); the burn rate of a window is
  ``observed-over-budget-fraction / allowed-fraction``.  The rule
  fires only when the FAST window (reacts in seconds) AND the SLOW
  window (proves it is not a blip) both burn past ``factor`` — the
  fast window alone pages on noise, the slow window alone pages an
  hour late.
- :class:`EmaSpikeRule` — anomaly detection on a counter rate or
  gauge series, reusing ``health.EmaSpikeWatch`` verbatim (one spike
  definition across the watchdog, the canary judge, and alerting).

:class:`AlertManager` evaluates a rule set EDGE-TRIGGERED: the
transition into breach emits one firing — an ``alert.fired`` trace
instant, a flight-recorder dump carrying the alert record and the
tail-exemplar ring, a counter bump — and lands in a bounded
alert-history ring exposed via ``/healthz``, the ``observe fleet``
CLI, and the web-status alerts column.  While the breach holds,
nothing re-fires; the transition out appends a "resolved" record.
A broken rule can never take down a serve loop: rule evaluation
errors are swallowed per-rule.
"""

import threading
import time

from veles_tpu.observe.timeseries import (digest_percentiles,
                                          merge_digests)

__all__ = ["ALERTS_SCHEMA_VERSION", "AlertRule", "BurnRateRule",
           "EmaSpikeRule", "AlertManager", "default_rules",
           "rule_from_spec", "alerts"]

ALERTS_SCHEMA_VERSION = 1


class AlertRule(object):
    """One named condition over a bucket series.  Subclasses
    implement ``evaluate(buckets) -> reason-string-or-None``;
    returning a reason means "in breach NOW" — the manager owns the
    edge detection."""

    kind = "rule"

    def __init__(self, name):
        self.name = str(name)

    def evaluate(self, buckets):
        raise NotImplementedError

    def spec(self):
        """The declarative form (the docs' rule format; soak receipts
        embed it so a firing names its exact condition)."""
        return {"name": self.name, "kind": self.kind}


class BurnRateRule(AlertRule):
    """Multi-window burn-rate pair over a latency histogram series.

    ``hist`` names the digest series (e.g.
    ``serve.tenant.interactive.latency_s``), ``budget_s`` the class
    latency budget, ``objective`` the fraction of requests that must
    land within it.  A window's burn rate is the observed
    over-budget fraction divided by the allowed fraction
    (``1 - objective``); the rule is in breach while BOTH the fast
    window (newest ``fast_buckets`` buckets) and the slow window
    (newest ``slow_buckets``) burn at >= ``factor``.  Windows with
    fewer than ``min_count`` observations abstain — an idle series
    must neither fire nor resolve-by-silence a firing based on one
    straggler."""

    kind = "burn_rate"

    def __init__(self, name, hist, budget_s, objective=0.99,
                 fast_buckets=3, slow_buckets=12, factor=2.0,
                 min_count=20):
        super(BurnRateRule, self).__init__(name)
        self.hist = str(hist)
        self.budget_s = float(budget_s)
        self.objective = float(objective)
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        self.fast_buckets = max(1, int(fast_buckets))
        self.slow_buckets = max(self.fast_buckets, int(slow_buckets))
        self.factor = float(factor)
        self.min_count = max(1, int(min_count))

    def window_burn(self, buckets):
        """Burn rate of one window, or None when the window lacks
        ``min_count`` observations."""
        merged = merge_digests(
            (bucket.get("hists") or {}).get(self.hist)
            for bucket in buckets)
        bins = merged["bins"]
        total = sum(bins.values())
        if total < self.min_count:
            return None
        from veles_tpu.observe import timeseries as _ts
        # a bin is over budget when its UPPER edge exceeds the
        # budget: pessimistic by at most one bin width (~19%), which
        # errs toward paging — the same side the digest percentiles
        # take
        over = sum(n for key, n in bins.items()
                   if _ts._bin_edge(key) > self.budget_s)
        allowed = 1.0 - self.objective
        return (over / float(total)) / allowed

    def evaluate(self, buckets):
        buckets = list(buckets)
        fast = self.window_burn(buckets[-self.fast_buckets:])
        slow = self.window_burn(buckets[-self.slow_buckets:])
        if fast is None or slow is None:
            return None
        if fast >= self.factor and slow >= self.factor:
            p99 = digest_percentiles(merge_digests(
                (b.get("hists") or {}).get(self.hist)
                for b in buckets[-self.fast_buckets:]),
                ps=(99,)).get("p99")
            return ("%s burning %.1fx fast / %.1fx slow "
                    "(budget %.3fs @ %.2f%%, fast p99 %s)"
                    % (self.hist, fast, slow, self.budget_s,
                       100.0 * self.objective,
                       "%.3fs" % p99 if p99 is not None else "n/a"))
        return None

    def spec(self):
        return {"name": self.name, "kind": self.kind,
                "hist": self.hist, "budget_s": self.budget_s,
                "objective": self.objective,
                "fast_buckets": self.fast_buckets,
                "slow_buckets": self.slow_buckets,
                "factor": self.factor, "min_count": self.min_count}


class EmaSpikeRule(AlertRule):
    """EMA anomaly rule over a counter-rate or gauge series —
    ``health.EmaSpikeWatch`` pointed at telemetry buckets.  Buckets
    are consumed once each (tracked by ts), spiking values are NOT
    folded into the EMA, and the rule is in breach exactly while the
    NEWEST consumed bucket spiked."""

    kind = "ema_spike"

    def __init__(self, name, metric, metric_kind="counter",
                 field="rate", spike_factor=10.0, spike_floor=1.0,
                 beta=0.5):
        from veles_tpu.health import EmaSpikeWatch
        super(EmaSpikeRule, self).__init__(name)
        self.metric = str(metric)
        self.metric_kind = metric_kind
        self.field = field
        self._watch = EmaSpikeWatch(spike_factor=spike_factor,
                                    spike_floor=spike_floor,
                                    beta=beta, label=self.metric)
        self._seen_ts = None
        self._breach = None

    def _value(self, bucket):
        if self.metric_kind == "gauge":
            value = (bucket.get("gauges") or {}).get(self.metric)
        else:
            entry = (bucket.get("counters") or {}).get(self.metric)
            value = (entry or {}).get(self.field)
            if value is None and entry is None:
                # an absent counter in a ticked bucket means zero
                # events, not missing data — feed the 0 so a burst
                # after silence still spikes against a real baseline
                value = 0.0
        if isinstance(value, bool) or not isinstance(value,
                                                     (int, float)):
            return None
        return float(value)

    def evaluate(self, buckets):
        for bucket in buckets:
            ts = bucket.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            if self._seen_ts is not None and ts <= self._seen_ts:
                continue
            self._seen_ts = ts
            value = self._value(bucket)
            if value is None:
                continue
            self._breach = self._watch.update(value)
        return self._breach

    def spec(self):
        return {"name": self.name, "kind": self.kind,
                "metric": self.metric,
                "metric_kind": self.metric_kind, "field": self.field,
                "spike_factor": self._watch.spike_factor,
                "spike_floor": self._watch.spike_floor,
                "beta": self._watch.beta}


def rule_from_spec(spec):
    """Build a rule from its declarative dict (the docs' format; what
    soak configs and saved rule sets round-trip through)."""
    kind = spec.get("kind")
    if kind == "burn_rate":
        return BurnRateRule(
            spec["name"], spec["hist"], spec["budget_s"],
            objective=spec.get("objective", 0.99),
            fast_buckets=spec.get("fast_buckets", 3),
            slow_buckets=spec.get("slow_buckets", 12),
            factor=spec.get("factor", 2.0),
            min_count=spec.get("min_count", 20))
    if kind == "ema_spike":
        return EmaSpikeRule(
            spec["name"], spec["metric"],
            metric_kind=spec.get("metric_kind", "counter"),
            field=spec.get("field", "rate"),
            spike_factor=spec.get("spike_factor", 10.0),
            spike_floor=spec.get("spike_floor", 1.0),
            beta=spec.get("beta", 0.5))
    raise ValueError("unknown alert rule kind %r" % (kind,))


def default_rules(budgets=None, objective=0.99, fast_buckets=3,
                  slow_buckets=12, factor=2.0, min_count=20,
                  scope="tenant"):
    """The stock serve rule set: one burn-rate pair per QoS class
    (budgets from serve/qos.py — override with a
    ``{class: budget_s}`` map) plus EMA anomaly rules on queue depth
    and fleet failures.  ``scope="fleet"`` points the burn rules at
    the fleet front's end-to-end class histograms instead of the
    host serving-edge ones (see ``qos.burn_rule_specs``)."""
    from veles_tpu.serve import qos
    rules = [rule_from_spec(spec) for spec in qos.burn_rule_specs(
        budgets=budgets, objective=objective,
        fast_buckets=fast_buckets, slow_buckets=slow_buckets,
        factor=factor, min_count=min_count, scope=scope)]
    rules.append(EmaSpikeRule(
        "queue_depth_spike", "serve.queue_depth",
        metric_kind="gauge", spike_factor=8.0, spike_floor=64.0))
    rules.append(EmaSpikeRule(
        "fleet_failures_spike", "serve.fleet.failed",
        metric_kind="counter", spike_factor=8.0, spike_floor=1.0))
    return rules


class AlertManager(object):
    """Edge-triggered evaluation of a rule set over bucket series,
    with a bounded alert-history ring.

    One manager instance per decision point (the process-global
    ``alerts`` for single-process serving, a FleetRouter's own for
    fleet rollups) — history and active state are per-manager, the
    ``alerts.fired``/``alerts.active`` metrics are shared."""

    def __init__(self, rules=(), history=64, registry=None):
        from veles_tpu.observe import metrics as _metrics
        import collections
        self.rules = list(rules)
        self._registry = registry if registry is not None \
            else _metrics.registry
        self._lock = threading.Lock()
        self._active = {}
        self._history = collections.deque(maxlen=max(1, int(history)))
        self._fired_total = 0

    def add_rule(self, rule):
        with self._lock:
            self.rules.append(rule)
        return rule

    def configure(self, specs):
        """Replace the rule set from declarative specs."""
        rules = [rule_from_spec(s) for s in specs]
        with self._lock:
            self.rules = rules
            self._active.clear()
        return rules

    def evaluate(self, buckets, wall=None, dump=True, context=None):
        """Sweep every rule against ``buckets``; returns the list of
        NEWLY-fired alert records (empty while steady or while a
        breach merely persists)."""
        buckets = list(buckets)
        wall = time.time() if wall is None else wall
        fired = []
        resolved = []
        with self._lock:
            rules = list(self.rules)
        for rule in rules:
            try:
                reason = rule.evaluate(buckets)
            except Exception:
                # a broken rule must never take down the loop that
                # evaluates it; it simply abstains
                reason = None
            with self._lock:
                active = self._active.get(rule.name)
                if reason and active is None:
                    record = {"alert": rule.name, "state": "firing",
                              "ts": wall, "reason": str(reason),
                              "rule": rule.spec()}
                    if context:
                        record["context"] = context
                    self._active[rule.name] = record
                    self._history.append(dict(record))
                    self._fired_total += 1
                    fired.append(record)
                elif reason and active is not None:
                    active["reason"] = str(reason)  # still burning
                elif not reason and active is not None:
                    self._active.pop(rule.name, None)
                    record = {"alert": rule.name, "state": "resolved",
                              "ts": wall, "fired_ts": active["ts"]}
                    self._history.append(record)
                    resolved.append(record)
        try:
            reg = self._registry
            if fired:
                reg.counter("alerts.fired").inc(len(fired))
            reg.gauge("alerts.active").set(len(self._active))
        except Exception:
            pass
        for record in fired:
            self._announce(record, dump=dump)
        for record in resolved:
            self._announce_resolved(record)
        return fired

    def _announce(self, record, dump=True):
        """One firing's evidence trail: trace instant + flight dump
        carrying the alert record and the tail-exemplar ring.  Never
        raises."""
        try:
            from veles_tpu.observe.trace import tracer
            if tracer.active:
                tracer.instant("alert.fired", cat="alerts",
                               alert=record["alert"],
                               reason=record["reason"])
        except Exception:
            pass
        if not dump:
            return
        try:
            from veles_tpu.observe import requests as reqtrace
            from veles_tpu.observe.flight import flight
            path = flight.dump("alert.%s" % record["alert"],
                               extra={"alert": record,
                                      "exemplars":
                                          reqtrace.exemplars.snapshot()})
            if path:
                # the active record (shared with the evaluate() return
                # value and the /healthz "firing" block) names its own
                # evidence file
                record["flight_dump"] = path
        except Exception:
            pass

    def _announce_resolved(self, record):
        try:
            from veles_tpu.observe.trace import tracer
            if tracer.active:
                tracer.instant("alert.resolved", cat="alerts",
                               alert=record["alert"])
        except Exception:
            pass

    def active(self):
        with self._lock:
            return [dict(r) for r in self._active.values()]

    def history(self, last=None):
        with self._lock:
            out = list(self._history)
        if last is not None and last > 0:
            out = out[-int(last):]
        return out

    def snapshot(self, history=16):
        """The /healthz + heartbeat ``alerts`` block."""
        with self._lock:
            active = [dict(r) for r in self._active.values()]
            tail = list(self._history)[-max(0, int(history)):]
            fired = self._fired_total
        return {"schema": ALERTS_SCHEMA_VERSION,
                "active": sorted(r["alert"] for r in active),
                "firing": active,
                "fired_total": fired,
                "history": tail}

    def clear(self):
        """Reset state AND rules (test isolation)."""
        with self._lock:
            self.rules = []
            self._active.clear()
            self._history.clear()
            self._fired_total = 0
        try:
            self._registry.gauge("alerts.active").set(0)
        except Exception:
            pass


#: The process-wide manager: empty (zero-cost) until a rule set is
#: installed — the serve service/launcher install ``default_rules``,
#: a FleetRouter keeps its OWN manager for fleet rollups.
alerts = AlertManager()
