"""Merge per-process traces into one cluster-scope Perfetto timeline.

Input: any mix of

- saved trace documents (``SpanTracer.save`` output, whose
  ``otherData`` carries the ``wall_epoch`` anchor and process label),
- shipped trace chunks (``SpanTracer.take_chunk`` output collected by
  the master's :class:`veles_tpu.observe.cluster.TraceCollector`).

Output: ONE ``{"traceEvents": [...]}`` document where every source
process gets its own synthetic pid (with a ``process_name`` metadata
event), thread tracks keep their names, and all timestamps are
offset-corrected onto a single reference clock: each event's local
``ts`` (µs since its tracer's perf_counter epoch) is first mapped onto
its process's wall clock via the recorded ``wall_epoch`` anchor, then
shifted by the per-process clock offset estimated at join time
(observe/cluster.py), then rebased so the merged timeline starts at 0.
A job's ``proto.job_out`` (master), ``slave.job`` / fill / step spans
(slave) and ``proto.update_in`` (master) line up on adjacent process
tracks, linked by the job id in their args.

CLI: ``python -m veles_tpu.observe merge -o merged.json master.json
slave.json [--offset label=seconds]``.
"""

import json

__all__ = ["part_from_doc", "merge_parts", "merge_run", "merge_files"]

_SYNTH_PID_BASE = 1


def part_from_doc(doc, label=None, offset_s=0.0):
    """Normalize a saved trace document into a merge part."""
    other = doc.get("otherData") or {}
    events = [e for e in doc.get("traceEvents", ())
              if e.get("ph") != "M" or e.get("name") == "thread_name"]
    threads = {}
    body = []
    for event in events:
        if event.get("ph") == "M":
            threads[str(event.get("tid"))] = (
                (event.get("args") or {}).get("name", ""))
        else:
            body.append(event)
    return {
        "label": label or other.get("label")
        or "pid:%s" % other.get("pid", "?"),
        "offset_s": float(offset_s),
        "chunks": [{
            "schema": 1,
            "pid": other.get("pid"),
            "wall_epoch": float(other.get("wall_epoch", 0.0)),
            "threads": threads,
            "events": body,
        }],
    }


def merge_parts(parts, trace_id=None):
    """Merge normalized parts (see module docstring) into one doc.

    Each part: ``{"label": str, "offset_s": float, "chunks": [chunk]}``
    where a chunk carries its own ``wall_epoch`` anchor, a ``threads``
    tid->name map, and raw tracer events.  ``offset_s`` is ADDED to the
    part's wall times to land on the reference clock (the master's),
    matching the join-time estimate convention of observe/cluster.py.
    """
    staged = []   # (wall_s, part_index, event)
    labels = []
    threads = {}  # (part_index, tid) -> name
    dropped = 0
    for index, part in enumerate(parts):
        labels.append(part.get("label") or "proc%d" % index)
        offset = float(part.get("offset_s") or 0.0)
        for chunk in part.get("chunks", ()):
            anchor = float(chunk.get("wall_epoch") or 0.0)
            for tid, name in (chunk.get("threads") or {}).items():
                threads.setdefault((index, str(tid)), name)
            for event in chunk.get("events", ()):
                ts = event.get("ts")
                if not isinstance(ts, (int, float)):
                    dropped += 1
                    continue
                staged.append((anchor + ts / 1e6 + offset, index, event))
    if not staged:
        base = 0.0
    else:
        base = min(wall for wall, _, _ in staged)
    out = []
    for index, label in enumerate(labels):
        pid = _SYNTH_PID_BASE + index
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": label}})
        # deterministic per-part ordering keeps merged docs diffable
        out.append({"name": "process_sort_index", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"sort_index": index}})
    for (index, tid), name in sorted(threads.items(),
                                     key=lambda kv: str(kv[0])):
        out.append({"name": "thread_name", "ph": "M",
                    "pid": _SYNTH_PID_BASE + index, "tid": int(tid),
                    "args": {"name": name or "thread-%s" % tid}})
    staged.sort(key=lambda item: item[0])
    for wall, index, event in staged:
        merged = dict(event)
        merged["pid"] = _SYNTH_PID_BASE + index
        merged["ts"] = (wall - base) * 1e6
        out.append(merged)
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "veles_tpu.observe.merge",
            "trace_id": trace_id,
            "parts": labels,
            "wall_base": base,
            "dropped_events": dropped,
        },
    }


def merge_run(master_doc, collector, trace_id=None, master_label="master"):
    """Master trace + a TraceCollector's shipped slave chunks -> one
    merged doc (the launcher's end-of-run auto-merge)."""
    parts = [part_from_doc(master_doc, label=master_label)]
    parts.extend(collector.parts())
    return merge_parts(parts, trace_id=trace_id)


def merge_files(paths, out_path, offsets=None, trace_id=None):
    """Merge saved per-process trace files (first file is the reference
    clock).  ``offsets`` maps a file's label (or basename) to the
    seconds to add onto its clock; files whose otherData lacks an
    anchor merge at offset 0 with a warning in the result metadata."""
    import os
    offsets = offsets or {}
    parts = []
    warnings = []
    for path in paths:
        with open(path) as fin:
            doc = json.load(fin)
        label = (doc.get("otherData") or {}).get("label") or \
            os.path.basename(path)
        offset = offsets.get(label, offsets.get(os.path.basename(path),
                                                0.0))
        if (doc.get("otherData") or {}).get("wall_epoch") is None:
            # a pre-anchor trace file merges at wall 0 — decades away
            # from any anchored peer on the rebased timeline; say so
            # instead of silently producing an unusable merge
            warnings.append(
                "%s has no wall_epoch anchor; its events merge at an "
                "arbitrary clock position" % os.path.basename(path))
        parts.append(part_from_doc(doc, label=label, offset_s=offset))
    merged = merge_parts(parts, trace_id=trace_id)
    if warnings:
        merged["otherData"]["warnings"] = warnings
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fout:
        json.dump(merged, fout)
    os.replace(tmp, out_path)
    return merged
