"""Shared background tornado HTTP serving.

Four services (web status, RESTful API, forge, frontend composer) run
the same serve-in-a-daemon-thread pattern; this is the one copy.  Bind
errors propagate to the caller instead of dying silently inside the
thread.

:class:`RequestTimer` is the shared per-request timing mixin.  Tornado's
own ``request.request_time()`` is ``time.time``-based — NTP-unsafe and
inconsistent with every other timer in the repo since the PR 5
perf_counter sweep (docs/observability.md) — so handlers mix this in
instead: wall time measured with ``time.perf_counter`` between
``prepare()`` and ``on_finish()``, published to the ``http.request_s``
histogram and, when the tracer/flight recorder is active, as an
``http.request`` span tagged with method/path/status.
"""

import threading
import time

__all__ = ["BackgroundHTTPServer", "RequestTimer"]


class RequestTimer(object):
    """Mixin for tornado ``RequestHandler`` subclasses (list it FIRST
    so the MRO runs its hooks): perf_counter request timing into the
    metrics registry + tracer.  Costs two attribute writes and one
    histogram observation per request."""

    def prepare(self):
        self._veles_started_ = time.perf_counter()
        return super(RequestTimer, self).prepare()

    def on_finish(self):
        started = getattr(self, "_veles_started_", None)
        if started is not None:
            elapsed = time.perf_counter() - started
            from veles_tpu.observe.metrics import registry
            from veles_tpu.observe.trace import tracer
            registry.histogram("http.request_s").observe(elapsed)
            if tracer.active:
                tracer.complete(
                    "http.request", started, elapsed, cat="http",
                    args={"method": self.request.method,
                          "path": self.request.path,
                          "status": self.get_status()})
        return super(RequestTimer, self).on_finish()


class BackgroundHTTPServer(object):
    """Runs a tornado Application on its own asyncio loop thread.

    ``start()`` returns once the socket is bound (raising the bind
    error, e.g. EADDRINUSE, in the calling thread); ``stop()`` stops the
    loop and joins the thread.
    """

    def __init__(self, app, port=0, address="127.0.0.1",
                 **server_kwargs):
        self.app = app
        self.port = port
        self.address = address
        self.server_kwargs = server_kwargs
        self._loop = None
        self._thread = None

    def start(self):
        import asyncio

        import tornado.httpserver
        import tornado.netutil

        started = threading.Event()
        failure = []

        def serve():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            try:
                server = tornado.httpserver.HTTPServer(
                    self.app, **self.server_kwargs)
                sockets = tornado.netutil.bind_sockets(
                    self.port, address=self.address)
                self.port = sockets[0].getsockname()[1]
                server.add_sockets(sockets)
            except Exception as exc:
                failure.append(exc)
                started.set()
                return
            started.set()
            loop.run_forever()

        self._thread = threading.Thread(target=serve, daemon=True)
        self._thread.start()
        if not started.wait(10):
            raise RuntimeError("HTTP server failed to start in 10 s")
        if failure:
            raise failure[0]
        return self._thread

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
