"""RESTful serving of a trained workflow.

Reference veles/restful_api.py:78: HTTP POST /api with {"input": ...}
feeds the loader and returns the transformed evaluation result.  Since
PR 7 this unit is a compatibility front over the real serving
subsystem (:mod:`veles_tpu.serve`, docs/serving.md): initialization
builds an :class:`~veles_tpu.serve.AOTEngine` (pre-compiled batch-shape
ladder, optional persistent compile cache) and a continuous batcher,
and the tornado endpoint is served by :class:`~veles_tpu.serve.
ServeService`'s async handler — concurrent requests co-batch into one
device dispatch with a single host sync per BATCH, where the old unit
jit-compiled ad hoc and synced per request.  The endpoint contract
(``{"input": ...}`` -> ``{"result", "probabilities"}``), the
``infer()`` method and ``requests_served`` are unchanged; overload now
answers ``503`` + ``retry_after`` instead of queueing without bound.
"""

import numpy

from veles_tpu.units import Unit

__all__ = ["RESTfulAPI"]


class RESTfulAPI(Unit):
    def __init__(self, workflow, **kwargs):
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.port = kwargs.get("port", 0)
        self.path = kwargs.get("path", "/api")
        #: serving knobs (docs/serving.md); defaults keep the unit a
        #: drop-in for the old single-sample server
        self.ladder = tuple(kwargs.get("ladder", (1, 8, 32, 128)))
        self.max_delay_s = kwargs.get("max_delay_s", 0.002)
        self.max_queue = kwargs.get("max_queue", 256)
        self.cache_root = kwargs.get("cache_root")
        self.persistent_cache = kwargs.get("persistent_cache", False)
        self.slo_p50_ms = kwargs.get("slo_p50_ms")
        self.slo_p99_ms = kwargs.get("slo_p99_ms")
        self.engine = None
        self._service_ = None
        self.restartable = False  # stop() shuts the HTTP server down

    @property
    def requests_served(self):
        return (self._service_.samples_served
                if self._service_ is not None else 0)

    def initialize(self, **kwargs):
        super(RESTfulAPI, self).initialize(**kwargs)
        from veles_tpu.serve import AOTEngine, ServeService
        loader = getattr(self.workflow, "loader", None)
        self.engine = AOTEngine.from_workflow(
            self.workflow, ladder=self.ladder,
            cache_root=self.cache_root,
            persistent_cache=self.persistent_cache)
        self.engine.compile()
        self._service_ = ServeService(
            self.engine, port=self.port, path=self.path,
            labels_mapping=getattr(loader, "reversed_labels_mapping",
                                   None),
            max_delay_s=self.max_delay_s, max_queue=self.max_queue,
            slo_p50_ms=self.slo_p50_ms, slo_p99_ms=self.slo_p99_ms)
        return True

    def infer(self, sample):
        """sample: nested list/array (with or without batch dim);
        compatibility wrapper over the batcher (rows co-batch with any
        concurrent HTTP traffic)."""
        if self._service_ is None:
            raise RuntimeError("initialize() the unit before infer()")
        if not self._service_.batcher.running:
            # programmatic use without start_background(): serve
            # in-process through the engine's sequential path (the
            # engine normalizes bare samples to a batch itself)
            probs = self.engine.infer(
                numpy.asarray(sample, self.engine.dtype))
            with self._service_._served_lock:
                self._service_.samples_served += len(probs)
            from veles_tpu.serve import format_result
            return format_result(probs, self._service_.labels_mapping)
        return self._service_.infer_payload(sample)

    # -- HTTP ---------------------------------------------------------------

    def start_background(self):
        thread = self._service_.start_background()
        self.port = self._service_.port
        self.info("REST API on http://127.0.0.1:%d%s (serve engine: "
                  "ladder %s)", self.port, self.path,
                  list(self.engine.ladder))
        return thread

    def stop(self):
        super(RESTfulAPI, self).stop()
        if self._service_ is not None:
            self._service_.stop()
