"""RESTful serving of a trained workflow.

Reference veles/restful_api.py:78: HTTP POST /api with {"input": ...}
feeds the loader and returns the transformed evaluation result.  Here
the unit compiles the workflow's forward (veles_tpu.compiler) once and
serves it with tornado; the response carries the argmax label (and
probabilities), matching root.common.evaluation_transform's default
role.
"""

import json

import numpy

from veles_tpu.units import Unit

__all__ = ["RESTfulAPI"]


class RESTfulAPI(Unit):
    def __init__(self, workflow, **kwargs):
        super(RESTfulAPI, self).__init__(workflow, **kwargs)
        self.port = kwargs.get("port", 0)
        self.path = kwargs.get("path", "/api")
        self._forward = None
        self._params = None
        self._server_ = None
        self.requests_served = 0
        self.restartable = False  # stop() shuts the HTTP server down

    def initialize(self, **kwargs):
        super(RESTfulAPI, self).initialize(**kwargs)
        self._compile()
        return True

    def _compile(self):
        from veles_tpu.compiler import (
            build_forward, extract_state, workflow_plan)
        sw = self.workflow
        plans = workflow_plan(sw)
        state = extract_state(sw)
        self._params = [{"weights": s["weights"], "bias": s["bias"]}
                        for s in state]
        self._forward = build_forward(plans)

    def infer(self, sample):
        """sample: nested list/array (with or without batch dim)."""
        x = numpy.asarray(sample, numpy.float32)
        loader = getattr(self.workflow, "loader", None)
        sample_shape = (loader.minibatch_data.shape[1:]
                        if loader is not None and loader.minibatch_data
                        else None)
        if sample_shape is not None and x.shape == tuple(sample_shape):
            x = x[None]
        probs = numpy.asarray(self._forward(self._params, x))
        labels = probs.argmax(axis=1)
        mapping = (loader.reversed_labels_mapping
                   if loader is not None else {})
        named = [mapping.get(int(l), int(l)) for l in labels]
        self.requests_served += len(labels)
        return {"result": named if len(named) > 1 else named[0],
                "probabilities": probs.tolist()}

    # -- HTTP ---------------------------------------------------------------

    def start_background(self):
        import tornado.web

        unit = self

        class ApiHandler(tornado.web.RequestHandler):
            def post(self):
                try:
                    body = json.loads(self.request.body)
                    self.write(unit.infer(body["input"]))
                except Exception as exc:
                    self.set_status(400)
                    self.write({"error": str(exc)})

        app = tornado.web.Application([(self.path, ApiHandler)])
        from veles_tpu.http_util import BackgroundHTTPServer
        self._server_ = BackgroundHTTPServer(app, port=self.port)
        thread = self._server_.start()
        self.port = self._server_.port
        self.info("REST API on http://127.0.0.1:%d%s", self.port,
                  self.path)
        return thread

    def stop(self):
        super(RESTfulAPI, self).stop()
        if self._server_ is not None:
            self._server_.stop()
