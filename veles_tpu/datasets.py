"""Real-dataset access: MNIST/CIFAR-10/STL-10 (cached on disk) + an
offline real-data anchor (scikit-learn's bundled UCI digits).

Reference parity: the reference's model-quality table
(/root/reference/docs/source/manualrst_veles_algorithms.rst:31,50,51,69)
is defined on MNIST (1.48 % validation error, 784-100-10; AE RMSE
0.5478), CIFAR-10 (17.21 %, conv), and STL-10 (35.10 %, conv).  Those
datasets are not redistributable inside this repo and the build
environment has no network egress, so this module:

- parses the standard idx / CIFAR-python / STL-10-binary formats from
  ``root.common.dirs.datasets`` (or ``$VELES_DATA``) when the user has
  the files, downloading them first when the network allows;
- always provides :func:`digits_arrays` — 1,797 real 8x8 handwritten
  digits that ship inside scikit-learn — so the full
  loader->workflow->decision->snapshotter quality path is exercised on
  genuine data even fully offline (see tests/test_quality.py and
  scripts/quality.py).
"""

import gzip
import os
import pickle
import struct

import numpy

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader, \
    FullBatchLoaderMSE

__all__ = ["DatasetNotFound", "load_idx", "mnist_arrays", "MnistLoader",
           "digits_arrays", "DigitsLoader", "cifar10_arrays",
           "Cifar10Loader", "stl10_arrays", "Stl10Loader", "selfcheck"]

MNIST_URLS = [
    # canonical mirrors of the Yann LeCun idx files
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
]
MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}
CIFAR10_URLS = [
    "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz",
    "https://ossci-datasets.s3.amazonaws.com/cifar-10-python.tar.gz",
]
STL10_URLS = [
    "https://cs.stanford.edu/~acoates/stl10/stl10_binary.tar.gz",
    "http://ai.stanford.edu/~acoates/stl10/stl10_binary.tar.gz",
]


class DatasetNotFound(Exception):
    """Raised when a dataset is neither cached nor downloadable."""


def _datasets_dir():
    path = root.common.dirs.get("datasets")
    os.makedirs(path, exist_ok=True)
    return path


def load_idx(path):
    """Parse one idx file (optionally .gz): big-endian magic + dims."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fin:
        raw = fin.read()
    zeros, dtype_code, ndim = struct.unpack(">HBB", raw[:4])
    if zeros != 0:
        raise ValueError("bad idx magic in %s" % path)
    dtypes = {0x08: numpy.uint8, 0x09: numpy.int8, 0x0B: numpy.int16,
              0x0C: numpy.int32, 0x0D: numpy.float32, 0x0E: numpy.float64}
    dtype = numpy.dtype(dtypes[dtype_code]).newbyteorder(">")
    shape = struct.unpack(">" + "I" * ndim, raw[4:4 + 4 * ndim])
    data = numpy.frombuffer(raw, dtype, offset=4 + 4 * ndim)
    return data.reshape(shape)


def _fetch(filename, data_dir, download=True):
    """Return the local path for *filename*, downloading if needed
    (atomically — a partial file would later fail as a confusing
    gzip error instead of engaging the openml fallback)."""
    for candidate in (os.path.join(data_dir, filename),
                      os.path.join(data_dir, "mnist", filename)):
        if os.path.exists(candidate):
            return candidate
        raw = candidate[:-3] if candidate.endswith(".gz") else None
        if raw and os.path.exists(raw):
            return raw
    target = os.path.join(data_dir, filename)
    if download:
        for base in MNIST_URLS:
            if not _download_file(base + filename, target):
                continue
            try:
                load_idx(target)  # a mirror's HTTP-200 error page
            except Exception:    # must not poison the cache forever
                try:
                    os.remove(target)
                except OSError:
                    pass
                continue
            return target
    raise DatasetNotFound(
        "MNIST file %s not found under %s and %s; place the idx files "
        "there or set $VELES_DATA" % (
            filename, data_dir,
            "download failed" if download
            else "downloads are disabled for validation"))


def mnist_arrays(data_dir=None, download=True):
    """(train_x f32 [60000,784] in [0,1], train_y i32, test_x, test_y).

    Self-checks the drop (shapes, label range, file checksums) so a
    future data drop immediately yields the reference-parity runs or
    fails with a clear message.  Source order: cached/downloaded idx
    files, then sklearn's ``fetch_openml("mnist_784")`` mirror (cached
    as mnist_openml.npz once it succeeds).  ``download=False``
    restricts to what is already cached (selfcheck/ingest use it so
    validating never triggers multi-hundred-MB transfers)."""
    data_dir = data_dir or _datasets_dir()
    try:
        raw, paths = _load_mnist_raw(data_dir, download)
    except DatasetNotFound as idx_err:
        return _mnist_openml(data_dir, idx_err, download)
    _verify_mnist(raw, paths)
    out = {key: (arr.astype(numpy.float32) / 255.0
                 if key.endswith("images")
                 else arr.astype(numpy.int32))
           for key, arr in raw.items()}
    return (out["train_images"], out["train_labels"],
            out["test_images"], out["test_labels"])


_OPENML_NPZ = "mnist_openml.npz"


def _load_openml_npz(npz):
    """Validated cache read; None when absent/corrupt (a truncated
    write must re-fetch, not crash MNIST forever)."""
    if not os.path.exists(npz):
        return None
    try:
        z = numpy.load(npz)
        arrays = (z["train_x"], z["train_y"], z["test_x"], z["test_y"])
        if arrays[0].shape != (60000, 784) or \
                arrays[2].shape != (10000, 784) or \
                arrays[1].shape != (60000,) or \
                arrays[3].shape != (10000,):
            raise ValueError("wrong shapes")
        for labels in (arrays[1], arrays[3]):
            if not numpy.issubdtype(labels.dtype, numpy.integer) or \
                    labels.min() < 0 or labels.max() > 9:
                raise ValueError("bad labels")
        return arrays
    except Exception:
        try:
            os.remove(npz)
        except OSError:
            pass
        return None


def _mnist_openml(data_dir, idx_err, download=True):
    """openml.org fallback for MNIST: a different host than the idx
    mirrors, so one blocked CDN doesn't kill the parity run.  The
    70k x 784 matrix preserves the canonical train/test order (first
    60k = train)."""
    npz = os.path.join(data_dir, _OPENML_NPZ)
    cached = _load_openml_npz(npz)
    if cached is not None:
        return cached
    if not download:
        raise idx_err
    try:
        from sklearn.datasets import fetch_openml
        bunch = fetch_openml("mnist_784", version=1, as_frame=False)
        x = numpy.asarray(bunch.data, numpy.float32) / 255.0
        y = numpy.asarray(bunch.target, numpy.int32)
    except Exception as openml_err:
        raise DatasetNotFound(
            "%s; openml fallback also failed: %r" % (idx_err,
                                                     openml_err))
    if x.shape != (70000, 784) or not (0 <= y.min() and y.max() <= 9):
        raise DatasetNotFound(
            "MNIST openml fallback self-check failed: data %s, label "
            "range [%s, %s]" % (x.shape, y.min(), y.max()))
    arrays = (x[:60000], y[:60000], x[60000:], y[60000:])
    tmp = npz + ".part.npz"
    try:
        os.makedirs(data_dir, exist_ok=True)
        numpy.savez_compressed(
            tmp, train_x=arrays[0], train_y=arrays[1],
            test_x=arrays[2], test_y=arrays[3])
        os.replace(tmp, npz)  # atomic: a killed write must not poison
    except OSError:
        pass  # cache write failure must not discard the fetched data
    return arrays


#: widely-published md5s of the canonical MNIST gz files (torchvision
#: ships the same values); a drop whose checksum mismatches gets a
#: warning, not a failure — users may legitimately drop re-compressed
#: or uncompressed copies
MNIST_MD5 = {
    "train-images-idx3-ubyte.gz": "f68b3c2dcbeaaa9fbdd348bbdeb94873",
    "train-labels-idx1-ubyte.gz": "d53e105ee54ea40749a09fcbcd1e9432",
    "t10k-images-idx3-ubyte.gz": "9fb629c4189551a2d022fa330f9573f3",
    "t10k-labels-idx1-ubyte.gz": "ec29112dd5afa0611ce80d1b7f02629c",
}


def _load_mnist_raw(data_dir, download=True):
    """Fetch + parse the four idx files; shared by mnist_arrays and
    selfcheck so what is validated is exactly what training loads.
    Returns ({key: raw uint8 array, images flattened}, [paths])."""
    out = {}
    paths = []
    for key, filename in MNIST_FILES.items():
        path = _fetch(filename, data_dir, download)
        paths.append(path)
        arr = load_idx(path)
        if key.endswith("images"):
            arr = arr.reshape(arr.shape[0], -1)
        out[key] = arr
    return out, paths


def _verify_mnist(out, paths, checksums=False):
    """Structural self-check: a wrong/truncated drop must fail HERE
    with a clear message, not as a confusing shape error mid-training.
    Returns a provenance report; file md5s only when ``checksums``
    (they cost a full re-read of ~11 MB — selfcheck wants them, the
    per-training-run load path does not)."""
    expect = {"train_images": (60000, 784), "train_labels": (60000,),
              "test_images": (10000, 784), "test_labels": (10000,)}
    for key, shape in expect.items():
        if out[key].shape != shape:
            raise DatasetNotFound(
                "MNIST self-check failed: %s has shape %s, expected %s "
                "— the dropped files are not the canonical idx set"
                % (key, out[key].shape, shape))
    for key in ("train_labels", "test_labels"):
        if not (0 <= out[key].min() and out[key].max() <= 9):
            raise DatasetNotFound(
                "MNIST self-check failed: %s range [%d, %d] outside "
                "0..9" % (key, out[key].min(), out[key].max()))
    report = {"shapes_ok": True}
    if checksums:
        report["files"] = {}
        for path in paths:
            digest = _md5_file(path)
            name = os.path.basename(path)
            known = MNIST_MD5.get(name)
            report["files"][name] = {
                "md5": digest,
                "canonical": (None if known is None
                              else digest == known)}
    return report


def digits_arrays(validation_count=360, seed=4):
    """Real handwritten digits (sklearn-bundled UCI dataset), split
    deterministically: (train_x, train_y, valid_x, valid_y).

    1,797 8x8 grayscale digits, features scaled to [0,1]."""
    from sklearn.datasets import load_digits
    bunch = load_digits()
    x = (bunch.data / 16.0).astype(numpy.float32)
    y = bunch.target.astype(numpy.int32)
    rng = numpy.random.RandomState(seed)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    return (x[validation_count:], y[validation_count:],
            x[:validation_count], y[:validation_count])


def _find_cifar_dir(data_dir):
    """Resolve the CIFAR-10 batches directory or raise DatasetNotFound
    (single source of truth for the layout probe — loader and
    selfcheck must agree on what counts as a drop)."""
    for sub in ("cifar-10-batches-py", "cifar10", "."):
        base = os.path.join(data_dir, sub)
        if os.path.exists(os.path.join(base, "data_batch_1")):
            return base
    raise DatasetNotFound(
        "CIFAR-10 python batches not found under %s" % data_dir)


def _download_file(url, target, timeout=60):
    """Stream one URL to ``target`` (atomic rename); True on success."""
    import urllib.request
    tmp = target + ".part"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp, \
                open(tmp, "wb") as fout:
            while True:
                block = resp.read(1 << 20)
                if not block:
                    break
                fout.write(block)
        os.replace(tmp, target)
        return True
    except Exception:
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _extract_tar(tar_path, data_dir):
    """Extract with the data filter; a corrupt archive raises
    DatasetNotFound rather than a bare tarfile error."""
    import tarfile
    try:
        with tarfile.open(tar_path) as tar:
            tar.extractall(data_dir, filter="data")
    except (tarfile.TarError, OSError, EOFError) as exc:
        raise DatasetNotFound(
            "cannot extract %s: %r" % (tar_path, exc))


def _maybe_download_tarball(urls, filename, data_dir):
    """Try each mirror for ``filename``; extract on success.  Returns
    True when a tarball was fetched + extracted (re-probe the layout
    then).  Quiet failure — the caller reports the authoritative
    DatasetNotFound.  A saved file that is not a tarball (a mirror's
    HTTP-200 error page) is deleted, not left to poison every later
    run."""
    import tarfile
    target = os.path.join(data_dir, filename)
    if os.path.exists(target) and tarfile.is_tarfile(target):
        _extract_tar(target, data_dir)
        return True
    for url in urls:
        if not _download_file(url, target):
            continue
        if not tarfile.is_tarfile(target):
            try:
                os.remove(target)
            except OSError:
                pass
            continue
        _extract_tar(target, data_dir)
        return True
    return False


def cifar10_arrays(data_dir=None, download=True):
    """(train_x f32 [50000,32,32,3] in [0,1], train_y, test_x, test_y)
    from the python-pickle CIFAR-10 batches (downloaded from the
    canonical/ossci mirrors when absent, the network allows, and
    ``download`` is True)."""
    data_dir = data_dir or _datasets_dir()
    try:
        base = _find_cifar_dir(data_dir)
    except DatasetNotFound:
        if not download or not _maybe_download_tarball(
                CIFAR10_URLS, "cifar-10-python.tar.gz", data_dir):
            raise
        base = _find_cifar_dir(data_dir)

    def read_batch(name):
        with open(os.path.join(base, name), "rb") as fin:
            batch = pickle.load(fin, encoding="bytes")
        data = batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return (data.astype(numpy.float32) / 255.0,
                numpy.array(batch[b"labels"], numpy.int32))

    xs, ys = zip(*[read_batch("data_batch_%d" % i) for i in range(1, 6)])
    test_x, test_y = read_batch("test_batch")
    train_x, train_y = numpy.concatenate(xs), numpy.concatenate(ys)
    for what, arr, shape in (
            ("train images", train_x, (50000, 32, 32, 3)),
            ("train labels", train_y, (50000,)),
            ("test images", test_x, (10000, 32, 32, 3)),
            ("test labels", test_y, (10000,))):
        if arr.shape != shape:
            raise DatasetNotFound(
                "CIFAR-10 self-check failed: %s shape %s, expected %s "
                "— the dropped batches are not the canonical python "
                "set" % (what, arr.shape, shape))
    if not (0 <= train_y.min() and train_y.max() <= 9):
        raise DatasetNotFound(
            "CIFAR-10 self-check failed: label range [%d, %d] outside "
            "0..9" % (train_y.min(), train_y.max()))
    return (train_x, train_y, test_x, test_y)


def _find_stl10_dir(data_dir):
    for sub in ("stl10_binary", "stl10", "."):
        base = os.path.join(data_dir, sub)
        if os.path.exists(os.path.join(base, "train_X.bin")):
            return base
    raise DatasetNotFound(
        "STL-10 binary files not found under %s" % data_dir)


def stl10_arrays(data_dir=None, download=True):
    """(train_x f32 [5000,96,96,3] in [0,1], train_y i32 0..9, test_x
    [8000,...], test_y) from the STL-10 binary files (train_X.bin /
    train_y.bin / test_X.bin / test_y.bin).

    Reference quality target: 35.10 % conv validation error
    (manualrst_veles_algorithms.rst:51).  STL-10 images are stored
    channel-major and column-major within each channel."""
    data_dir = data_dir or _datasets_dir()
    try:
        base = _find_stl10_dir(data_dir)
    except DatasetNotFound:
        if not download or not _maybe_download_tarball(
                STL10_URLS, "stl10_binary.tar.gz", data_dir):
            raise
        base = _find_stl10_dir(data_dir)

    def read_split(x_name, y_name, count, what):
        x = numpy.fromfile(os.path.join(base, x_name), numpy.uint8)
        if x.size != count * 3 * 96 * 96:
            raise DatasetNotFound(
                "STL-10 self-check failed: %s holds %d bytes, expected "
                "%d (%d images) — not the canonical binary file"
                % (what, x.size, count * 3 * 96 * 96, count))
        x = x.reshape(count, 3, 96, 96).transpose(0, 3, 2, 1)
        x = (x.astype(numpy.float32) / 255.0)
        y = numpy.fromfile(os.path.join(base, y_name), numpy.uint8)
        if y.shape != (count,):
            raise DatasetNotFound(
                "STL-10 self-check failed: labels %s shape %s, "
                "expected (%d,)" % (what, y.shape, count))
        if not (1 <= y.min() and y.max() <= 10):
            raise DatasetNotFound(
                "STL-10 self-check failed: label range [%d, %d] "
                "outside 1..10" % (y.min(), y.max()))
        return x, (y.astype(numpy.int32) - 1)  # 1-indexed on disk

    train_x, train_y = read_split("train_X.bin", "train_y.bin",
                                  5000, "train")
    test_x, test_y = read_split("test_X.bin", "test_y.bin",
                                8000, "test")
    return train_x, train_y, test_x, test_y


def _md5_file(path, chunk=1 << 20):
    """Chunked md5 — dataset binaries run to hundreds of MB; reading
    them whole just to hash doubles peak memory for nothing."""
    import hashlib
    digest = hashlib.md5()
    with open(path, "rb") as fin:
        while True:
            block = fin.read(chunk)
            if not block:
                return digest.hexdigest()
            digest.update(block)


def selfcheck(data_dir=None):
    """Validate whatever datasets are present; report per dataset.

    {name: {"status": "ok"|"missing", ...provenance...}} — run after a
    data drop to confirm the reference-parity runs (1.48 % MNIST /
    17.21 % CIFAR-10) will start with zero code changes:

        python -c "from veles_tpu.datasets import selfcheck; \
                   print(selfcheck())"
    """
    report = {}
    data_dir = data_dir or _datasets_dir()
    # download=False everywhere: validation must never trigger
    # multi-hundred-MB transfers (the fetch CLI command is the
    # explicit download path)
    try:
        raw, paths = _load_mnist_raw(data_dir, download=False)
        row = _verify_mnist(raw, paths, checksums=True)
        row["status"] = "ok"
        row["source"] = "idx"
        report["mnist"] = row
    except DatasetNotFound as exc:
        npz = os.path.join(data_dir, _OPENML_NPZ)
        if _load_openml_npz(npz) is not None:
            report["mnist"] = {"status": "ok", "source": "openml",
                               "md5": _md5_file(npz)}
        else:
            report["mnist"] = {"status": "missing", "detail": str(exc)}
    try:
        cifar10_arrays(data_dir, download=False)
        base = _find_cifar_dir(data_dir)
        files = {}
        for i in list(range(1, 6)) + ["test"]:
            name = ("data_batch_%d" % i if isinstance(i, int)
                    else "test_batch")
            files[name] = _md5_file(os.path.join(base, name))
        report["cifar10"] = {"status": "ok", "shapes_ok": True,
                             "files": files}
    except DatasetNotFound as exc:
        report["cifar10"] = {"status": "missing", "detail": str(exc)}
    try:
        stl10_arrays(data_dir, download=False)
        base = _find_stl10_dir(data_dir)
        files = {name: _md5_file(os.path.join(base, name))
                 for name in ("train_X.bin", "train_y.bin",
                              "test_X.bin", "test_y.bin")}
        report["stl10"] = {"status": "ok", "shapes_ok": True,
                           "files": files}
    except DatasetNotFound as exc:
        report["stl10"] = {"status": "missing", "detail": str(exc)}
    return report


def _ingest_table():
    """artifact name -> (dataset, destination subdir under the cache);
    everything the one-command ingest recognizes in a drop dir."""
    table = {_OPENML_NPZ: ("mnist", "")}
    for name in MNIST_FILES.values():
        table[name] = ("mnist", "")
        table[name[:-3]] = ("mnist", "")            # uncompressed idx
    for name in ["data_batch_%d" % i for i in range(1, 6)] + [
            "test_batch", "batches.meta"]:
        table[name] = ("cifar10", "cifar-10-batches-py")
    for name in ("train_X.bin", "train_y.bin", "test_X.bin",
                 "test_y.bin", "unlabeled_X.bin", "class_names.txt"):
        table[name] = ("stl10", "stl10_binary")
    return table


_INGEST_FILES = _ingest_table()
_INGEST_TARBALLS = {
    "cifar-10-python.tar.gz": "cifar10",
    "stl10_binary.tar.gz": "stl10",
}


def ingest(source_dir, data_dir=None):
    """One-command data drop: scan ``source_dir`` recursively for
    canonical dataset artifacts (MNIST idx files, CIFAR-10 python
    batches or tarball, STL-10 binaries or tarball), stage them into
    the dataset cache, and return the checksummed :func:`selfcheck`
    report — anyone with the files can produce the reference-parity
    QUALITY rows with zero code changes:

        python -m veles_tpu.datasets ingest <dir-with-the-files>
    """
    import shutil
    data_dir = data_dir or _datasets_dir()
    staged = []
    for dirpath, _dirnames, filenames in os.walk(source_dir):
        for fname in filenames:
            src = os.path.join(dirpath, fname)
            try:
                if fname in _INGEST_TARBALLS:
                    _extract_tar(src, data_dir)
                    staged.append((fname, "extracted"))
                elif fname in _INGEST_FILES:
                    _dataset, sub = _INGEST_FILES[fname]
                    dest_dir = os.path.join(data_dir, sub) if sub \
                        else data_dir
                    dest = os.path.join(dest_dir, fname)
                    if os.path.exists(dest) and \
                            os.path.samefile(src, dest):
                        # ingesting the cache dir itself (a plausible
                        # "validate what I have" run): nothing to copy
                        staged.append((fname, "already in cache"))
                        continue
                    os.makedirs(dest_dir, exist_ok=True)
                    shutil.copy2(src, dest)
                    staged.append((fname, "copied"))
            except (DatasetNotFound, OSError, shutil.Error) as exc:
                # a bad artifact lands in the report, not as a crash
                # with files half-staged
                staged.append((fname, "FAILED: %r" % exc))
    report = selfcheck(data_dir)
    report["ingested"] = {
        "source": os.path.abspath(source_dir),
        "data_dir": data_dir,
        "files": ["%s (%s)" % pair for pair in sorted(staged)],
    }
    return report


def _main(argv=None):
    """``python -m veles_tpu.datasets {ingest,selfcheck,fetch}``."""
    import argparse
    import json as _json
    parser = argparse.ArgumentParser(
        prog="python -m veles_tpu.datasets",
        description="dataset drop/ingest utilities (MNIST, CIFAR-10, "
                    "STL-10)")
    sub = parser.add_subparsers(dest="command", required=True)
    p_ing = sub.add_parser(
        "ingest", help="stage canonical dataset files from a directory "
                       "into the cache, then selfcheck")
    p_ing.add_argument("source", help="directory holding the files")
    p_ing.add_argument("--data-dir", default=None)
    p_chk = sub.add_parser(
        "selfcheck", help="validate + checksum whatever is cached")
    p_chk.add_argument("--data-dir", default=None)
    p_fet = sub.add_parser(
        "fetch", help="attempt mirror downloads of all three datasets, "
                      "then selfcheck")
    p_fet.add_argument("--data-dir", default=None)
    args = parser.parse_args(argv)
    data_dir = args.data_dir or _datasets_dir()
    if args.command == "ingest":
        report = ingest(args.source, data_dir)
    elif args.command == "fetch":
        for fn in (mnist_arrays, cifar10_arrays, stl10_arrays):
            try:
                fn(data_dir)
            except DatasetNotFound:
                pass
        report = selfcheck(data_dir)
    else:
        report = selfcheck(data_dir)
    print(_json.dumps(report, indent=1, sort_keys=True))
    statuses = [row.get("status") for name, row in report.items()
                if name in ("mnist", "cifar10", "stl10")]
    return 0 if "ok" in statuses else 1


class _SplitLoader(FullBatchLoader):
    """FullBatch loader over prebuilt (train, valid) arrays, laid out
    [valid | train] to match the loader class-window contract.
    Subclasses implement get_arrays() from picklable state so snapshots
    restore cleanly (the dataset is re-read, not pickled)."""

    def get_arrays(self):
        """-> (train_x, train_y, valid_x, valid_y)"""
        raise NotImplementedError

    WITH_LABELS = True

    def load_data(self):
        train_x, train_y, valid_x, valid_y = self.get_arrays()
        self.original_data = numpy.concatenate([valid_x, train_x])
        if self.WITH_LABELS:
            self.original_labels = numpy.concatenate(
                [valid_y, train_y])
        self.class_lengths[0] = 0
        self.class_lengths[1] = len(valid_x)
        self.class_lengths[2] = len(train_x)


class _SplitLoaderMSE(FullBatchLoaderMSE, _SplitLoader):
    """_SplitLoader layout with reconstruction targets == inputs (the
    autoencoder feed); one copy of the [valid|train] class-window
    contract for both label and MSE variants.  Labels are skipped —
    a reconstruction task would otherwise pay per-step label gathers
    it never reads."""

    WITH_LABELS = False

    def load_data(self):
        super(_SplitLoaderMSE, self).load_data()
        self.original_targets = numpy.array(self.original_data.mem,
                                            copy=True)


class MnistLoader(_SplitLoader):
    """MNIST-784 through the standard FullBatch HBM-resident path; the
    10k test set serves as the validation class (how the reference's
    1.48 % number is defined)."""

    def __init__(self, workflow, data_dir=None, **kwargs):
        super(MnistLoader, self).__init__(workflow, **kwargs)
        self.data_dir = data_dir

    def get_arrays(self):
        return mnist_arrays(self.data_dir)


class DigitsLoader(_SplitLoader):
    """Offline real-data anchor: sklearn's 1,797 handwritten digits."""

    def __init__(self, workflow, validation_count=360, seed=4, **kwargs):
        super(DigitsLoader, self).__init__(workflow, **kwargs)
        self.validation_count = validation_count
        self.split_seed = seed

    def get_arrays(self):
        return digits_arrays(self.validation_count, self.split_seed)


class Cifar10Loader(_SplitLoader):
    """CIFAR-10 (32x32x3) with the 10k test batch as validation."""

    def __init__(self, workflow, data_dir=None, **kwargs):
        super(Cifar10Loader, self).__init__(workflow, **kwargs)
        self.data_dir = data_dir

    def get_arrays(self):
        return cifar10_arrays(self.data_dir)


class Stl10Loader(_SplitLoader):
    """STL-10 (96x96x3) with the 8k test split as validation."""

    def __init__(self, workflow, data_dir=None, **kwargs):
        super(Stl10Loader, self).__init__(workflow, **kwargs)
        self.data_dir = data_dir

    def get_arrays(self):
        return stl10_arrays(self.data_dir)


if __name__ == "__main__":
    raise SystemExit(_main())
