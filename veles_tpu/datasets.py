"""Real-dataset access: MNIST/CIFAR-10 (cached on disk) + an offline
real-data anchor (scikit-learn's bundled UCI handwritten digits).

Reference parity: the reference's model-quality table
(/root/reference/docs/source/manualrst_veles_algorithms.rst:31,50) is
defined on MNIST (1.48 % validation error, 784-100-10) and CIFAR-10
(17.21 %, conv).  Those datasets are not redistributable inside this
repo and the build environment has no network egress, so this module:

- parses the standard idx / CIFAR-python formats from
  ``root.common.dirs.datasets`` (or ``$VELES_DATA``) when the user has
  the files, downloading them first when the network allows;
- always provides :func:`digits_arrays` — 1,797 real 8x8 handwritten
  digits that ship inside scikit-learn — so the full
  loader->workflow->decision->snapshotter quality path is exercised on
  genuine data even fully offline (see tests/test_quality.py and
  scripts/quality.py).
"""

import gzip
import os
import pickle
import struct

import numpy

from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader, \
    FullBatchLoaderMSE

__all__ = ["DatasetNotFound", "load_idx", "mnist_arrays", "MnistLoader",
           "digits_arrays", "DigitsLoader", "cifar10_arrays",
           "Cifar10Loader"]

MNIST_URLS = [
    # canonical mirrors of the Yann LeCun idx files
    "https://storage.googleapis.com/cvdf-datasets/mnist/",
    "https://ossci-datasets.s3.amazonaws.com/mnist/",
]
MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}


class DatasetNotFound(Exception):
    """Raised when a dataset is neither cached nor downloadable."""


def _datasets_dir():
    path = root.common.dirs.get("datasets")
    os.makedirs(path, exist_ok=True)
    return path


def load_idx(path):
    """Parse one idx file (optionally .gz): big-endian magic + dims."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fin:
        raw = fin.read()
    zeros, dtype_code, ndim = struct.unpack(">HBB", raw[:4])
    if zeros != 0:
        raise ValueError("bad idx magic in %s" % path)
    dtypes = {0x08: numpy.uint8, 0x09: numpy.int8, 0x0B: numpy.int16,
              0x0C: numpy.int32, 0x0D: numpy.float32, 0x0E: numpy.float64}
    dtype = numpy.dtype(dtypes[dtype_code]).newbyteorder(">")
    shape = struct.unpack(">" + "I" * ndim, raw[4:4 + 4 * ndim])
    data = numpy.frombuffer(raw, dtype, offset=4 + 4 * ndim)
    return data.reshape(shape)


def _fetch(filename, data_dir):
    """Return the local path for *filename*, downloading if needed."""
    for candidate in (os.path.join(data_dir, filename),
                      os.path.join(data_dir, "mnist", filename)):
        if os.path.exists(candidate):
            return candidate
        raw = candidate[:-3] if candidate.endswith(".gz") else None
        if raw and os.path.exists(raw):
            return raw
    import urllib.error
    import urllib.request
    target = os.path.join(data_dir, filename)
    for base in MNIST_URLS:
        try:
            urllib.request.urlretrieve(base + filename, target)
            return target
        except (urllib.error.URLError, OSError):
            continue
    raise DatasetNotFound(
        "MNIST file %s not found under %s and download failed; place "
        "the idx files there or set $VELES_DATA" % (filename, data_dir))


def mnist_arrays(data_dir=None):
    """(train_x f32 [60000,784] in [0,1], train_y i32, test_x, test_y)."""
    data_dir = data_dir or _datasets_dir()
    out = {}
    for key, filename in MNIST_FILES.items():
        arr = load_idx(_fetch(filename, data_dir))
        if key.endswith("images"):
            arr = (arr.reshape(arr.shape[0], -1).astype(numpy.float32) /
                   255.0)
        else:
            arr = arr.astype(numpy.int32)
        out[key] = arr
    return (out["train_images"], out["train_labels"],
            out["test_images"], out["test_labels"])


def digits_arrays(validation_count=360, seed=4):
    """Real handwritten digits (sklearn-bundled UCI dataset), split
    deterministically: (train_x, train_y, valid_x, valid_y).

    1,797 8x8 grayscale digits, features scaled to [0,1]."""
    from sklearn.datasets import load_digits
    bunch = load_digits()
    x = (bunch.data / 16.0).astype(numpy.float32)
    y = bunch.target.astype(numpy.int32)
    rng = numpy.random.RandomState(seed)
    order = rng.permutation(len(x))
    x, y = x[order], y[order]
    return (x[validation_count:], y[validation_count:],
            x[:validation_count], y[:validation_count])


def cifar10_arrays(data_dir=None):
    """(train_x f32 [50000,32,32,3] in [0,1], train_y, test_x, test_y)
    from the python-pickle CIFAR-10 batches."""
    data_dir = data_dir or _datasets_dir()
    for sub in ("cifar-10-batches-py", "cifar10", "."):
        base = os.path.join(data_dir, sub)
        if os.path.exists(os.path.join(base, "data_batch_1")):
            break
    else:
        raise DatasetNotFound(
            "CIFAR-10 python batches not found under %s" % data_dir)

    def read_batch(name):
        with open(os.path.join(base, name), "rb") as fin:
            batch = pickle.load(fin, encoding="bytes")
        data = batch[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return (data.astype(numpy.float32) / 255.0,
                numpy.array(batch[b"labels"], numpy.int32))

    xs, ys = zip(*[read_batch("data_batch_%d" % i) for i in range(1, 6)])
    test_x, test_y = read_batch("test_batch")
    return (numpy.concatenate(xs), numpy.concatenate(ys), test_x, test_y)


class _SplitLoader(FullBatchLoader):
    """FullBatch loader over prebuilt (train, valid) arrays, laid out
    [valid | train] to match the loader class-window contract.
    Subclasses implement get_arrays() from picklable state so snapshots
    restore cleanly (the dataset is re-read, not pickled)."""

    def get_arrays(self):
        """-> (train_x, train_y, valid_x, valid_y)"""
        raise NotImplementedError

    WITH_LABELS = True

    def load_data(self):
        train_x, train_y, valid_x, valid_y = self.get_arrays()
        self.original_data = numpy.concatenate([valid_x, train_x])
        if self.WITH_LABELS:
            self.original_labels = numpy.concatenate(
                [valid_y, train_y])
        self.class_lengths[0] = 0
        self.class_lengths[1] = len(valid_x)
        self.class_lengths[2] = len(train_x)


class _SplitLoaderMSE(FullBatchLoaderMSE, _SplitLoader):
    """_SplitLoader layout with reconstruction targets == inputs (the
    autoencoder feed); one copy of the [valid|train] class-window
    contract for both label and MSE variants.  Labels are skipped —
    a reconstruction task would otherwise pay per-step label gathers
    it never reads."""

    WITH_LABELS = False

    def load_data(self):
        super(_SplitLoaderMSE, self).load_data()
        self.original_targets = numpy.array(self.original_data.mem,
                                            copy=True)


class MnistLoader(_SplitLoader):
    """MNIST-784 through the standard FullBatch HBM-resident path; the
    10k test set serves as the validation class (how the reference's
    1.48 % number is defined)."""

    def __init__(self, workflow, data_dir=None, **kwargs):
        super(MnistLoader, self).__init__(workflow, **kwargs)
        self.data_dir = data_dir

    def get_arrays(self):
        return mnist_arrays(self.data_dir)


class DigitsLoader(_SplitLoader):
    """Offline real-data anchor: sklearn's 1,797 handwritten digits."""

    def __init__(self, workflow, validation_count=360, seed=4, **kwargs):
        super(DigitsLoader, self).__init__(workflow, **kwargs)
        self.validation_count = validation_count
        self.split_seed = seed

    def get_arrays(self):
        return digits_arrays(self.validation_count, self.split_seed)


class Cifar10Loader(_SplitLoader):
    """CIFAR-10 (32x32x3) with the 10k test batch as validation."""

    def __init__(self, workflow, data_dir=None, **kwargs):
        super(Cifar10Loader, self).__init__(workflow, **kwargs)
        self.data_dir = data_dir

    def get_arrays(self):
        return cifar10_arrays(self.data_dir)
