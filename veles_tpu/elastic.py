"""Elastic-fleet bookkeeping: membership epochs, power-weighted
partition of an epoch's unserved sample space, and the shared
straggler-speculation threshold math.

VELES's control plane was built for a fixed fleet; TensorFlow's system
design (PAPERS.md, 1605.08695) treats dynamic worker membership and
speculative re-execution as first-class.  This module holds the pure
math and bookkeeping both planes share:

- :class:`FleetView` — the master's view of live membership.  Every
  join and leave bumps a **membership epoch**; the Server stamps jobs
  and rejects updates from departed members (docs/distributed.md,
  "Elasticity contract"), so a preempted chip's late duplicate can
  never double-apply work that was requeued at drop time.
- :func:`power_shares` — largest-remainder apportionment of the
  epoch's *unserved remainder* among live slaves weighted by their
  reported computing power.  Pushed to slaves on every reshard so the
  fleet knows its fair split without restarting the run.
- :func:`speculation_threshold` — the straggler bar (lifted from
  jobfarm's backup-copy logic): once an in-flight job is older than
  ``factor x`` the mean completed duration (power-corrected, floored),
  an idle peer shadows it and the first result wins.

All power inputs are **degenerate-safe**: a zero, negative or
non-finite rating (a failed benchmark, a corrupt handshake) is
neutralized to the baseline 1.0 before any division, so the threshold
and partition math never divide by a sick fleet aggregate.
"""

import hashlib
import math

__all__ = ["FleetView", "effective_power", "fleet_mean_power",
           "power_shares", "speculation_threshold", "fleet_snapshot",
           "shard_owners", "movement_plan", "POWER_SCALE_BOUND"]

#: Bound on the power correction applied to the speculation threshold:
#: a chip rated 100x slower than the fleet mean must still be
#: speculated *eventually* — unbounded runway would turn one absurd
#: rating into a job that is never shadowed.
POWER_SCALE_BOUND = 8.0


def effective_power(power):
    """A slave's power rating, sanitized for use in ratios.

    Zero, negative, non-finite, or non-numeric ratings (the client
    reports 1.0 on a failed benchmark, but a corrupt handshake can
    ship anything) collapse to the neutral 1.0 — the same weight the
    client itself falls back to — so fleet aggregates stay positive
    and every division downstream is safe.
    """
    try:
        value = float(power)
    except (TypeError, ValueError):
        return 1.0
    if not math.isfinite(value) or value <= 0.0:
        return 1.0
    return value


def power_shares(total, powers):
    """Apportion ``total`` work units among members by power.

    ``powers`` maps member key -> reported power rating.  Returns
    {key: integer share}, shares summing exactly to ``total``
    (largest-remainder method: floors first, then the biggest
    fractional parts pick up the leftover units; ties broken by key so
    the split is deterministic).  Empty fleet or unknown/negative
    total -> {} (nothing to partition).
    """
    if not powers or total is None or total < 0:
        return {}
    total = int(total)
    eff = {key: effective_power(p) for key, p in powers.items()}
    aggregate = sum(eff.values())  # > 0: effective_power is positive
    exact = {key: total * p / aggregate for key, p in eff.items()}
    shares = {key: int(exact[key]) for key in eff}
    leftover = total - sum(shares.values())
    for key in sorted(eff, key=lambda k: (shares[k] - exact[k],
                                          str(k)))[:leftover]:
        shares[key] += 1
    return shares


def _hrw(shard, member):
    """Rendezvous (highest-random-weight) score of ``member`` for
    ``shard`` — a keyed 64-bit hash, so each shard ranks the member
    set in an order that is stable across processes and independent of
    which OTHER members exist (the property that makes membership
    churn move only the affected shards)."""
    digest = hashlib.blake2b(
        ("%s|%s" % (shard, member)).encode(), digest_size=8).digest()
    return int.from_bytes(digest, "big")


def shard_owners(n_shards, members, previous=None):
    """Consistent-hash assignment of ``n_shards`` logical state shards
    to ``members`` (hashable keys), balanced to exact quotas.

    Rendezvous hashing gives every shard a preference order over the
    member set; the assignment is then rebalanced so each member owns
    ``floor(n_shards/len(members))`` or one more — the uneven remainder
    lands on the members the shard space prefers.  With ``previous``
    (the pre-churn ``{shard: member}`` map) the rebalance is
    *minimal-move*: a shard keeps its old owner unless that owner left
    or sits over quota, so one leave moves ~n_shards/N shards (the
    departed member's) and one join moves ~n_shards/N' (one shed per
    over-quota member), never a full reshuffle.  Deterministic for a
    given (n_shards, member set, previous).  Returns {shard: member}.
    """
    keys = sorted(set(members), key=str)
    if not keys:
        raise ValueError("shard_owners: empty member set")
    n = len(keys)
    base, extra = divmod(int(n_shards), n)
    # quota: the members ranked highest by the whole shard space get
    # the remainder — deterministic in the member set alone, so the
    # same fleet always computes the same quotas
    rank = sorted(keys, key=lambda m: (-_hrw("quota", m), str(m)))
    quota = {m: base + (1 if rank.index(m) < extra else 0)
             for m in keys}
    held = {m: [] for m in keys}
    pool = []
    for shard in range(int(n_shards)):
        owner = (previous or {}).get(shard)
        if owner in quota:
            held[owner].append(shard)
        else:
            pool.append(shard)
    # over-quota members shed the shards that prefer them LEAST —
    # those are exactly the shards most likely to prefer the joiner
    for m in keys:
        if len(held[m]) > quota[m]:
            held[m].sort(key=lambda s: (-_hrw(s, m), s))
            pool.extend(held[m][quota[m]:])
            held[m] = held[m][:quota[m]]
    # the pool (new/orphaned/shed shards) lands by preference order,
    # respecting quotas; ties broken by shard id for determinism
    for shard in sorted(pool):
        prefs = sorted(keys, key=lambda m: (-_hrw(shard, m), str(m)))
        for m in prefs:
            if len(held[m]) < quota[m]:
                held[m].append(shard)
                break
    owners = {}
    for m, shards in held.items():
        for shard in shards:
            owners[shard] = m
    return owners


def movement_plan(previous, owners):
    """The shards a reshard actually moves: those whose owner changed
    between ``previous`` and ``owners`` (both ``{shard: member}``).
    New shards (absent from ``previous``) count as moved — they must
    be materialized on their owner either way.  The ``changed_fraction``
    against the full shard count is the receipt the consistent-hash
    claim is audited by (a full gather would move fraction 1.0)."""
    moved = sorted(s for s in owners
                   if previous is None or previous.get(s) != owners[s])
    total = max(len(owners), 1)
    return {
        "moved": moved,
        "n_moved": len(moved),
        "n_shards": len(owners),
        "changed_fraction": len(moved) / float(total),
    }


def fleet_mean_power(fleet_powers):
    """Mean sanitized power of a fleet (> 0 by construction), or None
    for an empty fleet.  Hoist this out of per-job speculation loops:
    only the owner's power varies job-to-job, so the fleet pass need
    not be repeated per candidate."""
    fleet = [effective_power(p) for p in fleet_powers]
    if not fleet:
        return None
    return sum(fleet) / len(fleet)


def speculation_threshold(mean_duration, factor, floor,
                          owner_power=None, fleet_powers=(),
                          mean_power=None):
    """Age (seconds) past which an in-flight job counts as straggling.

    ``factor x mean_duration`` is the MapReduce backup-task bar the
    jobfarm pioneered here; ``floor`` keeps millisecond-scale jobs
    from speculating their whole tail.  When the fleet reports power
    ratings, the bar is *power-corrected*: a job on a chip rated below
    the fleet mean gets proportionally more runway (and a fast chip
    less), bounded by :data:`POWER_SCALE_BOUND` so one absurd rating
    cannot make a job unspeculatable.  All aggregates are
    degenerate-safe (zero/negative/single-member fleets included) via
    :func:`effective_power`.  Callers looping over candidate jobs
    should hoist :func:`fleet_mean_power` and pass ``mean_power``
    (``fleet_powers`` is then ignored).
    """
    try:
        mean = float(mean_duration)
    except (TypeError, ValueError):
        mean = 0.0
    if not math.isfinite(mean) or mean < 0.0:
        mean = 0.0
    if mean_power is None:
        mean_power = fleet_mean_power(fleet_powers)
    scale = 1.0
    if mean_power is not None:
        scale = mean_power / effective_power(owner_power)
        scale = min(max(scale, 1.0 / POWER_SCALE_BOUND),
                    POWER_SCALE_BOUND)
    return max(float(factor) * mean * scale, float(floor))


class FleetView(object):
    """The master's live-membership ledger.

    Every :meth:`join` and :meth:`leave` bumps ``membership_epoch`` —
    the monotonically increasing counter the Server stamps on jobs and
    reshard pushes.  An update arriving from a slave that left at
    epoch E is *stale* with respect to every epoch > E: its work was
    requeued when it left, so the Server drops the duplicate instead
    of applying it (the exactly-once half of the elasticity contract).

    Besides the static power ratings the handshake reports, the view
    can track **measured throughput** per member as an EMA
    (:meth:`observe_throughput`): the serve fleet weights its routing
    and hedging by what hosts actually deliver, not what they claimed
    at join time (``shares(..., by="throughput")`` is the matching
    share mode).  Every observation is sanitized exactly like
    :func:`effective_power` — a member reporting zero/negative/NaN
    throughput contributes the neutral 1.0, never a sick aggregate —
    and an unobserved (cold-start) member reads 1.0 until its first
    real sample lands.
    """

    def __init__(self, throughput_alpha=0.2):
        self.membership_epoch = 0
        self.members = {}  # sid -> reported power rating
        #: EMA smoothing for measured throughput: weight of the NEWEST
        #: observation (0 < alpha <= 1; 1 = no smoothing)
        self.throughput_alpha = min(max(float(throughput_alpha),
                                        1e-6), 1.0)
        self._throughput = {}  # sid -> sanitized EMA

    def __len__(self):
        return len(self.members)

    def join(self, sid, power):
        """Admit ``sid``; returns the new membership epoch."""
        self.members[sid] = power
        self.membership_epoch += 1
        return self.membership_epoch

    def leave(self, sid):
        """Retire ``sid``; returns the (possibly bumped) epoch.  An
        unknown sid does not bump — a double drop is not a membership
        change.  The throughput EMA is forgotten with the member: a
        rejoin restarts cold (its old rate is stale evidence)."""
        if sid in self.members:
            del self.members[sid]
            self._throughput.pop(sid, None)
            self.membership_epoch += 1
        return self.membership_epoch

    def observe_throughput(self, sid, rate):
        """Fold one measured throughput sample (e.g. rows/second) into
        ``sid``'s EMA; returns the new EMA.  The FIRST observation
        seeds the EMA directly (no bias toward the neutral baseline);
        each later one decays in with ``throughput_alpha``.  Sick
        samples (zero/negative/NaN/garbage) are neutralized to 1.0
        BEFORE the fold, mirroring :func:`effective_power`, so one
        corrupt report can dent the EMA but never poison it."""
        rate = effective_power(rate)
        prev = self._throughput.get(sid)
        if prev is None:
            ema = rate
        else:
            alpha = self.throughput_alpha
            ema = alpha * rate + (1.0 - alpha) * prev
        self._throughput[sid] = ema
        return ema

    def throughput(self, sid, default=1.0):
        """``sid``'s throughput EMA, or ``default`` before any
        observation (cold start) / for unknown members.  The neutral
        1.0 keeps aggregates safe; callers that can substitute a
        better prior (the serve router uses the fleet mean so a cold
        host competes for traffic instead of starving against
        measured absolute rates) pass ``default=None`` and handle the
        miss themselves."""
        return self._throughput.get(sid, default)

    def throughputs(self):
        """Per-member throughput EMAs for the live fleet (cold members
        at the neutral 1.0) — threshold/aggregate inputs."""
        return [self.throughput(sid) for sid in self.members]

    def shares(self, remaining, by="power"):
        """Split of ``remaining`` work units across the live fleet
        ({} when the remainder is unknown): ``by="power"`` weights by
        the static reported ratings, ``by="throughput"`` by the
        measured EMAs (the serve tier's mode)."""
        if by == "throughput":
            weights = {sid: self.throughput(sid)
                       for sid in self.members}
            return power_shares(remaining, weights)
        return power_shares(remaining, self.members)

    def powers(self):
        """The live fleet's raw power ratings (threshold inputs)."""
        return list(self.members.values())


#: Fleet keys surfaced to dashboards: registry name -> short name
#: (the elastic mirror of observe.metrics._HEALTH_KEYS).
_FLEET_KEYS = (
    ("elastic.membership_epoch", "membership_epoch"),
    ("elastic.fleet_live", "live"),
    ("elastic.speculative_inflight", "speculative_inflight"),
    ("elastic.reshards", "reshards"),
    ("elastic.speculative_jobs", "speculative_jobs"),
    ("elastic.duplicates_dropped", "duplicates_dropped"),
    ("elastic.stale_updates", "stale_updates"),
    ("elastic.drops_deferred", "drops_deferred"),
    ("server.blacklist_size", "blacklisted"),
    ("server.quarantined", "quarantined"),
)


def fleet_snapshot(reg=None):
    """The elastic-fleet counters as a flat dict for the web-status
    fleet column and post-mortems: membership epoch, live/blacklisted/
    quarantined counts, speculation and exactly-once accounting.  Only
    metrics a server actually published appear ({} on slaves and
    standalone runs)."""
    from veles_tpu.observe.metrics import snapshot_keys
    return snapshot_keys(_FLEET_KEYS, reg)
