"""Deterministic fault injection for recovery testing.

VELES's operational claim is that training runs SURVIVE failures —
slaves drop and rejoin, snapshots are the resume point.  The reference
proved it with ad-hoc knobs (client ``death_probability``); this module
generalizes them into one seeded, deterministic harness so every
recovery path in the checkpoint and control planes can be exercised by
tests instead of assumed (the MapReduce lesson: speculation and
re-execution are only trustworthy because they run on every job).

Model: a :class:`FaultPlan` holds named *injection points* with a
trigger (fire on the Nth hit, with probability p, or always) and an
*action* string the site interprets.  Sites are pre-wired at the
failure surface of a run:

==================  =========================  =========================
point               module                     actions
==================  =========================  =========================
``net.send``        network_common.write_frame drop, delay (sender
                                               stall — blocks that
                                               peer's loop), truncate,
                                               corrupt
``net.recv``        network_common.read_frame  corrupt, delay (per-
                                               frame latency, awaited)
``server.serve``    server.Server._serve_job   kill, stall
``server.reshard``  server.Server._reshard     kill (sever one conn
                                               mid-reshard-push — the
                                               kill-during-reshard
                                               case the exactly-once
                                               update guarantee must
                                               survive; fires per
                                               slave pushed)
``client.job``      client.Client._job_loop    die
``slave.preempt``   client.Client._job_loop    kill (SIGKILL SELF —
                                               real preemption for
                                               subprocess soaks; use
                                               ``client.job=die`` for
                                               in-process tests.
                                               aK-style schedules,
                                               e.g. ``kill:a4:x1``,
                                               preempt after K clean
                                               jobs)
``slave.rejoin_after``  soak drivers           (no action verb: the
                    (scripts/elastic_soak.py)  fired fault's *param*
                                               is the seconds a
                                               driver waits before
                                               respawning the
                                               preempted slave; an
                                               aK/xM schedule shapes
                                               the rejoin cadence)
``net.update``      client (update send)       nan (poison the update
                                               payload's float arrays;
                                               param overrides the
                                               poison value)
``snapshot.write``  snapshotter (atomic write) crash, enospc
``pipeline.serve``  pipeline_input worker      exc
``step.grad``       models.fused / nn_units    nan (non-finite
                                               gradients: fused step
                                               adds the poison to every
                                               grad leaf, per-unit path
                                               poisons err_output)
``step.loss``       models.fused (train step)  nan (non-finite loss,
                                               gradients untouched)
``serve.drop``      serve.batcher (submit)     drop (request shed with
                                               503 + retry_after)
``serve.stall``     serve.batcher (worker)     stall (worker sleeps
                                               ``param`` s before the
                                               batch — trips the
                                               latency SLO watch)
``serve.oom``       serve.batcher (dispatch)   oom (simulated
                                               RESOURCE_EXHAUSTED —
                                               batcher caps the ladder
                                               and replays in chunks)
``freshness.publish``  snapshotter             truncate (torn
                    (publish_snapshot)         NON-atomic copy at the
                                               final published path —
                                               the watcher must
                                               skip-and-retry, not
                                               load), crash (die after
                                               the copy, before the
                                               LATEST flip — stale
                                               pointer, burned
                                               ordinal)
``serve.host.stall``  serve.transport          stall (this served
                    (per served frame)         frame parks ``param``
                                               seconds — the induced
                                               straggler the fleet's
                                               request hedging must
                                               beat; a pipelined
                                               stall parks only its
                                               own request, never the
                                               link)
``serve.host.preempt``  serve.transport        kill (SIGKILL SELF —
                    (per served frame)         real mid-stream host
                                               death for the
                                               fleet_soak subprocess
                                               hosts; aK schedules
                                               preempt after K clean
                                               frames), sever (drop
                                               the connection — the
                                               in-process stand-in:
                                               the router sees the
                                               link die and requeues)
``serve.hedge.lose_race``  serve.fleet         (any action: the
                    (router, per hedge         router SKIPS the
                    loser)                     loser's wire cancel,
                                               so the losing copy
                                               completes and its late
                                               result exercises the
                                               duplicate-rejection
                                               fence deterministically)
``mesh.reshard``    parallel.mesh              crash (die after the
                    (MeshManager._reshard)     safety snapshot, before
                                               destructive shard
                                               movement —
                                               ``MeshManager.resume``
                                               / ``--resume auto``
                                               recovers bit-exactly)
``serve.tenant.flood``  serve.batcher          (any action: ``param``
                    (per admission)            — default 32 —
                                               best_effort requests
                                               flood the queue as real
                                               load, so the class-
                                               ordered shedder must
                                               evict THEM to admit the
                                               arriving request — the
                                               QoS soak's noisy-
                                               neighbor tenant)
==================  =========================  =========================

(``snapshot.write`` also covers ``serve.freshness``'s
``export_model_spec`` — a trainer crash mid-export leaves a torn
``.tmp`` and no final file, the same contract as the Snapshotter.)

Activation: programmatic (``chaos.install(FaultPlan(...))`` /
``chaos.uninstall()``) or via ``VELES_CHAOS`` in the environment, e.g.
``VELES_CHAOS="seed=42;net.recv=corrupt:n3;snapshot.write=crash:n2"``.
Every site guards with ``if chaos.plan is not None`` — a disabled
harness costs one global load per site, nothing else.

Determinism: triggers count HITS per point under a lock, and the
probability stream comes from one seeded ``random.Random``, so a given
plan against a deterministic run always fires at the same places.
"""

import errno
import os
import random
import threading

__all__ = ["Fault", "FaultPlan", "ChaosCrash", "install", "uninstall",
           "install_from_env", "plan"]


class ChaosCrash(BaseException):
    """Simulated sudden process death (the in-process stand-in for
    ``kill -9``).  Derives from BaseException on purpose: recovery code
    that swallows ``Exception`` must NOT accidentally survive a
    simulated crash — only the test harness catches this."""


class Fault(object):
    """One armed injection: where, what, and when it fires."""

    __slots__ = ("point", "action", "nth", "probability", "times",
                 "param", "after", "hits", "fired")

    def __init__(self, point, action, nth=None, probability=None,
                 times=None, param=None, after=None):
        self.point = point
        self.action = action
        self.nth = nth                  # fire on the Nth hit (1-based)
        self.probability = probability  # else: fire with probability p
        self.times = times              # max firings (None = unlimited)
        self.param = param              # action parameter (e.g. delay s)
        self.after = after              # stay silent for the first N hits
        self.hits = 0
        self.fired = 0

    def _should_fire(self, rng):
        self.hits += 1
        if self.after is not None and self.hits <= self.after:
            return False
        if self.times is not None and self.fired >= self.times:
            return False
        if self.nth is not None:
            return self.hits == self.nth
        if self.probability is not None:
            return rng.random() < self.probability
        return True  # unconditional

    def __repr__(self):
        trig = ("n%d" % self.nth if self.nth is not None else
                "p%g" % self.probability if self.probability is not None
                else "*")
        if self.after is not None:
            trig += ":a%d" % self.after
        return "<Fault %s=%s:%s hits=%d fired=%d>" % (
            self.point, self.action, trig, self.hits, self.fired)


class FaultPlan(object):
    """A seeded set of faults; ``fire(point)`` is the only hot call."""

    def __init__(self, seed=0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._faults = {}
        self._lock = threading.Lock()
        #: chronological (point, action, hit#) record of every firing
        self.log = []

    def add(self, point, action, nth=None, probability=None, times=None,
            param=None, after=None):
        fault = Fault(point, action, nth=nth, probability=probability,
                      times=times, param=param, after=after)
        self._faults.setdefault(point, []).append(fault)
        return self

    def fire(self, point):
        """Count a hit at ``point``; return the triggered :class:`Fault`
        or None.  Thread-safe and deterministic for a given hit order."""
        faults = self._faults.get(point)
        if not faults:
            return None
        with self._lock:
            for fault in faults:
                if fault._should_fire(self._rng):
                    fault.fired += 1
                    self.log.append((point, fault.action, fault.hits))
                    return fault
        return None

    def fired(self, point=None):
        """Total firings (optionally for one point) — test assertions."""
        return sum(1 for p, _, _ in self.log
                   if point is None or p == point)

    @classmethod
    def from_spec(cls, spec):
        """Parse ``"seed=42;point=action[:trigger[:param]];..."``.

        Trigger: ``nK`` = Kth hit exactly once, ``pX`` = probability X
        per hit, ``xM`` = at most M unconditional firings, ``aK`` =
        stay silent for the first K hits (composes with the others:
        ``nan:a8:x12`` fires unconditionally on hits 9-20 — the
        sustained-fault window the nan-injection tests use),
        absent/``*`` = always.  Param is a float handed to the site
        (e.g. delay seconds, or the poison value for ``nan``)."""
        plan_seed = 0
        entries = []
        for entry in (spec or "").split(";"):
            entry = entry.strip()
            if not entry:
                continue
            if entry.startswith("seed="):
                plan_seed = int(entry[5:], 0)
                continue
            entries.append(entry)
        plan = cls(seed=plan_seed)
        for entry in entries:
            if "=" not in entry:
                raise ValueError(
                    "chaos spec entry must be point=action[:trigger]"
                    ", got %r" % entry)
            point, _, rhs = entry.partition("=")
            parts = rhs.split(":")
            action = parts[0]
            nth = probability = times = param = after = None
            for token in parts[1:]:
                if not token or token == "*":
                    continue
                if token.startswith("n"):
                    nth, times = int(token[1:]), 1
                elif token.startswith("p"):
                    probability = float(token[1:])
                elif token.startswith("x"):
                    times = int(token[1:])
                elif token.startswith("a"):
                    after = int(token[1:])
                else:
                    param = float(token)
            plan.add(point.strip(), action, nth=nth,
                     probability=probability, times=times, param=param,
                     after=after)
        return plan


#: the active plan; every injection site guards on ``is not None``, so
#: a disabled harness does exactly one global load per site
plan = None


def install(new_plan):
    """Activate a plan process-wide; returns it for chaining."""
    global plan
    plan = new_plan
    return new_plan


def uninstall():
    global plan
    plan = None


def install_from_env(env="VELES_CHAOS"):
    """Activate from the environment (no-op when unset/empty)."""
    spec = os.environ.get(env)
    if spec:
        return install(FaultPlan.from_spec(spec))
    return None


def enospc():
    """The ENOSPC OSError chaos sites raise (one place, one message)."""
    return OSError(errno.ENOSPC, "No space left on device (chaos)")


def poison_tree(obj, value=float("nan")):
    """A structural copy of a payload tree with every float leaf (array
    or scalar) replaced by ``value`` — the ``net.update=nan`` action's
    implementation.  Integer arrays, strings, and other non-float
    leaves pass through unchanged, so the poisoned payload still parses
    like a real update and only its *numerics* are sick (the failure
    mode the master's finiteness quarantine must catch)."""
    import numpy
    if isinstance(obj, dict):
        return {k: poison_tree(v, value) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(poison_tree(v, value) for v in obj)
    if isinstance(obj, numpy.ndarray) and obj.dtype.kind == "f":
        return numpy.full_like(obj, value)
    if isinstance(obj, float):
        return value
    return obj


install_from_env()
