"""Graphics transport: ZMQ PUB broadcasting pickled plotters.

Reference veles/graphics_server.py:65-174 bound inproc + ipc + EPGM
multicast endpoints and launched a matplotlib client subprocess; here
the PUB socket binds inproc + ipc + tcp and attempts the reference's
EPGM multicast endpoint too — engaged automatically on pgm-built zmq,
skipped with a log line on pgm-less builds (this image's zmq).  The
client (veles_tpu.graphics_client) renders to PNG files or an
interactive backend.
"""

import os
import tempfile

from veles_tpu.logger import Logger
from veles_tpu import plotter as plotter_module

__all__ = ["GraphicsServer"]


class GraphicsServer(Logger):
    def __init__(self, launcher=None):
        super(GraphicsServer, self).__init__()
        import zmq
        self.context = zmq.Context.instance()
        self.socket = self.context.socket(zmq.PUB)
        self.endpoints = {}
        port = self.socket.bind_to_random_port("tcp://127.0.0.1")
        self.endpoints["tcp"] = "tcp://127.0.0.1:%d" % port
        ipc_path = os.path.join(
            tempfile.gettempdir(),
            "veles-tpu-graphics-%d.ipc" % os.getpid())
        try:
            self.socket.bind("ipc://" + ipc_path)
            self.endpoints["ipc"] = "ipc://" + ipc_path
        except Exception:
            pass
        inproc = "inproc://veles-tpu-graphics"
        try:
            self.socket.bind(inproc)
            self.endpoints["inproc"] = inproc
        except Exception:
            pass
        # EPGM multicast (reference graphics_server.py:100-142): bound
        # when the zmq build ships pgm support; on pgm-less builds
        # (this image) the bind raises "protocol not supported" and
        # the capability is skipped — tcp/ipc/inproc carry the plots
        from veles_tpu.config import root
        mcast = root.common.graphics.get("multicast_address")
        if mcast:
            epgm = "epgm://%s:5555" % mcast
            try:
                self.socket.bind(epgm)
                self.endpoints["epgm"] = epgm
            except Exception as exc:
                self.info("EPGM multicast unavailable (%s): %s — "
                          "plots ride tcp/ipc/inproc", epgm, exc)
        if launcher is not None:
            launcher.graphics_server = self
        self.published = 0
        self.info("graphics server on %s", self.endpoints["tcp"])

    def publish(self, plot):
        self.socket.send(plotter_module.dumps(plot))
        self.published += 1

    def shutdown(self):
        self.socket.close(0)

    @staticmethod
    def launch_client(output_dir, endpoint, extra_env=None):
        """Spawn the renderer subprocess (reference launched
        graphics_client the same way)."""
        import subprocess
        import sys
        env = dict(os.environ)
        env.update(extra_env or {})
        return subprocess.Popen(
            [sys.executable, "-m", "veles_tpu.graphics_client",
             "--endpoint", endpoint, "--output", output_dir], env=env)
