"""Post-training int8 quantization (docs/serving.md "Quantized
ladder").

The serve-side cash-in of the TPU paper's 8-bit argument: a trained
f32 model spec (``plans``/``params``/``sample_shape`` — the same
triple ``serve.freshness.export_model_spec`` publishes) is calibrated
against a sample stream and rewritten with per-channel symmetric int8
weights plus per-layer activation scales.  The quantized spec is
*still* a model spec — it round-trips through ``export_model_spec`` /
``publish_snapshot`` and the freshness watcher unchanged, and an
:class:`~veles_tpu.serve.engine.AOTEngine` built from it detects the
quantized entries and compiles the int8 forward
(:mod:`veles_tpu.quant.forward` over ``ops/matmul_int8.py``) instead
of the f32 one — a quantized engine is "just another digest" to the
hot-reload/canary/rung-cap machinery.

- :mod:`veles_tpu.quant.ptq` — calibration (min/max or percentile
  activation ranges, clip-fraction accounting) and the weight
  quantization pass;
- :mod:`veles_tpu.quant.forward` — the quantized forward builder
  (``compiler.build_forward``'s int8 twin) and the spec predicates.
"""

from veles_tpu.quant.forward import (  # noqa: F401
    build_quantized_forward, is_quantized_entry, is_quantized_params)
from veles_tpu.quant.ptq import (  # noqa: F401
    CalibrationResult, calibrate_activations, calibration_dir,
    quantize_model_spec, quantize_tensor, quantize_weights)

__all__ = ["CalibrationResult", "build_quantized_forward",
           "calibrate_activations", "calibration_dir",
           "is_quantized_entry", "is_quantized_params",
           "quantize_model_spec", "quantize_tensor",
           "quantize_weights"]
