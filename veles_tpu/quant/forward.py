"""The quantized forward builder — ``compiler.build_forward``'s int8
twin.

Per layer: quantize the f32 activation onto the calibrated per-tensor
grid (``clip(round(x / act_scale), -127, 127)``), run the int8 Pallas
kernel (matmul for all2all layers, the im2col conv for conv layers —
both over the shared :func:`veles_tpu.ops.common.mxu_int8_dot` product
step) with int32 accumulation and the fused dequant epilogue
(``f32(acc) * (act_scale * weights_scale[c]) + bias``), then the
layer's own f32 activation function.  Activations carry f32 between
layers — the w8a8 recipe with an f32 spine, which keeps softmax /
tanh / pooling semantics untouched and lets non-quantized layers mix
freely in one ladder.

The builder consumes the entry layout :func:`veles_tpu.quant.ptq.
quantize_model_spec` produces; :func:`is_quantized_params` is how
:class:`~veles_tpu.serve.engine.AOTEngine` decides which forward to
compile — presence of ``weights_scale`` in any entry, nothing else,
so a quantized spec needs no side-channel flag through the snapshot /
publish / watcher pipeline.
"""

import functools

__all__ = ["build_quantized_forward", "f32_layer_apply",
           "is_quantized_entry", "is_quantized_params",
           "quantize_activation", "walk_forward"]


def is_quantized_entry(entry):
    """One layer's params are int8-quantized (pass artifacts present)."""
    return entry is not None and entry.get("weights_scale") is not None


def is_quantized_params(params):
    """True when ANY layer entry carries quantization artifacts — the
    AOTEngine's forward-selection predicate."""
    return any(is_quantized_entry(entry) for entry in params)


def quantize_activation(x, act_scale):
    """On-device activation quantization onto the calibrated symmetric
    grid.  ``jnp.round`` is round-half-even, the same rule as the
    host-side ``numpy.rint`` in ptq.py — one rounding rule everywhere."""
    import jax.numpy as jnp
    from veles_tpu.quant.ptq import QMAX
    q = jnp.round(x / act_scale)
    return jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)


def _apply_quantized(plan, entry, h):
    """One quantized layer: quantize input, int8 kernel with fused
    dequant+bias, f32 activation."""
    import jax.numpy as jnp

    from veles_tpu.models.conv import Conv
    from veles_tpu.ops.matmul_int8 import conv2d_int8, matmul_int8

    act_scale = entry["act_scale"].astype(jnp.float32)
    # combined dequant factor: activation scale x per-channel weight
    # scale, folded HERE so the kernel epilogue is one multiply
    scale = act_scale * entry["weights_scale"].astype(jnp.float32)
    bias = entry.get("bias")
    if issubclass(plan.forward_cls, Conv):
        x = h
        if x.ndim == 3:
            x = x[..., None]
        z = conv2d_int8(
            quantize_activation(x, act_scale), entry["weights"],
            scale, bias=bias,
            padding=plan.static.get("padding", (0, 0, 0, 0)),
            sliding=plan.static.get("sliding", (1, 1)))
    else:
        x2 = h.reshape(h.shape[0], -1)
        z = matmul_int8(quantize_activation(x2, act_scale),
                        entry["weights"], scale, bias=bias)
    return z


def walk_forward(plans, params, x, layer_fn):
    """The ONE inference layer walk the quantized forward AND the
    calibration pass share — mirroring ``compiler.build_forward``'s
    semantics (dropout is identity at inference, softmax applied only
    at the tail) so the walk rules cannot drift between the f32
    reference, the int8 twin and the statistics the scales are solved
    from.  ``layer_fn(i, plan, entry, h) -> h`` owns the per-layer
    arithmetic; dropout layers never reach it."""
    import jax

    from veles_tpu.models.all2all import All2AllSoftmax
    from veles_tpu.models.dropout import DropoutForward

    h = x
    for i, (plan, entry) in enumerate(zip(plans, params)):
        if issubclass(plan.forward_cls, DropoutForward):
            continue  # identity at inference (inverted dropout)
        h = layer_fn(i, plan, entry, h)
    if plans and plans[-1].forward_cls is All2AllSoftmax:
        h = jax.nn.softmax(h, axis=-1)
    return h


def f32_layer_apply(plan, entry, h):
    """One f32 layer step with ``build_forward``'s semantics: an
    All2AllSoftmax layer keeps its LOGITS (the tail softmax belongs to
    the walk), everything else runs its stock ``apply`` with the
    plan's static config."""
    from veles_tpu.models.all2all import All2All, All2AllSoftmax
    if plan.forward_cls is All2AllSoftmax:
        return All2All.apply(entry, h)
    return functools.partial(plan.forward_cls.apply,
                             **plan.static)(entry, h)


def build_quantized_forward(plans):
    """Pure inference fn(params_list, x) -> output, the int8 mirror of
    ``compiler.build_forward``: same layer walk (:func:`walk_forward`),
    same softmax tail, same dropout-is-identity rule — only the
    parameterized layers' arithmetic runs on the int8 level.  Entries
    without quantization artifacts run their stock f32 ``apply``, so
    partially-quantized specs work layer by layer."""
    def forward(params, x):
        import jax.numpy as jnp

        from veles_tpu.models.all2all import All2AllSoftmax

        def layer(i, plan, entry, h):
            if not is_quantized_entry(entry):
                return f32_layer_apply(plan, entry, h)
            z = _apply_quantized(plan, entry, h)
            if plan.forward_cls is All2AllSoftmax:
                return z  # keep logits; softmax applied at the tail
            return plan.forward_cls._activate(z).astype(jnp.float32)

        return walk_forward(plans, params, x, layer)
    return forward
