"""Post-training quantization pass: calibrate, scale, round, clip.

Scheme (the TPU paper's serving recipe, zero-point-free):

- **Weights**: per-output-channel symmetric scales — ``s_w[c] =
  max|W[..., c]| / 127`` (an all-zero channel gets scale 1.0 so the
  divide stays exact and the channel quantizes to zeros), ``W_q =
  clip(round(W / s_w), -127, 127)``.  Symmetric means no zero points:
  the int8 matmul needs no cross-term corrections and the dequant
  epilogue is one multiply.  ``weight_granularity="tensor"`` collapses
  to one scale per layer — kept for the accuracy A/B
  (tests/test_quant.py proves per-channel strictly tighter on
  channel-skewed weights), not for production.
- **Activations**: per-tensor symmetric scales calibrated from a
  sample stream run through the f32 forward — ``mode="minmax"`` takes
  the observed ``max|x|``; ``mode="percentile"`` (default) takes the
  ``percentile``-th percentile of ``|x|``, deliberately CLIPPING the
  outlier tail (saturating a few extreme activations costs less top-1
  than stretching the whole int8 grid to cover them).  The clipped
  fraction per layer is part of the calibration record and rides the
  ``serve.quant.clip_fraction`` gauge — a clip fraction drifting up
  between calibrations means the activation distribution moved and
  the scales are stale.

Biases stay f32 (they add AFTER the dequant epilogue; quantizing them
buys no MXU time and costs accuracy).  The calibration record is
written as JSON into :func:`calibration_dir` (``VELES_QUANT_CALIB``
overrides — the test suite routes it to tmp) so a published quantized
spec always has a sidecar saying how its scales were chosen.
"""

import json
import logging
import os

import numpy

from veles_tpu.observe.metrics import registry as _registry

__all__ = ["CalibrationResult", "calibrate_activations",
           "calibration_dir", "quantize_model_spec", "quantize_tensor",
           "quantize_weights", "QMAX"]

logger = logging.getLogger("veles_tpu.quant")

#: symmetric int8 grid: [-127, 127].  -128 is deliberately unused so
#: the grid is symmetric around zero and |q| * s never overflows the
#: magnitude the scale was solved for
QMAX = 127


def calibration_dir():
    """``$VELES_QUANT_CALIB`` or ``<root cache dir>/quant_calib`` —
    resolved per call so tests can redirect via the environment (the
    ``_calibration_to_tmp`` conftest fixture)."""
    env = os.environ.get("VELES_QUANT_CALIB", "")
    if env:
        return env
    from veles_tpu.config import root
    return os.path.join(root.common.dirs.get("cache", "/tmp"),
                        "quant_calib")


def quantize_tensor(x, scale):
    """``clip(round(x / scale), -127, 127)`` as int8 — numpy in, numpy
    out; ``numpy.rint`` is round-half-even, matching ``jnp.round`` so
    host-side weight quantization and the on-device activation
    quantization in :mod:`veles_tpu.quant.forward` share one rounding
    rule."""
    x = numpy.asarray(x, numpy.float32)
    q = numpy.rint(x / numpy.asarray(scale, numpy.float32))
    return numpy.clip(q, -QMAX, QMAX).astype(numpy.int8)


def quantize_weights(weights, granularity="channel"):
    """(W_q int8, scales f32 (Cout,)): per-output-channel symmetric
    quantization of a weight array — last axis is the output channel
    for both the all2all (fan_in, fan_out) and conv HWIO (ky, kx, Cin,
    Cout) layouts, so ONE reduction rule covers both families.
    ``granularity="tensor"`` broadcasts a single max-over-everything
    scale to the channel vector (same downstream shape, so the engine
    path is identical)."""
    w = numpy.asarray(weights, numpy.float32)
    cout = w.shape[-1]
    flat = numpy.abs(w.reshape(-1, cout))
    if granularity == "channel":
        amax = flat.max(axis=0)
    elif granularity == "tensor":
        amax = numpy.full((cout,), flat.max() if flat.size else 0.0,
                          numpy.float32)
    else:
        raise ValueError("granularity must be 'channel' or 'tensor', "
                         "got %r" % (granularity,))
    # an all-zero channel has no magnitude to solve a scale for: scale
    # 1.0 keeps the divide exact (0/1 == 0) and dequant returns zeros
    scales = numpy.where(amax > 0, amax / QMAX, 1.0).astype(
        numpy.float32)
    return quantize_tensor(w, scales), scales


class CalibrationResult(object):
    """Per-layer activation calibration: what the quantizer consumes
    and the sidecar JSON records."""

    __slots__ = ("mode", "percentile", "samples", "layers")

    def __init__(self, mode, percentile, samples, layers):
        self.mode = mode
        self.percentile = percentile
        self.samples = int(samples)
        self.layers = layers  # {layer index: {"act_scale", "amax",
        #                       "clip_fraction", "cls"}}

    @property
    def clip_fraction(self):
        """Mean clipped fraction over the calibrated layers — the
        one-number health signal the ``serve.quant.clip_fraction``
        gauge carries."""
        if not self.layers:
            return 0.0
        return float(numpy.mean(
            [e["clip_fraction"] for e in self.layers.values()]))

    def to_dict(self):
        return {"mode": self.mode, "percentile": self.percentile,
                "samples": self.samples,
                "clip_fraction": round(self.clip_fraction, 6),
                "layers": {str(i): dict(e)
                           for i, e in sorted(self.layers.items())}}

    def save(self, path=None):
        """Write the sidecar JSON record; returns the path."""
        if path is None:
            digest = "%08x" % (hash(tuple(sorted(
                (i, round(e["act_scale"], 9))
                for i, e in self.layers.items()))) & 0xffffffff)
            path = os.path.join(calibration_dir(),
                                "calib_%s.json" % digest)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fout:
            json.dump(self.to_dict(), fout, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path


def _quantizable(plan, entry):
    """True for layers the int8 path covers: parameterized all2all and
    conv forwards.  Everything else (pooling, dropout, and any future
    family) stays f32 — the quantized forward mixes levels per layer."""
    if entry.get("weights") is None:
        return False
    from veles_tpu.models.all2all import All2All
    from veles_tpu.models.conv import Conv
    return issubclass(plan.forward_cls, (All2All, Conv))


def calibrate_activations(plans, params, samples, mode="percentile",
                          percentile=99.9):
    """Run ``samples`` through the f32 forward, recording each
    quantizable layer's INPUT range; returns a
    :class:`CalibrationResult`.

    The stats are taken on the f32 activations (standard PTQ: the
    quantized net sees slightly different inputs layer by layer, but
    the drift is second-order next to the grid resolution).  The walk
    is the shared :func:`veles_tpu.quant.forward.walk_forward` —
    dropout identity, softmax-keeps-logits — so the statistics are
    solved on EXACTLY the activations the f32 reference produces; the
    stream should be representative serving traffic — a training-set
    slice or a traffic capture."""
    import jax.numpy as jnp

    from veles_tpu.quant.forward import f32_layer_apply, walk_forward

    if mode not in ("minmax", "percentile"):
        raise ValueError("mode must be 'minmax' or 'percentile', got %r"
                         % (mode,))
    x = numpy.asarray(samples, numpy.float32)
    if x.ndim and x.shape[0] == 0:
        raise ValueError("calibration needs a non-empty sample stream")
    layers = {}

    def record_then_apply(i, plan, entry, h):
        if _quantizable(plan, entry):
            vals = numpy.abs(numpy.asarray(h, numpy.float32)).ravel()
            full = float(vals.max()) if vals.size else 0.0
            if mode == "percentile" and vals.size:
                amax = float(numpy.percentile(vals, percentile))
            else:
                amax = full
            if amax <= 0:
                amax = 1.0  # degenerate stream: identity-safe scale
            clipped = float(numpy.mean(vals > amax)) if vals.size \
                else 0.0
            layers[i] = {
                "act_scale": amax / QMAX, "amax": amax,
                "observed_max": full,
                "clip_fraction": round(clipped, 6),
                "cls": plan.forward_cls.__name__}
        # advance on the f32 level; entries may carry solver state
        # (a zoo training state) — the forward sees weights/bias only
        fentry = {"weights": entry.get("weights"),
                  "bias": entry.get("bias")}
        return f32_layer_apply(plan, fentry, h)

    walk_forward(plans, params, jnp.asarray(x), record_then_apply)
    result = CalibrationResult(mode, percentile, x.shape[0], layers)
    _registry.gauge("serve.quant.clip_fraction").set(
        round(result.clip_fraction, 6))
    return result


def quantize_model_spec(plans, params, samples=None, calibration=None,
                        mode="percentile", percentile=99.9,
                        weight_granularity="channel",
                        save_report=True):
    """The post-training quantization pass: f32 (plans, params) -> the
    quantized params list; plans are unchanged (the architecture IS
    the same — only the arithmetic level differs).

    Quantizable entries come back as ``{"weights": int8,
    "weights_scale": f32 (Cout,), "act_scale": f32 scalar, "bias":
    f32}`` — arrays only, so ``AOTEngine._put_params`` ships them to
    the device unmodified and ``model_digest`` separates them from
    the f32 source by dtype and key set.  Non-quantizable entries
    keep their ``{"weights", "bias"}`` shape.  The result pickles
    through ``export_model_spec``/``publish_snapshot`` and back
    bit-identically (tests/test_quant.py round-trip).

    Pass ``samples`` (a calibration stream) or a precomputed
    ``calibration``; returns ``(qparams, calibration)``."""
    if calibration is None:
        if samples is None:
            raise ValueError("need samples or a CalibrationResult")
        calibration = calibrate_activations(
            plans, params, samples, mode=mode, percentile=percentile)
    qparams = []
    for i, (plan, entry) in enumerate(zip(plans, params)):
        if not _quantizable(plan, entry) or i not in calibration.layers:
            qparams.append({
                "weights": None if entry.get("weights") is None
                else numpy.asarray(entry["weights"], numpy.float32),
                "bias": None if entry.get("bias") is None
                else numpy.asarray(entry["bias"], numpy.float32)})
            continue
        w_q, scales = quantize_weights(entry["weights"],
                                       granularity=weight_granularity)
        qparams.append({
            "weights": w_q,
            "weights_scale": scales,
            "act_scale": numpy.asarray(
                calibration.layers[i]["act_scale"], numpy.float32),
            "bias": None if entry.get("bias") is None
            else numpy.asarray(entry["bias"], numpy.float32)})
    if save_report:
        try:
            path = calibration.save()
            logger.info("quantized %d/%d layers (%s, clip %.4f%%); "
                        "calibration record: %s",
                        len(calibration.layers), len(plans),
                        weight_granularity,
                        100.0 * calibration.clip_fraction, path)
        except OSError as exc:  # a read-only cache must not fail PTQ
            logger.warning("calibration record not written: %s", exc)
    return qparams, calibration
