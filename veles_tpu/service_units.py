"""Accelerated utility units over the ops layer.

Reference counterparts: InputJoiner (veles/input_joiner.py:49, the
join.jcl templated concat kernel), MeanDispNormalizer
(veles/mean_disp_normalizer.py:50, the (x-mean)*rdisp kernel), Avatar
(veles/avatar.py:22, device-side Array cloning), and the Shell
interaction unit (veles/interaction.py:49).
"""

import numpy

from veles_tpu.backends import NumpyDevice
from veles_tpu.memory import Array
from veles_tpu.units import Unit

__all__ = ["InputJoiner", "MeanDispNormalizer", "Avatar", "Shell"]


def _on_device(device):
    return device is not None and device.exists and \
        not isinstance(device, NumpyDevice)


class InputJoiner(Unit):
    """Concatenates N input Arrays along axis 1 (ops.join)."""

    def __init__(self, workflow, **kwargs):
        super(InputJoiner, self).__init__(workflow, **kwargs)
        self.inputs = list(kwargs.get("inputs", ()))
        self.output = Array()
        self.device = None

    def link_inputs(self, *pairs):
        """pairs: (unit, attr_name) whose Arrays join in order."""
        for unit, attr in pairs:
            self.inputs.append(getattr(unit, attr))
        return self

    def initialize(self, device=None, **kwargs):
        self.device = device
        super(InputJoiner, self).initialize(**kwargs)
        if not self.inputs:
            raise ValueError("InputJoiner needs at least one input")
        return True

    def run(self):
        if _on_device(self.device):
            from veles_tpu import ops
            for arr in self.inputs:
                arr.initialize(self.device)
            parts = [arr.devmem for arr in self.inputs]
            parts = [p.reshape(p.shape[0], -1) for p in parts]
            self.output.set_device_array(ops.join(*parts), self.device)
        else:
            mats = []
            for arr in self.inputs:
                arr.map_read()
                mats.append(arr.mem.reshape(len(arr.mem), -1))
            self.output.map_invalidate()
            self.output.mem = numpy.concatenate(mats, axis=1)


class MeanDispNormalizer(Unit):
    """output = (input - mean) * rdisp elementwise over samples."""

    def __init__(self, workflow, **kwargs):
        super(MeanDispNormalizer, self).__init__(workflow, **kwargs)
        self.input = None   # linked Array
        self.mean = None    # linked Array or ndarray
        self.rdisp = None
        self.output = Array()
        self.device = None
        self.demand("input", "mean", "rdisp")

    def initialize(self, device=None, **kwargs):
        self.device = device
        return super(MeanDispNormalizer, self).initialize(**kwargs)

    @staticmethod
    def _as_host(value):
        if hasattr(value, "map_read"):
            value.map_read()
            return value.mem
        return numpy.asarray(value)

    def run(self):
        if _on_device(self.device):
            from veles_tpu import ops
            mean = self.device.put(self._as_host(self.mean).ravel())
            rdisp = self.device.put(self._as_host(self.rdisp).ravel())
            self.input.initialize(self.device)
            x = self.input.devmem
            out = ops.mean_disp_normalize(
                x.reshape(x.shape[0], -1), mean, rdisp).reshape(x.shape)
            self.output.set_device_array(out, self.device)
        else:
            self.input.map_read()
            x = self.input.mem
            flat = x.reshape(len(x), -1).astype(numpy.float32)
            out = (flat - self._as_host(self.mean).ravel()) * \
                self._as_host(self.rdisp).ravel()
            self.output.map_invalidate()
            self.output.mem = out.reshape(x.shape)


class Avatar(Unit):
    """Copies a set of source Arrays to cloned output Arrays each run
    (device-side memcpy in the reference)."""

    def __init__(self, workflow, **kwargs):
        super(Avatar, self).__init__(workflow, **kwargs)
        self._pairs = []  # (source Array, clone Array)
        self.device = None

    def clone(self, unit, *attrs):
        """Mirror unit.<attr> into self.<attr>; returns self."""
        for attr in attrs:
            source = getattr(unit, attr)
            mirror = Array()
            setattr(self, attr, mirror)
            self._pairs.append((source, mirror))
        return self

    def initialize(self, device=None, **kwargs):
        self.device = device
        return super(Avatar, self).initialize(**kwargs)

    def run(self):
        for source, mirror in self._pairs:
            if _on_device(self.device) and \
                    source._devmem_ is not None:
                mirror.set_device_array(source.devmem, self.device)
            else:
                source.map_read()
                mirror.map_invalidate()
                mirror.mem = numpy.array(source.mem)


class Shell(Unit):
    """Drops into an interactive shell mid-workflow (reference
    interaction.Shell embedded IPython).  Uses IPython when available,
    else code.interact; gated off unless stdin is a tty or
    ``force=True`` (so test runs never block)."""

    def __init__(self, workflow, **kwargs):
        super(Shell, self).__init__(workflow, **kwargs)
        self.force = kwargs.get("force", False)
        self.banner = kwargs.get(
            "banner", "veles-tpu shell: `workflow` is live; ^D resumes")

    def run(self):
        import sys
        if not self.force and not sys.stdin.isatty():
            return
        namespace = {"workflow": self.workflow, "unit": self}
        try:
            import IPython
            IPython.embed(banner1=self.banner, user_ns=namespace)
        except ImportError:
            import code
            code.interact(banner=self.banner, local=namespace)
