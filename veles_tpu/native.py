"""ctypes bindings + on-demand build for the native inference runtime.

Counterpart of the reference's libVeles consumption path: a package
exported by Workflow.package_export is loaded and executed by the C++
runtime (native/src/), with the greedy strip-packing arena planner and
the batch-sharding thread-pool engine.  Build uses cmake+make the first
time and caches the shared library under the user cache dir (NOT
inside the repo: CMake drops generated .cpp probes into its build
tree, which pollutes source-tree audits).
"""

import ctypes
import os
import subprocess
import threading

import numpy

__all__ = ["NativeWorkflow", "build_native", "native_available",
           "source_digest"]

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_ROOT, "native")


def source_digest():
    """Hash of every native source file: the cache key (computed once,
    on first use — importing this module must not walk the source
    tree).  An existence-only check against a shared cache dir would
    keep serving a stale .so across source changes and checkouts.
    ``serve/engine.py``'s ``model_digest`` is the same pattern applied
    to the AOT compile cache: digest-keyed cache dirs, content (not
    existence) as the key."""
    global _digest
    if _digest is None:
        import hashlib
        digest = hashlib.sha256()
        for dirpath, _, filenames in sorted(os.walk(_NATIVE_DIR)):
            for filename in sorted(filenames):
                if filename.endswith((".cc", ".h", ".txt")):
                    path = os.path.join(dirpath, filename)
                    digest.update(filename.encode())
                    with open(path, "rb") as fin:
                        digest.update(fin.read())
        _digest = digest.hexdigest()[:16]
    return _digest


def _lib_path():
    """Digest-keyed build dir + library path, resolved lazily."""
    build_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.expanduser("~/.cache")),
        "veles_tpu", "native_build", source_digest())
    return build_dir, os.path.join(build_dir, "libveles_tpu_native.so")


_digest = None
_build_lock = threading.Lock()
_lib = None


def build_native(force=False):
    """Build (or rebuild) the shared library; returns its path."""
    with _build_lock:
        build_dir, lib_path = _lib_path()
        if os.path.exists(lib_path) and not force:
            return lib_path
        os.makedirs(build_dir, exist_ok=True)
        subprocess.run(
            ["cmake", "-DCMAKE_BUILD_TYPE=Release", _NATIVE_DIR],
            cwd=build_dir, check=True, capture_output=True)
        subprocess.run(
            ["cmake", "--build", ".", "-j"],
            cwd=build_dir, check=True, capture_output=True)
        return lib_path


def native_available():
    try:
        _load_lib()
        return True
    except Exception:
        return False


def _load_lib():
    global _lib
    if _lib is not None:
        return _lib
    path = build_native()
    lib = ctypes.CDLL(path)
    lib.veles_workflow_load.restype = ctypes.c_void_p
    lib.veles_workflow_load.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.veles_workflow_destroy.argtypes = [ctypes.c_void_p]
    lib.veles_workflow_input_size.restype = ctypes.c_longlong
    lib.veles_workflow_input_size.argtypes = [ctypes.c_void_p]
    lib.veles_workflow_output_size.restype = ctypes.c_longlong
    lib.veles_workflow_output_size.argtypes = [ctypes.c_void_p]
    lib.veles_workflow_unit_count.restype = ctypes.c_longlong
    lib.veles_workflow_unit_count.argtypes = [ctypes.c_void_p]
    lib.veles_workflow_arena_size.restype = ctypes.c_longlong
    lib.veles_workflow_arena_size.argtypes = [ctypes.c_void_p,
                                              ctypes.c_int]
    lib.veles_workflow_run.restype = ctypes.c_int
    lib.veles_workflow_run.argtypes = [
        ctypes.c_void_p,
        numpy.ctypeslib.ndpointer(numpy.float32, flags="C_CONTIGUOUS"),
        numpy.ctypeslib.ndpointer(numpy.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    _lib = lib
    return lib


class NativeWorkflow(object):
    """Loads a package and runs batched inference natively."""

    def __init__(self, package_path):
        self._lib = _load_lib()
        err = ctypes.create_string_buffer(1024)
        self._handle = self._lib.veles_workflow_load(
            package_path.encode(), err, len(err))
        if not self._handle:
            raise RuntimeError(
                "native load failed: %s" % err.value.decode())

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.veles_workflow_destroy(handle)
            self._handle = None

    @property
    def input_size(self):
        return int(self._lib.veles_workflow_input_size(self._handle))

    @property
    def output_size(self):
        return int(self._lib.veles_workflow_output_size(self._handle))

    @property
    def unit_count(self):
        return int(self._lib.veles_workflow_unit_count(self._handle))

    def arena_size(self, batch):
        size = int(self._lib.veles_workflow_arena_size(
            self._handle, batch))
        if size < 0:
            raise RuntimeError("arena planning failed")
        return size

    def run(self, batch_data):
        """batch_data: (B, *input_shape) float array -> (B, output_size)."""
        x = numpy.ascontiguousarray(batch_data, numpy.float32)
        batch = x.shape[0]
        if x.size != batch * self.input_size:
            raise ValueError(
                "expected %d floats/sample, got %d" %
                (self.input_size, x.size // max(batch, 1)))
        out = numpy.zeros((batch, self.output_size), numpy.float32)
        err = ctypes.create_string_buffer(1024)
        rc = self._lib.veles_workflow_run(
            self._handle, x.reshape(-1), out.reshape(-1), batch, err,
            len(err))
        if rc != 0:
            raise RuntimeError("native run failed: %s" %
                               err.value.decode())
        return out
