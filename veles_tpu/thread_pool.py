"""Thread pool executing the unit graph.

TPU-native counterpart of reference veles/thread_pool.py:58,71 (a Twisted
threadpool subclass).  Rebuilt on ``concurrent.futures`` — no reactor.
Keeps the reference capabilities that matter: worker callbacks, pause /
resume, failure routing (``errback``), SIGINT escalation (first ^C asks
for graceful stop, second forces shutdown), and idempotent shutdown with
registered callbacks.
"""

import functools
import signal
import sys
import threading
from concurrent.futures import ThreadPoolExecutor

from veles_tpu.logger import Logger

__all__ = ["ThreadPool"]


class ThreadPool(Logger):
    pools = []
    _sigint_installed = False
    _sigint_lock = threading.Lock()
    sigint_hook = None  # set by Workflow/Launcher for graceful stop

    def __init__(self, minthreads=2, maxthreads=32, name="pool", **kwargs):
        super(ThreadPool, self).__init__(**kwargs)
        self.name = name
        self._executor = ThreadPoolExecutor(
            max_workers=maxthreads, thread_name_prefix=name)
        self._paused = threading.Event()
        self._paused.set()  # set == running
        self.failure = None
        self._failure_lock = threading.Lock()
        self._shutdown_callbacks = []
        self._shutting_down = False
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()
        ThreadPool.pools.append(self)
        self._install_sigint()

    @classmethod
    def _install_sigint(cls):
        with cls._sigint_lock:
            if cls._sigint_installed:
                return
            if threading.current_thread() is not threading.main_thread():
                return
            try:
                prev = signal.getsignal(signal.SIGINT)

                def handler(signum, frame):
                    if cls.sigint_hook is not None:
                        hook, cls.sigint_hook = cls.sigint_hook, None
                        sys.stderr.write(
                            "\n^C: requesting graceful stop "
                            "(press again to force)\n")
                        hook()
                        return
                    for pool in list(cls.pools):
                        pool.shutdown(False)
                    if callable(prev):
                        prev(signum, frame)
                    else:
                        raise KeyboardInterrupt()

                signal.signal(signal.SIGINT, handler)
                cls._sigint_installed = True
            except ValueError:  # pragma: no cover - non-main thread
                pass

    # -- task submission ---------------------------------------------------

    def callInThread(self, fn, *args, **kwargs):
        """Submit ``fn``; exceptions route to :meth:`errback`."""
        if self._shutting_down:
            return None
        with self._inflight_lock:
            self._inflight += 1
            self._idle.clear()
        future = self._executor.submit(
            self._run_task, fn, args, kwargs)
        return future

    def _run_task(self, fn, args, kwargs):
        self._paused.wait()
        try:
            return fn(*args, **kwargs)
        except BaseException:  # noqa: B036 - all failures route to errback
            self.errback(sys.exc_info())
        finally:
            with self._inflight_lock:
                self._inflight -= 1
                if self._inflight == 0:
                    self._idle.set()

    def errback(self, exc_info):
        """Record the first failure; workflows poll :attr:`failure`."""
        with self._failure_lock:
            if self.failure is None:
                self.failure = exc_info
        self.error("worker failure: %s", exc_info[1])

    def wait_idle(self, timeout=None):
        return self._idle.wait(timeout)

    # -- pause / resume ----------------------------------------------------

    def pause(self):
        self._paused.clear()

    def resume(self):
        self._paused.set()

    @property
    def paused(self):
        return not self._paused.is_set()

    # -- shutdown ----------------------------------------------------------

    def register_on_shutdown(self, callback):
        self._shutdown_callbacks.append(callback)

    def shutdown(self, wait=True):
        if self._shutting_down:
            return
        self._shutting_down = True
        self._paused.set()
        for callback in self._shutdown_callbacks:
            try:
                callback()
            except Exception:
                self.exception("shutdown callback failed")
        self._executor.shutdown(wait=wait, cancel_futures=not wait)
        if self in ThreadPool.pools:
            ThreadPool.pools.remove(self)

    @staticmethod
    def reset():
        for pool in list(ThreadPool.pools):
            pool.shutdown(False)


def threadsafe(fn):
    """Decorator serialising calls on a per-object lock ``_ts_lock_``."""
    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        lock = getattr(self, "_ts_lock_", None)
        if lock is None:
            lock = threading.RLock()
            self._ts_lock_ = lock
        with lock:
            return fn(self, *args, **kwargs)
    return wrapped
