"""Dataset normalizers.

TPU-native counterpart of reference veles/normalization.py:110 —
a keyed registry of normalizers with the analyze → coefficients →
normalize / denormalize lifecycle and picklable state.  The full mapping
set of the reference is covered: ``none``, ``linear``, ``range_linear``,
``mean_disp``, ``exp``, ``pointwise``, ``external_mean``,
``internal_mean``.

Coefficients are numpy (host side): normalization is a data-preparation
step; the per-step device work (mean/disp application inside the training
loop) goes through ops.normalize.mean_disp_normalize instead.
"""

import numpy

__all__ = [
    "NormalizerRegistry", "NormalizerBase", "StatelessNormalizer",
    "NoneNormalizer", "LinearNormalizer", "RangeLinearNormalizer",
    "MeanDispersionNormalizer", "ExponentNormalizer", "PointwiseNormalizer",
    "ExternalMeanNormalizer", "InternalMeanNormalizer",
]


class NormalizerRegistry(type):
    """Metaclass registry mapping ``MAPPING`` names to classes
    (reference: normalization.py:110)."""

    normalizers = {}

    def __init__(cls, name, bases, namespace):
        super(NormalizerRegistry, cls).__init__(name, bases, namespace)
        mapping = namespace.get("MAPPING")
        if mapping:
            NormalizerRegistry.normalizers[mapping] = cls

    @staticmethod
    def get(name, **kwargs):
        try:
            factory = NormalizerRegistry.normalizers[name]
        except KeyError:
            raise ValueError(
                "Unknown normalization type %r (known: %s)" % (
                    name, sorted(NormalizerRegistry.normalizers)))
        return factory(**kwargs)


class NormalizerBase(object, metaclass=NormalizerRegistry):
    """analyze() accumulates dataset statistics; normalize()/denormalize()
    apply them in place-compatible fashion (returns the array)."""

    MAPPING = None

    def __init__(self, **kwargs):
        self._initialized = False
        self.kwargs = kwargs

    @property
    def initialized(self):
        return self._initialized

    def analyze(self, data):
        """Accumulate statistics from a chunk of the dataset."""
        self._analyze(numpy.asarray(data))
        self._initialized = True

    def _analyze(self, data):
        raise NotImplementedError

    def normalize(self, data):
        if not self._initialized:
            raise RuntimeError(
                "%s.normalize() before analyze()" % type(self).__name__)
        return self._normalize(data)

    def denormalize(self, data):
        if not self._initialized:
            raise RuntimeError(
                "%s.denormalize() before analyze()" % type(self).__name__)
        return self._denormalize(data)

    def analyze_and_normalize(self, data):
        self.analyze(data)
        return self.normalize(data)

    def _normalize(self, data):
        raise NotImplementedError

    def _denormalize(self, data):
        raise NotImplementedError

    def __getstate__(self):
        return self.__dict__.copy()

    def __setstate__(self, state):
        self.__dict__.update(state)


class StatelessNormalizer(NormalizerBase):
    """Normalizers that need no dataset statistics."""

    def analyze(self, data):
        self._initialized = True

    def _analyze(self, data):
        pass


class NoneNormalizer(StatelessNormalizer):
    """Identity (reference: normalization.py:496)."""

    MAPPING = "none"

    def _normalize(self, data):
        return data

    def _denormalize(self, data):
        return data


class _IntervalMixin(object):
    """Target interval handling shared by the linear family
    (reference: normalization.py:322)."""

    def _init_interval(self, kwargs):
        self.interval = tuple(kwargs.get("interval", (-1.0, 1.0)))
        if len(self.interval) != 2:
            raise ValueError("interval must be (min, max)")


class LinearNormalizer(StatelessNormalizer, _IntervalMixin):
    """Scale each *sample* into the target interval using its own
    min/max (stateless; reference: normalization.py:347)."""

    MAPPING = "linear"

    def __init__(self, **kwargs):
        super(LinearNormalizer, self).__init__(**kwargs)
        self._init_interval(kwargs)

    def _normalize(self, data):
        data = numpy.asarray(data, numpy.float64) \
            if not numpy.issubdtype(numpy.asarray(data).dtype,
                                    numpy.floating) else numpy.asarray(data)
        flat = data.reshape(len(data), -1)
        dmin = flat.min(axis=1, keepdims=True)
        dmax = flat.max(axis=1, keepdims=True)
        span = dmax - dmin
        span[span == 0] = 1
        lo, hi = self.interval
        flat *= (hi - lo) / span
        shift = dmin * (hi - lo) / span - lo
        flat -= shift
        return data

    def _denormalize(self, data):
        raise NotImplementedError(
            "linear is per-sample lossy; denormalize is undefined")


class RangeLinearNormalizer(NormalizerBase, _IntervalMixin):
    """Scale using the GLOBAL dataset min/max gathered by analyze()
    (reference: normalization.py:398)."""

    MAPPING = "range_linear"

    def __init__(self, **kwargs):
        super(RangeLinearNormalizer, self).__init__(**kwargs)
        self._init_interval(kwargs)
        self.min = None
        self.max = None

    def _analyze(self, data):
        dmin, dmax = float(data.min()), float(data.max())
        self.min = dmin if self.min is None else min(self.min, dmin)
        self.max = dmax if self.max is None else max(self.max, dmax)

    def _scale(self):
        span = self.max - self.min
        if span == 0:
            span = 1.0
        lo, hi = self.interval
        return (hi - lo) / span

    def _normalize(self, data):
        lo, _hi = self.interval
        data -= self.min
        data *= self._scale()
        data += lo
        return data

    def _denormalize(self, data):
        lo, _hi = self.interval
        data -= lo
        data /= self._scale()
        data += self.min
        return data


class MeanDispersionNormalizer(NormalizerBase):
    """(x - mean) / (max - min), computed feature-wise over the dataset
    (reference: normalization.py:284).  Exposes ``mean`` and ``rdisp``
    for the on-device ops.normalize kernel."""

    MAPPING = "mean_disp"

    def __init__(self, **kwargs):
        super(MeanDispersionNormalizer, self).__init__(**kwargs)
        self._sum = None
        self._count = 0
        self._min = None
        self._max = None

    def _analyze(self, data):
        flat = data.reshape(len(data), -1).astype(numpy.float64)
        s = flat.sum(axis=0)
        mn = flat.min(axis=0)
        mx = flat.max(axis=0)
        if self._sum is None:
            self._sum, self._min, self._max = s, mn, mx
        else:
            self._sum += s
            numpy.minimum(self._min, mn, out=self._min)
            numpy.maximum(self._max, mx, out=self._max)
        self._count += len(flat)

    @property
    def mean(self):
        return self._sum / self._count

    @property
    def disp(self):
        return self._max - self._min

    @property
    def rdisp(self):
        disp = self.disp.copy()
        disp[disp == 0] = 1
        return 1.0 / disp

    def _normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat -= self.mean.astype(flat.dtype)
        flat *= self.rdisp.astype(flat.dtype)
        return data

    def _denormalize(self, data):
        flat = data.reshape(len(data), -1)
        flat /= self.rdisp.astype(flat.dtype)
        flat += self.mean.astype(flat.dtype)
        return data


class ExponentNormalizer(StatelessNormalizer):
    """Stable softmax-style exponent normalization per sample
    (reference: normalization.py:467)."""

    MAPPING = "exp"

    def _normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat -= flat.max(axis=1, keepdims=True)
        numpy.exp(flat, out=flat)
        flat /= flat.sum(axis=1, keepdims=True)
        return data

    def _denormalize(self, data):
        flat = data.reshape(len(data), -1)
        numpy.log(flat, out=flat)
        return data


class PointwiseNormalizer(NormalizerBase):
    """Per-feature linear map into [-1, 1] computed from feature-wise
    min/max (reference: normalization.py:511)."""

    MAPPING = "pointwise"

    def __init__(self, **kwargs):
        super(PointwiseNormalizer, self).__init__(**kwargs)
        self._min = None
        self._max = None

    def _analyze(self, data):
        flat = data.reshape(len(data), -1).astype(numpy.float64)
        mn = flat.min(axis=0)
        mx = flat.max(axis=0)
        if self._min is None:
            self._min, self._max = mn, mx
        else:
            numpy.minimum(self._min, mn, out=self._min)
            numpy.maximum(self._max, mx, out=self._max)

    @property
    def _mul_add(self):
        disp = self._max - self._min
        disp[disp == 0] = 1
        mul = 2.0 / disp
        add = -1.0 - self._min * mul
        return mul, add

    def _normalize(self, data):
        mul, add = self._mul_add
        flat = data.reshape(len(data), -1)
        flat *= mul.astype(flat.dtype)
        flat += add.astype(flat.dtype)
        return data

    def _denormalize(self, data):
        mul, add = self._mul_add
        flat = data.reshape(len(data), -1)
        flat -= add.astype(flat.dtype)
        flat /= mul.astype(flat.dtype)
        return data


class ExternalMeanNormalizer(StatelessNormalizer):
    """Subtract a user-supplied mean sample (reference:
    normalization.py:593).  kwargs: mean_source (array or .npy path),
    scale (optional divisor)."""

    MAPPING = "external_mean"

    def __init__(self, **kwargs):
        super(ExternalMeanNormalizer, self).__init__(**kwargs)
        source = kwargs.get("mean_source")
        if source is None:
            raise ValueError("external_mean requires mean_source")
        if isinstance(source, str):
            source = numpy.load(source)
        self.mean = numpy.asarray(source)
        self.scale = kwargs.get("scale", 1.0)

    def _normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat -= self.mean.ravel().astype(flat.dtype)
        if self.scale != 1.0:
            flat /= self.scale
        return data

    def _denormalize(self, data):
        flat = data.reshape(len(data), -1)
        if self.scale != 1.0:
            flat *= self.scale
        flat += self.mean.ravel().astype(flat.dtype)
        return data


class InternalMeanNormalizer(NormalizerBase):
    """Subtract the dataset mean computed by analyze()
    (reference: normalization.py:636)."""

    MAPPING = "internal_mean"

    def __init__(self, **kwargs):
        super(InternalMeanNormalizer, self).__init__(**kwargs)
        self._sum = None
        self._count = 0

    def _analyze(self, data):
        flat = data.reshape(len(data), -1).astype(numpy.float64)
        s = flat.sum(axis=0)
        if self._sum is None:
            self._sum = s
        else:
            self._sum += s
        self._count += len(flat)

    @property
    def mean(self):
        return self._sum / self._count

    def _normalize(self, data):
        flat = data.reshape(len(data), -1)
        flat -= self.mean.astype(flat.dtype)
        return data

    def _denormalize(self, data):
        flat = data.reshape(len(data), -1)
        flat += self.mean.astype(flat.dtype)
        return data
