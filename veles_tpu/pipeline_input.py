"""Asynchronous double-buffered input pipeline for the training hot path.

The fused training loop (veles_tpu/models/fused.py) collapsed compute to
one XLA dispatch per minibatch, but each step still paid host
``fill_minibatch`` -> host->device transfer -> dispatch strictly in
sequence.  This module overlaps the three stages: while step *k* executes
on device, a single worker thread (a dedicated ``thread_pool.ThreadPool``)
serves minibatch *k+1* into a ping-pong host staging buffer
(``memory.Array.stage_begin/stage_put``) and immediately starts its async
host->device transfer, so the steady-state step time approaches
``max(fill, transfer, compute)`` instead of their sum — the TPU paper's
feed-the-MXU lesson applied to the input path.

Correctness model (full rules in docs/pipeline_input.md):

- the worker runs the loader's ORDINARY serve path (``serve_next_minibatch``
  + ``_on_successful_serve``), so shuffling, class iteration, short-tail
  padding and epoch accounting are bit-identical to the synchronous path;
- the public serving fields downstream units gate on (minibatch
  class/size/offset, ``epoch_number``, the four end-of-class Bools) are
  routed through a thread-keyed ``loader.ServeShadow`` while the worker
  serves ahead; each :class:`PrefetchItem` carries the shadow snapshot,
  which :meth:`Prefetcher.step` applies on the graph thread when the
  minibatch is consumed — downstream units always see the flags of the
  batch they are processing, never the one being prefetched;
- consumers read the minibatch through the item's device arrays (an
  async ``device_put`` of the staged host fill, or the adopted gather
  result on device-resident loaders), never through the Arrays' host
  buffers, which belong to the worker while it fills ahead.

Shutdown: ``Workflow.stop()`` reaches :meth:`shutdown` via
``Loader.stop``; a normally-finished run shuts down through the
``on_workflow_finish`` unit hook.  Both join the worker thread, so no
non-daemon threads outlive the run.  Every served-but-unconsumed
minibatch keeps its serve record in ``pending_minibatches_`` until it
is consumed; shutdown (and the standard pickling path, for mid-run
snapshots) requeues those records through ``failed_minibatches``, so
serving ahead never drops a minibatch — the same recovery path as a
dropped master-slave job.
"""

import contextlib
import queue
import threading
import time

from veles_tpu import chaos
from veles_tpu.loader.base import ServeShadow
from veles_tpu.logger import Logger
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer

__all__ = ["Prefetcher", "PrefetchItem"]


class PrefetchItem(object):
    """One served minibatch: device arrays + the serve-time snapshot of
    the loader's public fields."""

    __slots__ = ("serial", "data", "labels", "targets", "values")

    def __init__(self, serial):
        self.serial = serial
        self.data = None
        self.labels = None
        self.targets = None
        self.values = None


class Prefetcher(Logger):
    """Serves a Loader's minibatches ``depth`` steps ahead on a worker
    thread, with ping-pong host staging and async H2D transfers.

    ``attach()`` routes ``loader.run()`` through :meth:`step`; the
    worker pool starts lazily on the first step and is recreated after
    ``shutdown()``, so one Prefetcher spans any number of runs.
    """

    def __init__(self, loader, device, depth=1, **kwargs):
        super(Prefetcher, self).__init__(**kwargs)
        self.loader = loader
        self.device = device
        self.depth = max(1, int(depth))
        self.nslots = self.depth + 1
        self.current = None
        self._pool = None
        self._results = queue.Queue()
        self._inflight = 0
        self._serial = 0
        self._shutdown = False
        # held around every worker serve; quiescent() takes it so a
        # mid-run pickle (snapshotter) never observes a half-applied
        # serve mutating pending_minibatches_/failed_minibatches
        self._serve_mutex = threading.Lock()
        self.stats = self._fresh_stats()
        # telemetry (docs/observability.md): per-stage histograms feed
        # the heartbeat/bench percentiles; resolved once, not per serve
        self._m_wait = _registry.histogram("pipeline.wait_s")
        self._m_fill = _registry.histogram("pipeline.fill_s")
        self._m_h2d = _registry.histogram("pipeline.h2d_s")
        _registry.gauge("pipeline.depth").set(self.depth)

    def _fresh_stats(self):
        return {"depth": self.depth, "serves": 0, "applied": 0,
                "wait_s": 0.0, "fill_s": 0.0, "h2d_s": 0.0}

    # -- lifecycle ---------------------------------------------------------

    def attach(self):
        self.loader._pipeline_ = self
        return self

    def detach(self):
        self.shutdown()
        if self.loader._pipeline_ is self:
            self.loader._pipeline_ = None

    def _start(self):
        from veles_tpu.thread_pool import ThreadPool
        self._shutdown = False
        self._inflight = 0
        self._results = queue.Queue()
        self.current = None
        self.stats = self._fresh_stats()
        # staging slots are (re-)initialized lazily per serve in
        # _serve_one_locked, so a wholesale .mem swap is always healed
        self._pool = ThreadPool(minthreads=1, maxthreads=1,
                                name="prefetch")

    def shutdown(self):
        """Stop serving ahead and JOIN the worker thread; idempotent.
        Never-consumed serves are requeued through failed_minibatches so
        no minibatch is silently dropped (same recovery path as a
        dropped master-slave job)."""
        self._shutdown = True
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)
        dropped = 0
        while True:  # drop never-consumed items...
            try:
                self._results.get_nowait()
                dropped += 1
            except queue.Empty:
                break
        loader = self.loader
        pending = loader.pending_minibatches_.pop(None, None)
        if pending:
            # ...but requeue their serve records (one per dropped item;
            # the worker joined, so no serve is concurrently appending).
            # Reversed because serve_next_minibatch pops failed jobs
            # LIFO: replay must preserve the original serve order
            loader.failed_minibatches.extend(reversed(pending))
            if dropped:
                self.debug("requeued %d never-consumed prefetched "
                           "minibatch(es)", len(pending))
        self._inflight = 0
        self.current = None
        loader._serve_shadow_ = None

    @contextlib.contextmanager
    def quiescent(self):
        """No serve runs while held (serves between jobs are already
        atomic w.r.t. this lock); used by ``Loader.__getstate__``."""
        with self._serve_mutex:
            yield

    def _staged_arrays(self):
        loader = self.loader
        arrays = [loader.minibatch_data, loader.minibatch_indices,
                  loader.minibatch_labels,
                  getattr(loader, "minibatch_targets", None)]
        return [a for a in arrays if a is not None and bool(a)]

    # -- graph-thread side -------------------------------------------------

    def step(self):
        """Pop the oldest served minibatch, apply its snapshot to the
        loader's public fields, and keep the worker ``depth`` serves
        ahead.  Called in place of the synchronous ``Loader.run``."""
        if self._pool is None:
            self._start()
        pool = self._pool
        if pool is not None and pool.failure is not None:
            # fail FAST on a worker serve failure: the pool keeps
            # processing queued serves, so waiting for starvation
            # (the _take path) could let a bad serve's neighbors feed
            # the graph for many more steps before anyone notices
            failure = pool.failure
            self.shutdown()
            raise failure[1].with_traceback(failure[2])
        while self._inflight < self.depth + 1 and not self._shutdown:
            self._submit()
        if _tracer.enabled:
            _tracer.counter("pipeline.inflight", self._inflight)
        item = self._take()
        if item is None:  # shut down mid-wait (Workflow.stop)
            return
        self._inflight -= 1
        self._apply(item)
        self.current = item
        self.stats["applied"] += 1

    def _submit(self):
        pool = self._pool
        if pool is None:  # concurrent shutdown() won the race
            return
        slot = self._serial % self.nslots
        serial = self._serial
        self._serial += 1
        self._inflight += 1
        pool.callInThread(self._serve_one, serial, slot)

    def _take(self):
        start = time.perf_counter()
        while True:
            try:
                item = self._results.get(timeout=0.2)
                break
            except queue.Empty:
                pool = self._pool
                if self._shutdown or pool is None:
                    return None
                failure = pool.failure
                if failure is not None:
                    self.shutdown()
                    raise failure[1].with_traceback(failure[2])
        waited = time.perf_counter() - start
        self.stats["wait_s"] += waited
        self._m_wait.observe(waited)
        if _tracer.enabled:
            _tracer.complete("pipeline.wait", start, waited,
                             cat="pipeline")
        timers = self.loader.timers
        timers["pipeline_wait"] = timers.get(
            "pipeline_wait", 0.0) + waited
        return item

    def _apply(self, item):
        """Write the item's serve-time snapshot into the loader's REAL
        public fields (backing attributes directly: the property
        setters would re-derive flags from the worker-advanced global
        offset)."""
        loader = self.loader
        values = item.values
        with self._serve_mutex:
            # the oldest pending record belongs to this (FIFO) item:
            # consuming it retires its requeue obligation
            pending = loader.pending_minibatches_.get(None)
            if pending:
                pending.pop(0)
        loader._minibatch_class = values["minibatch_class"]
        loader._minibatch_size_ = values["minibatch_size"]
        loader._minibatch_offset_ = values["minibatch_offset"]
        for name in ServeShadow.FLAGS:
            flag = getattr(loader, name)
            flag <<= values[name]
        # count samples at CONSUME time (graph thread, real fields):
        # updates samples_served and epoch_number exactly like the
        # synchronous path's post-serve accounting
        loader._on_successful_serve()

    # -- worker-thread side ------------------------------------------------

    def _serve_one(self, serial, slot):
        with self._serve_mutex:
            self._serve_one_locked(serial, slot)

    def _serve_one_locked(self, serial, slot):
        if chaos.plan is not None:
            fault = chaos.plan.fire("pipeline.serve")
            if fault is not None and fault.action == "exc":
                # a worker-thread serve failure must surface on the
                # graph thread (Prefetcher._take's pool-failure path),
                # not hang the run or leak the worker
                raise RuntimeError(
                    "chaos: injected serve failure (serial %d)" % serial)
        loader = self.loader
        shadow = loader._serve_shadow_
        if shadow is None or shadow.thread is not threading.current_thread():
            # first serve of this pool: seed the worker's view from the
            # loader's live (applied) state
            shadow = ServeShadow(loader, threading.current_thread())
            loader._serve_shadow_ = shadow
        t0 = time.perf_counter()
        for arr in self._staged_arrays():
            if not arr.staged:
                # a wholesale .mem assignment dropped the slots (shape
                # may have changed); re-stage around the new buffer so
                # the in-flight-DMA protection never silently lapses
                arr.stage_init(self.nslots)
            arr.stage_begin(slot)
        # NOTE two deviations from the synchronous Loader.run, both so
        # that serving AHEAD never miscounts: the previous serve's
        # pending record is NOT popped (every served-but-unconsumed
        # minibatch keeps its requeue record until _apply retires it or
        # shutdown moves it to failed_minibatches), and
        # _on_successful_serve runs at APPLY time on the graph thread —
        # like the master-slave contract, samples are counted when
        # consumed, so a requeued serve is never counted twice
        loader.serve_next_minibatch(None)
        t1 = time.perf_counter()

        item = PrefetchItem(serial)
        item.values = dict(shadow.values)
        item.data = loader.minibatch_data.staged_capture(self.device)
        if loader.minibatch_labels:
            item.labels = loader.minibatch_labels.staged_capture(
                self.device)
        targets = getattr(loader, "minibatch_targets", None)
        if targets is not None and bool(targets):
            item.targets = targets.staged_capture(self.device)
        t2 = time.perf_counter()

        self.stats["serves"] += 1
        self.stats["fill_s"] += t1 - t0
        self.stats["h2d_s"] += t2 - t1
        self._m_fill.observe(t1 - t0)
        self._m_h2d.observe(t2 - t1)
        if _tracer.enabled:
            # worker-thread spans land on their own Perfetto track, so
            # the fill/H2D overlap with the graph thread's step spans
            # is visible directly
            _tracer.complete("pipeline.fill", t0, t1 - t0,
                             cat="pipeline", args={"serial": serial})
            _tracer.complete("pipeline.h2d", t1, t2 - t1,
                             cat="pipeline", args={"serial": serial})
        timers = loader.timers
        timers["pipeline_fill"] = timers.get(
            "pipeline_fill", 0.0) + (t1 - t0)
        timers["pipeline_h2d"] = timers.get(
            "pipeline_h2d", 0.0) + (t2 - t1)
        self._results.put(item)
