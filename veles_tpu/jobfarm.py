"""Task farming over the master-slave control plane.

The reference ran two task-parallel meta-workflows through its
master-slave protocol: genetics chromosome evaluations
(reference: genetics/optimization_workflow.py:186-221) and ensemble
member training (reference: ensemble/base_workflow.py:135-153), each
job a self-contained model run.  :class:`JobFarm` is that plane here:
a list of picklable job specs is served through the SAME
Server/Client stack the data-parallel trainer uses — checksum
handshake, timeout watchdog, drop/requeue, shm bypass — with results
collected in job order.

One-shot::

    results = JobFarm("my-tag").run(jobs, runner=fn, local_slaves=4)

Persistent (several batches over one set of workers — a GA farms one
batch per generation and remote workers must survive between them)::

    farm = JobFarm("my-tag").start(runner=fn, local_slaves=4)
    for generation in ...:
        fits = farm.submit(specs)
    farm.shutdown()

``local_slaves`` spawns in-process Client worker threads — the
single-host convenience (and the test harness).  Real scale-out runs
``JobFarm("my-tag").worker(address, fn)`` on other hosts against the
master's logged address; both modes mix, and workers stay connected
across submit() batches: an idle worker parks PASSIVELY at the
control plane's sync point, and the server pushes work to it — on
updates, on ``submit()`` (which resumes parked workers), and on the
watchdog tick that retries requeued/speculative work.  The tag takes
the place of the trainer's source checksum: master and workers must
quote the same one.

Straggler/failure semantics: a job whose slave dies is requeued
(Server drop -> ``drop_slave``); once a job has run longer than
``speculation_factor`` x the mean completed-job time, an idle slave
re-executes it speculatively (first result wins — the MapReduce
backup-task move, threshold included), so one slow worker cannot
stall the tail.  A runner exception travels back as a result and
fails the batch loudly at collection time — a silently dropped job
would skew a GA's selection or an ensemble's vote invisibly.
Farmed jobs are whole model runs, so the Server's adaptive drop
watchdog gets a week-long default timeout here instead of the
trainer's 60 s (override with ``job_timeout=``).
"""

import hashlib
import threading
import time
from collections import deque

from veles_tpu import elastic
from veles_tpu.logger import Logger

__all__ = ["JobFarm", "FarmJobError"]

#: farmed jobs are full trainings with wildly varying durations; the
#: trainer plane's 60 s watchdog default would drop (and blacklist!)
#: every realistic worker mid-job
DEFAULT_JOB_TIMEOUT = 7 * 24 * 3600.0


class FarmJobError(RuntimeError):
    """One or more farmed jobs raised on their worker, or the batch
    timed out with jobs unfinished."""


_UNSET = object()


def farm_enabled(farm_slaves, farm_address):
    """The one enablement rule for farm-riding classes: local workers
    requested, OR an explicit bind address (the "127.0.0.1:0" default
    is the no-farming sentinel; remote-only setups pass a real
    address)."""
    return bool(farm_slaves) or farm_address != "127.0.0.1:0"


class _FarmMaster(object):
    """Workflow-contract adapter the Server drives on the master.

    Holds at most one active batch; ``reset(jobs)`` arms the next one.
    With no active batch every requester parks passively; the next
    ``submit()`` resumes them through the server's parked-requester
    release (clients never poll — see client.py's 'wait' handling)."""

    #: farm results are opaque job payloads, not per-unit control
    #: records: keep the Server's all-or-nothing finiteness prewalk (a
    #: NaN fitness/result quarantines the worker BEFORE results[] or
    #: the duration stats mutate) rather than the SPMD planes' inline
    #: validate-during-apply (docs/distributed.md)
    update_validation = "prewalk"

    #: this adapter runs its OWN job-stamp/backup-copy bookkeeping
    #: (dedup by result slot, epoch stamps) — the Server must not
    #: layer its lifted speculation pass on top, or every tail job
    #: could triplicate (docs/distributed.md, "Elasticity contract")
    owns_speculation = True

    def __init__(self, checksum, speculation_factor=2.0,
                 min_speculation_s=5.0, context=None):
        self.checksum = checksum
        self.speculation_factor = speculation_factor
        self.min_speculation_s = min_speculation_s
        self.context = context
        self._lock = threading.Lock()
        self._specs = []
        self._pending = deque()
        self._outstanding = {}      # job index -> {slave id: t0}
        self._powers = {}           # slave id -> reported power rating
        self._durations = deque(maxlen=200)
        self.epoch = 0              # batch counter; stamps every job
        self.results = []
        self._remaining = 0
        self.done = threading.Event()
        self.done.set()

    def reset(self, jobs):
        with self._lock:
            if not self.done.is_set():
                raise RuntimeError("previous batch still running")
            self.epoch += 1
            self._specs = list(jobs)
            self._pending = deque(enumerate(self._specs))
            self._outstanding = {}
            self.results = [_UNSET] * len(self._specs)
            self._remaining = len(self._specs)
            if self._specs:
                self.done.clear()

    # -- Server-side workflow contract ---------------------------------

    def generate_initial_data_for_slave(self, slave):
        # shared context ships ONCE per worker at handshake (e.g. the
        # eval batch every ensemble-test job reads) instead of riding
        # inside every job spec
        if self.context is None:
            return None
        return ("ctx", self.context)

    def generate_data_for_slave(self, slave):
        with self._lock:
            self._powers[slave.id] = getattr(slave, "power", 1.0)
            if self._pending:
                i, spec = self._pending.popleft()
                # perf_counter: these stamps feed job durations and
                # the speculation threshold — an NTP step on the wall
                # clock would fake (or hide) a straggler
                self._outstanding.setdefault(i, {})[slave.id] = \
                    time.perf_counter()
                return (self.epoch, i, spec)
            # nothing fresh: maybe shadow a straggler (backup task;
            # first result wins).  Only once the job has run longer
            # than speculation_factor x the mean completed duration
            # (with an absolute floor: millisecond-scale jobs would
            # otherwise speculate the whole batch tail) — immediate
            # re-issue would duplicate every tail job.  The threshold
            # math is shared with the Server's lifted speculation pass
            # (elastic.speculation_threshold): power-corrected, and
            # degenerate-safe against zero/negative/corrupt ratings
            if not self._durations:
                return False
            mean = sum(self._durations) / len(self._durations)
            mean_power = elastic.fleet_mean_power(
                self._powers.values())
            now = time.perf_counter()
            for i, copies in self._outstanding.items():
                if slave.id in copies or self.results[i] is not _UNSET:
                    continue
                owner = min(copies, key=copies.get)
                threshold = elastic.speculation_threshold(
                    mean, self.speculation_factor,
                    self.min_speculation_s,
                    owner_power=self._powers.get(owner),
                    mean_power=mean_power)
                if now - copies[owner] > threshold:
                    copies[slave.id] = now
                    return (self.epoch, i, self._specs[i])
            return False            # park until an update frees work

    def unserved_remainder(self):
        """Reshard input (Server._reshard): jobs of the current batch
        not yet resolved — pending plus in-flight."""
        with self._lock:
            return sum(1 for r in self.results if r is _UNSET)

    def apply_update_validated(self, update, slave):
        """Inline-validation form for farms that opt in
        (``update_validation = "inline"``): a farm update is ONE
        opaque part, so validate-then-apply is already a single
        traversal."""
        from veles_tpu import health
        if not health.all_finite(update):
            raise health.PoisonedUpdate(self)
        return self.apply_data_from_slave(update, slave)

    def apply_data_from_slave(self, update, slave):
        epoch, i, result = update
        with self._lock:
            if epoch != self.epoch:
                # a late duplicate from a PREVIOUS batch (its job was
                # requeued or speculated and both copies eventually
                # reported): without this stamp it would silently
                # land in the current batch's slot i
                return True
            copies = self._outstanding.get(i)
            t0 = None
            if copies is not None and slave is not None:
                t0 = copies.pop(slave.id, None)
            if t0 is not None:
                self._durations.append(time.perf_counter() - t0)
            if self.results[i] is not _UNSET:
                return True         # a backup copy finished first
            self.results[i] = result
            self._outstanding.pop(i, None)
            self._remaining -= 1
            finished = self._remaining == 0
        if finished:
            self.done.set()
        return True

    def drop_slave(self, slave):
        with self._lock:
            # a departed member's rating must not keep skewing the
            # fleet-mean power the speculation threshold divides by
            self._powers.pop(slave.id, None)
            for i in list(self._outstanding):
                copies = self._outstanding[i]
                copies.pop(slave.id, None)
                if not copies and self.results[i] is _UNSET:
                    # no other copy in flight: requeue at the front so
                    # the oldest failure is retried first
                    del self._outstanding[i]
                    self._pending.appendleft((i, self._specs[i]))

    def apply_initial_data_from_master(self, data):  # pragma: no cover
        raise AssertionError("master adapter used as a slave")


class _FarmSlave(object):
    """Workflow-contract adapter the Client drives on a worker.

    When the master ships a shared context, the runner is called as
    ``runner(spec, context)``; otherwise ``runner(spec)``."""

    _NO_CTX = object()

    def __init__(self, checksum, runner):
        self.checksum = checksum
        self.runner = runner
        self.context = self._NO_CTX

    def apply_initial_data_from_master(self, initial):
        if isinstance(initial, tuple) and len(initial) == 2 \
                and initial[0] == "ctx":
            self.context = initial[1]

    def do_job(self, data, update, callback):
        epoch, i, spec = data
        try:
            if self.context is self._NO_CTX:
                result = self.runner(spec)
            else:
                result = self.runner(spec, self.context)
            callback((epoch, i, ("ok", result)))
        except Exception as exc:  # travels back; farm fails loudly
            callback((epoch, i, ("err", repr(exc))))


class JobFarm(Logger):
    """Farm independent picklable jobs across control-plane workers."""

    def __init__(self, tag, codec=None, speculation_factor=2.0,
                 min_speculation_s=5.0, context=None,
                 job_timeout=DEFAULT_JOB_TIMEOUT, **server_kwargs):
        super(JobFarm, self).__init__()
        self.tag = tag
        self.codec = codec
        self.speculation_factor = speculation_factor
        self.min_speculation_s = min_speculation_s
        self.context = context
        self.job_timeout = job_timeout
        self.server_kwargs = server_kwargs
        self.server = None
        self._master = None
        self._clients = []
        self._threads = []

    @property
    def checksum(self):
        """Stands in for the trainer's source checksum: master and
        workers agree on the job TYPE, not on a workflow file."""
        return hashlib.sha1(
            ("jobfarm:%s" % self.tag).encode()).hexdigest()

    @property
    def address(self):
        """host:port remote workers join (valid once started)."""
        if self.server is None:
            return None
        return "%s:%d" % (self.server.host, self.server.port)

    # -- master side ----------------------------------------------------

    def start(self, runner=None, address="127.0.0.1:0",
              local_slaves=0):
        """Bind the farm master and spawn ``local_slaves`` in-process
        workers (``runner`` required then).  Remote workers can join
        ``self.address`` any time.  Returns self."""
        from veles_tpu.client import Client
        from veles_tpu.server import Server

        if self.server is not None:
            raise RuntimeError("farm already started")
        if local_slaves and runner is None:
            raise ValueError("local_slaves > 0 requires a runner")
        self._master = _FarmMaster(self.checksum,
                                   self.speculation_factor,
                                   self.min_speculation_s,
                                   context=self.context)
        self.server = Server(address, self._master, codec=self.codec,
                             job_timeout=self.job_timeout,
                             **self.server_kwargs)
        self.server.start_background()
        if not self.server.wait_listening(10):
            exc = self.server.bind_error
            self.server = None
            raise RuntimeError(
                "farm master failed to bind %s: %r" % (address, exc))
        self.info("farm '%s' serving at %s (join remote workers with "
                  "JobFarm(%r).worker(%r, runner))",
                  self.tag, self.address, self.tag, self.address)
        for _ in range(local_slaves):
            client = Client(self.address,
                            _FarmSlave(self.checksum, runner),
                            codec=self.codec)
            self._clients.append(client)
            self._threads.append(client.start_background())
        return self

    def submit(self, jobs, timeout=None):
        """Serve one batch until every result is in; return them in
        job order.  ``timeout`` (seconds) bounds the batch; on expiry
        a :class:`FarmJobError` reports what was unfinished."""
        if self.server is None:
            raise RuntimeError("start() the farm first")
        jobs = list(jobs)
        if not jobs:
            return []
        master = self._master
        master.reset(jobs)
        # workers park passively between batches; release them
        self.server.resume()
        if not master.done.wait(timeout):
            missing = [i for i, r in enumerate(master.results)
                       if r is _UNSET]
            raise FarmJobError(
                "farm timed out after %ss with %d/%d jobs unfinished "
                "(indices %s)" % (timeout, len(missing), len(jobs),
                                  missing[:10]))
        errors = [(i, r[1]) for i, r in enumerate(master.results)
                  if r[0] == "err"]
        if errors:
            raise FarmJobError(
                "%d/%d farmed jobs raised on their workers: %s" % (
                    len(errors), len(jobs),
                    "; ".join("job %d: %s" % e for e in errors[:5])))
        return [r[1] for r in master.results]

    def shutdown(self):
        """Stop the master; local and remote workers exit their loops."""
        if self.server is None:
            return
        self.server.stop()
        self.server._done.wait(10)
        for thread in self._threads:
            thread.join(10)
        self.server = None
        self._master = None
        self._clients = []
        self._threads = []

    def run(self, jobs, runner=None, address="127.0.0.1:0",
            local_slaves=0, timeout=None, on_listening=None):
        """One-shot convenience: start -> submit -> shutdown.
        ``on_listening`` (optional) receives the bound Server before
        jobs are served — e.g. to launch workers against its port."""
        self.start(runner=runner, address=address,
                   local_slaves=local_slaves)
        try:
            if on_listening is not None:
                on_listening(self.server)
            return self.submit(jobs, timeout=timeout)
        finally:
            self.shutdown()

    # -- worker side ----------------------------------------------------

    def worker(self, address, runner, **client_kwargs):
        """Blocking worker loop for a remote host: execute farmed jobs
        until the master shuts down.  Quote the master's tag."""
        from veles_tpu.client import Client

        client = Client(address, _FarmSlave(self.checksum, runner),
                        codec=self.codec, **client_kwargs)
        client.run()
        return client.jobs_done
