"""Shared mutable values used for control flow between units.

TPU-native counterpart of the reference's mutable module
(reference: veles/mutable.py:44,90,101).

``Bool`` is a shared, mutable boolean cell.  Units hold references to the
same cell so that one unit flipping a flag is instantly visible to every
gate that tests it.  Boolean operators (``|``, ``&``, ``~``, ``^``) build
*derived* cells that recompute from their operands on read, which is how
gate expressions like ``decision.complete | loader.train_ended`` stay live.

``LinkableAttribute`` aliases an attribute of one object to an attribute of
another (one- or two-way), which is how ``unit.link_attrs`` shares tensors
and scalars across the graph without copying.
"""

__all__ = ["Bool", "LinkableAttribute"]


def _op_or(a, b):
    return bool(a) or bool(b)


def _op_and(a, b):
    return bool(a) and bool(b)


def _op_xor(a, b):
    return bool(a) != bool(b)


def _op_not(a):
    return not bool(a)


#: named expression ops: picklable (unlike lambdas), so derived gate
#: expressions stay LIVE across snapshot/restore
_BOOL_OPS = {"or": _op_or, "and": _op_and, "xor": _op_xor, "not": _op_not}


class Bool(object):
    """A mutable boolean cell supporting live derived expressions."""

    __slots__ = ("_value", "_op", "_args", "on_change")

    def __init__(self, value=False):
        self._op = None
        self._args = ()
        self._value = bool(value)
        self.on_change = None

    # -- value access ------------------------------------------------------

    def __bool__(self):
        if self._op is not None:
            return _BOOL_OPS[self._op](*self._args)
        return self._value

    __nonzero__ = __bool__

    @property
    def derived(self):
        return self._op is not None

    def __ilshift__(self, value):
        """``flag <<= True`` assigns; assignment breaks derivation."""
        self._op = None
        self._args = ()
        new = bool(value)
        changed = new != self._value
        self._value = new
        if changed and self.on_change is not None:
            self.on_change(self)
        return self

    # -- derivation --------------------------------------------------------

    @staticmethod
    def _derived(op, *args):
        b = Bool()
        b._op = op
        b._args = args
        return b

    def __or__(self, other):
        return Bool._derived("or", self, _as_bool(other))

    __ror__ = __or__

    def __and__(self, other):
        return Bool._derived("and", self, _as_bool(other))

    __rand__ = __and__

    def __xor__(self, other):
        return Bool._derived("xor", self, _as_bool(other))

    __rxor__ = __xor__

    def __invert__(self):
        return Bool._derived("not", self)

    def __repr__(self):
        kind = "derived" if self.derived else "plain"
        return "<Bool %s %s>" % (kind, bool(self))

    # Both plain and derived cells round-trip: the op name + operand
    # Bools pickle fine, and pickle preserves shared-object identity so
    # a gate expression still tracks the SAME source cells after
    # restore (the reference's gate-remembering semantics).
    def __getstate__(self):
        return {"value": self._value, "op": self._op, "args": self._args}

    def __setstate__(self, state):
        self._op = state.get("op")
        self._args = state.get("args", ())
        self._value = state["value"]
        self.on_change = None


def _as_bool(value):
    if isinstance(value, Bool):
        return value
    return Bool(bool(value))


class LinkableAttribute(object):
    """Alias ``obj.name`` to ``source_obj.source_name``.

    Installed as a class-level descriptor with per-instance targets, so
    several instances of the same class can link to different sources.
    Assignment through a one-way link raises unless ``assignment_guard`` is
    disabled; two-way links propagate writes back to the source.
    """

    #: name of the per-instance link table.  Deliberately has no trailing
    #: underscore: links between units pickle together with the workflow
    #: graph (matching the reference, which pickles links too), so data
    #: aliases survive snapshot/restore.
    TABLE = "_linked_attrs"

    @classmethod
    def reinstall(cls, obj):
        """Ensure class-level descriptors exist for every pickled link.

        A snapshot restored in a FRESH process carries the
        per-instance link table, but the descriptors were installed on
        the original process's class object — without this, restored
        units lose every data alias and re-initialize fails on
        unsatisfied demands."""
        table = obj.__dict__.get(cls.TABLE)
        if not table:
            return
        klass = type(obj)
        for name in table:
            if not isinstance(klass.__dict__.get(name),
                              _LinkDescriptor):
                setattr(klass, name, _LinkDescriptor(name))
            # a plain instance attribute would shadow the descriptor
            obj.__dict__.pop(name, None)

    def __init__(self, obj, name, source_obj, source_name,
                 two_way=False, assignment_guard=True):
        self.name = name
        self.two_way = two_way
        self.assignment_guard = assignment_guard
        cls = type(obj)
        descriptor = cls.__dict__.get(name)
        if not isinstance(descriptor, _LinkDescriptor):
            descriptor = _LinkDescriptor(name)
            # Remove any plain instance attribute that would shadow us.
            setattr(cls, name, descriptor)
        obj.__dict__.pop(name, None)
        table = obj.__dict__.setdefault(LinkableAttribute.TABLE, {})
        table[name] = (source_obj, source_name, two_way, assignment_guard)

    @staticmethod
    def unlink(obj, name):
        """Remove the alias; the attribute becomes a plain instance attr."""
        table = obj.__dict__.get(LinkableAttribute.TABLE)
        if table is not None:
            table.pop(name, None)


class _LinkDescriptor(object):
    """Class-level descriptor reading per-instance link targets from the
    instance's own ``_linked_attrs`` table (no global id-keyed state, so
    no leaks, no id-reuse aliasing, and pickling just works)."""

    def __init__(self, name):
        self.name = name

    def _target(self, obj):
        table = obj.__dict__.get(LinkableAttribute.TABLE)
        if table is None:
            return None
        return table.get(self.name)

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        target = self._target(obj)
        if target is None:
            try:
                return obj.__dict__[self.name]
            except KeyError:
                raise AttributeError(self.name)
        source_obj, source_name, _, _ = target
        return getattr(source_obj, source_name)

    def __set__(self, obj, value):
        target = self._target(obj)
        if target is None:
            obj.__dict__[self.name] = value
            return
        source_obj, source_name, two_way, guard = target
        if two_way or not guard:
            setattr(source_obj, source_name, value)
        else:
            raise AttributeError(
                "%s.%s is linked one-way from %s.%s; breaking the link by "
                "assignment is forbidden" %
                (type(obj).__name__, self.name,
                 type(source_obj).__name__, source_name))

    def __delete__(self, obj):
        table = obj.__dict__.get(LinkableAttribute.TABLE)
        if table is not None:
            table.pop(self.name, None)
