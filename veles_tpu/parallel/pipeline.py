"""Pipeline parallelism over a mesh axis (GPipe-style microbatching).

No reference behavior to match (the 2015 platform had only
parameter-server DP); this is a native capability of the parallel
layer.  Stage parameters live stacked with a leading stage dimension
sharded over the ``pipe`` axis — each device holds ONE stage.  The
schedule is the classic skewed wavefront: at tick t, device p runs
microbatch (t - p); activations hop to the next stage via
``lax.ppermute`` over ICI each tick; total ticks = M + P - 1 for M
microbatches over P stages.  Autodiff through the scan gives the
backward pipeline for free (tested against the sequential oracle).

Constraint (classic GPipe): every stage maps activations to the SAME
shape, so the rotating buffer is well-formed — which is exactly the
transformer-block contract ((B, T, D) -> (B, T, D)), making the block
stack the natural stage payload: :func:`build_pipeline_train_step`
splits a transformer model's homogeneous block run into contiguous
stage groups over the axis and keeps the head (and any prefix) layers
replicated, trained off the psum-replicated final activations.  With
``microbatches=1`` every stage executes the EXACT op sequence of the
single-device fused step on the same values (stage hops and the
replication psum move exact bytes; discarded warm-up/drain ticks
contribute exact-zero gradients), so the split step is BIT-IDENTICAL
to the unsplit one — the receipt tests/test_transformer.py pins.
``microbatches>1`` accumulates per-microbatch wgrads inside the scan
(a different f32 grouping than the whole-batch contraction):
documented-ULP-bounded, same as the tensor-parallel bound.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.parallel.mesh import shard_map

__all__ = ["pipeline_forward", "stack_stage_params",
           "stage_param_sharding", "build_pipeline_train_step",
           "stack_pipeline_state", "unstack_pipeline_state"]


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> tree with leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *per_stage_params)


def stage_param_sharding(mesh, params_stacked, axis="pipe"):
    """Shard the leading (stage) dimension over the pipe axis."""
    def spec(leaf):
        return NamedSharding(mesh, P(axis))
    return jax.tree.map(
        lambda leaf: jax.device_put(leaf, spec(leaf)), params_stacked)


def pipeline_forward(stage_fn, params_stacked, x, mesh, microbatches,
                     axis="pipe", data_axis=None):
    """Run x (B, ...) through P pipelined stages; returns (B, ...).

    stage_fn(stage_params, activation) -> activation (same shape).
    params_stacked: pytree, leading dim = number of stages, sharded
    over ``axis`` (see stage_param_sharding).
    ``data_axis``: optionally shard the batch dim over a second mesh
    axis — each data-parallel row runs its own wavefront (dp x pp);
    stage params replicate across rows.
    """
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if data_axis is not None:
        rows = mesh.shape[data_axis]
        if batch % rows:
            raise ValueError("batch %d %% %s rows %d != 0" %
                             (batch, data_axis, rows))
        batch //= rows  # per-row batch, as seen inside shard_map
    if batch % microbatches:
        raise ValueError("batch %d %% microbatches %d != 0" %
                         (batch, microbatches))

    def sharded(params_local, x_full):
        # params_local: leading dim 1 (this device's stage)
        p = lax.axis_index(axis)
        my_params = jax.tree.map(lambda l: l[0], params_local)
        result = _wavefront(stage_fn, my_params, x_full, p, axis,
                            n_stages, microbatches, batch)
        # replicate the final activations to every pipe rank
        return lax.psum(
            jnp.where(p == n_stages - 1, result, jnp.zeros_like(result)),
            axis)

    fn = shard_map(
        sharded, mesh=mesh,
        in_specs=(P(axis), P(data_axis)), out_specs=P(data_axis),
        check_vma=False)
    return fn(params_stacked, x)


def _wavefront(stage_fn, my_params, x_full, p, axis, n_stages,
               microbatches, batch):
    """The skewed-wavefront scan shared by :func:`pipeline_forward`
    and the train step: returns the (batch, ...) result as produced on
    the LAST stage (garbage elsewhere — callers mask + replicate).
    Warm-up/drain ticks process finite garbage whose outputs get zero
    cotangents, so their gradient contributions are exact zeros."""
    if batch % microbatches:
        # a clear trace-time error, not a reshape failure deep in jit
        raise ValueError("batch %d %% microbatches %d != 0"
                         % (batch, microbatches))
    mbs = x_full.reshape((microbatches, batch // microbatches) +
                         x_full.shape[1:])
    ticks = microbatches + n_stages - 1
    buf = jnp.zeros_like(mbs[0])
    perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

    def tick(buf, t):
        mb_idx = t - p
        inject = mbs[jnp.clip(mb_idx, 0, microbatches - 1)]
        current = jnp.where(p == 0, inject, buf)
        out = stage_fn(my_params, current)
        nxt = lax.ppermute(out, axis, perm)
        return nxt, out

    _, outs = lax.scan(tick, buf, jnp.arange(ticks))
    # last stage emits microbatch m at tick m + (P-1)
    tail = lax.dynamic_slice_in_dim(outs, n_stages - 1, microbatches,
                                    axis=0)
    return tail.reshape((batch,) + x_full.shape[1:])


# -- the pipeline-parallel train step ---------------------------------------


def _stage_split(plans):
    """(prefix, blocks, tail) indices: the contiguous run of
    TransformerBlock plans is the stage payload; everything before /
    after stays replicated."""
    from veles_tpu.models.transformer import TransformerBlock
    flags = [p.forward_cls is TransformerBlock for p in plans]
    if not any(flags):
        raise ValueError("no transformer-block layers to stage-split")
    start = flags.index(True)
    stop = len(flags) - flags[::-1].index(True)
    if not all(flags[start:stop]):
        raise ValueError("transformer blocks must be contiguous for "
                         "the stage split")
    return start, stop


def stack_pipeline_state(mesh, plans, state, axis="pipe"):
    """Host state -> pipeline-placed device state: the block entries
    regroup as ``blocks_per_stage`` entries whose leaves stack a
    leading stage dim sharded over ``axis`` (stack_stage_params'
    layout); prefix/tail entries replicate.  Returns (placed_state,
    layout) where ``layout`` feeds :func:`unstack_pipeline_state`."""
    import numpy

    start, stop = _stage_split(plans)
    n_stages = mesh.shape[axis]
    n_blocks = stop - start
    if n_blocks % n_stages:
        raise ValueError("%d transformer blocks %% %d stages != 0"
                         % (n_blocks, n_stages))
    per_stage = n_blocks // n_stages
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P(axis))

    def put_repl(entry):
        return {k: (None if v is None else jax.device_put(v, repl))
                for k, v in entry.items()}

    placed = [put_repl(e) for e in state[:start]]
    for j in range(per_stage):
        # entry j stacks block (stage*per_stage + j) over stages
        rows = [state[start + s * per_stage + j]
                for s in range(n_stages)]
        stacked = {}
        for key in rows[0]:
            if rows[0][key] is None:
                stacked[key] = None
            else:
                stacked[key] = jax.device_put(
                    numpy.stack([numpy.asarray(r[key])
                                 for r in rows]), shard)
        placed.append(stacked)
    placed += [put_repl(e) for e in state[stop:]]
    layout = {"start": start, "stop": stop, "per_stage": per_stage,
              "n_stages": n_stages}
    return placed, layout


def unstack_pipeline_state(placed, layout):
    """Inverse of :func:`stack_pipeline_state` -> global host state."""
    import numpy

    start, per_stage = layout["start"], layout["per_stage"]
    n_stages = layout["n_stages"]

    def host(entry):
        return {k: (None if v is None else numpy.asarray(v))
                for k, v in entry.items()}

    state = [host(e) for e in placed[:start]]
    stacked = placed[start:start + per_stage]
    blocks = []
    for s in range(n_stages):
        for j in range(per_stage):
            entry = stacked[j]
            blocks.append({
                k: (None if v is None else numpy.asarray(v)[s])
                for k, v in entry.items()})
    state += blocks
    state += [host(e) for e in placed[start + per_stage:]]
    return state


def build_pipeline_train_step(plans, loss="softmax", mesh=None,
                              axis="pipe", microbatches=1,
                              donate=True, compiler_options=None):
    """Compile the pipeline-parallel fused train step: the model's
    contiguous transformer-block run splits into ``mesh.shape[axis]``
    contiguous stage groups driven through the shared skewed wavefront
    (:func:`_wavefront`); prefix/tail layers run replicated off the
    stage stack's psum-replicated output.  State must be placed with
    :func:`stack_pipeline_state`.

    The replication step is a psum-forward/identity-backward
    custom_vjp (``parallel.tensor.psum_conjugates``): differentiating
    a plain ``lax.psum`` inside shard_map inflates cotangents by the
    axis size (see parallel/tensor.py), and identity IS the correct
    transpose here — each rank's tail consumes its own replicated
    copy.  The numerics guard psums the stage-shard grad-norm over the
    axis so a poisoned step skips uniformly on every stage.

    Same fixed-arity contract as ``compiler.build_train_step`` with
    ``.lower`` exposed for step-FLOPs introspection."""
    from veles_tpu import compiler as _compiler
    from veles_tpu.parallel.tensor import psum_conjugates

    if mesh is None:
        raise ValueError("build_pipeline_train_step needs a mesh")
    start, stop = _stage_split(plans)
    n_stages = mesh.shape[axis]
    n_blocks = stop - start
    if n_blocks % n_stages:
        raise ValueError("%d transformer blocks %% %d stages != 0"
                         % (n_blocks, n_stages))
    per_stage = n_blocks // n_stages
    block_plans = plans[start:stop]
    for p in block_plans[1:]:
        if p.hyper_full() != block_plans[0].hyper_full() or \
                p.static != block_plans[0].static:
            raise ValueError(
                "stage-split blocks must share hyper/static config "
                "(stacked entries update under one plan)")
    # the step's reduced plan list: one entry per STACKED block slot
    step_plans = (plans[:start] + block_plans[:per_stage] +
                  plans[stop:])
    enter, leave = psum_conjugates(axis)

    def forward_fn(params, x, key, remat):
        p = lax.axis_index(axis)
        prefix, stacked = params[:start], params[start:start +
                                                 per_stage]
        tail = params[start + per_stage:]
        h = x
        if prefix:
            h = _compiler._forward_for_loss(
                plans[:start], prefix, h, key, remat=remat)
            # the wavefront consumes h only on stage 0 (the where-
            # injection), so the raw cotangent reaching the prefix is
            # zero on every other rank; the enter conjugate psums it,
            # making the prefix backward — and thus the 'replicated'
            # prefix updates and their share of the finiteness norm —
            # bit-identical on every rank (the replication invariant
            # out_specs P() promises)
            h = enter(h)
        my_blocks = [jax.tree.map(lambda l: l[0], e) for e in stacked]
        statics = [pl.static for pl in block_plans[:per_stage]]

        def stage_fn(block_params, a):
            from veles_tpu.models.transformer import TransformerBlock
            for bp, static in zip(block_params, statics):
                a = TransformerBlock.apply(bp, a, **static)
            return a

        result = _wavefront(stage_fn, my_blocks, h, p, axis, n_stages,
                            microbatches, h.shape[0])
        h = leave(jnp.where(p == n_stages - 1, result,
                            jnp.zeros_like(result)))
        if tail:
            # fold_offset: dropout layers after the block run must key
            # on their GLOBAL layer index, exactly like the fused step
            h = _compiler._forward_for_loss(
                plans[stop:], tail, h, key, remat=remat,
                fold_offset=stop)
        return h

    staged = set(range(start, start + per_stage))

    def gsq_fn(grads):
        # stage shards see only their own wgrads; the psum makes the
        # guard's norm global so poisoned steps skip on every stage
        from veles_tpu.parallel.tensor import sharded_gsq
        return sharded_gsq(grads, staged, axis)

    raw = _compiler._build_step_fn(step_plans, loss,
                                   forward_fn=forward_fn,
                                   gsq_fn=gsq_fn)

    state_spec = ([P()] * start + [P(axis)] * per_stage +
                  [P()] * (len(plans) - stop))
    spmd = shard_map(
        raw, mesh=mesh,
        in_specs=(state_spec, P(), P(), P(), P(), P(), P()),
        out_specs=(state_spec, P()), check_vma=False)
    return _compiler._finalize_step(
        spmd, donate, compiler_options, mesh=mesh, pipe_axis=axis,
        microbatches=microbatches)
