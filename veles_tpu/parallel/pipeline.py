"""Pipeline parallelism over a mesh axis (GPipe-style microbatching).

No reference behavior to match (the 2015 platform had only
parameter-server DP); this is a native capability of the parallel
layer.  Stage parameters live stacked with a leading stage dimension
sharded over the ``pipe`` axis — each device holds ONE stage.  The
schedule is the classic skewed wavefront: at tick t, device p runs
microbatch (t - p); activations hop to the next stage via
``lax.ppermute`` over ICI each tick; total ticks = M + P - 1 for M
microbatches over P stages.  Autodiff through the scan gives the
backward pipeline for free (tested against the sequential oracle).

Constraint (classic GPipe): every stage maps activations to the SAME
shape, so the rotating buffer is well-formed.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.parallel.mesh import shard_map

__all__ = ["pipeline_forward", "stack_stage_params",
           "stage_param_sharding"]


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> tree with leading stage dim."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves),
                        *per_stage_params)


def stage_param_sharding(mesh, params_stacked, axis="pipe"):
    """Shard the leading (stage) dimension over the pipe axis."""
    def spec(leaf):
        return NamedSharding(mesh, P(axis))
    return jax.tree.map(
        lambda leaf: jax.device_put(leaf, spec(leaf)), params_stacked)


def pipeline_forward(stage_fn, params_stacked, x, mesh, microbatches,
                     axis="pipe", data_axis=None):
    """Run x (B, ...) through P pipelined stages; returns (B, ...).

    stage_fn(stage_params, activation) -> activation (same shape).
    params_stacked: pytree, leading dim = number of stages, sharded
    over ``axis`` (see stage_param_sharding).
    ``data_axis``: optionally shard the batch dim over a second mesh
    axis — each data-parallel row runs its own wavefront (dp x pp);
    stage params replicate across rows.
    """
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if data_axis is not None:
        rows = mesh.shape[data_axis]
        if batch % rows:
            raise ValueError("batch %d %% %s rows %d != 0" %
                             (batch, data_axis, rows))
        batch //= rows  # per-row batch, as seen inside shard_map
    if batch % microbatches:
        raise ValueError("batch %d %% microbatches %d != 0" %
                         (batch, microbatches))

    def sharded(params_local, x_full):
        # params_local: leading dim 1 (this device's stage)
        p = lax.axis_index(axis)
        my_params = jax.tree.map(lambda l: l[0], params_local)
        mbs = x_full.reshape((microbatches, batch // microbatches) +
                             x_full.shape[1:])
        ticks = microbatches + n_stages - 1
        buf = jnp.zeros_like(mbs[0])
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

        def tick(buf, t):
            mb_idx = t - p
            inject = mbs[jnp.clip(mb_idx, 0, microbatches - 1)]
            current = jnp.where(p == 0, inject, buf)
            out = stage_fn(my_params, current)
            nxt = lax.ppermute(out, axis, perm)
            return nxt, out

        _, outs = lax.scan(tick, buf, jnp.arange(ticks))
        # last stage emits microbatch m at tick m + (P-1)
        tail = lax.dynamic_slice_in_dim(outs, n_stages - 1,
                                        microbatches, axis=0)
        result = tail.reshape((batch,) + x_full.shape[1:])
        # replicate the final activations to every pipe rank
        return lax.psum(
            jnp.where(p == n_stages - 1, result, jnp.zeros_like(result)),
            axis)

    fn = shard_map(
        sharded, mesh=mesh,
        in_specs=(P(axis), P(data_axis)), out_specs=P(data_axis),
        check_vma=False)
    return fn(params_stacked, x)
