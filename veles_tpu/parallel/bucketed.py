"""Bucketed gradient all-reduce overlapped with the backward pass.

SCALING.json's round-5 receipt: every distributed step all-reduced one
flat ~250 MB gradient pytree with no overlap credited — the comm sat
serially behind the whole backward.  This module is the SPMD data
plane's fix (the TensorFlow-paper split, PAPERS.md: dataflow inner
loop, control-plane outer loop):

- :func:`plan_buckets` partitions the gradient pytree into
  size-targeted buckets (default ~25 MB, ``--grad-bucket-mb``),
  walking the leaves in REVERSE layer order — the order the backward
  pass produces them — so bucket 0 is ready while most of the
  backward is still running.  Leaves larger than a bucket are split at
  exact element boundaries (a leaf may straddle a bucket edge).
- :func:`bucketed_all_reduce` issues one collective per bucket inside
  a ``shard_map``-ed step, chained through
  ``lax.optimization_barrier`` so XLA's all-reduce combiner cannot
  re-fuse them into the flat monolith and the latency-hiding scheduler
  (async ``all-reduce-start``/``-done`` on TPU) can overlap each
  bucket's wire time with the remaining backward + update compute.
  Bit-identical to the flat single-tensor all-reduce: ``psum`` is
  elementwise and the concatenate/slice round-trip is exact
  (tests/test_bucketed.py proves every boundary case).
- optional ``compress="bf16"`` halves the wire bytes; the step-level
  numerics guard (docs/health.md) covers the rounding: a compressed
  step whose grads go non-finite is SKIPPED bit-exactly and the
  trainer auto-falls back to f32 (``FusedTrainer.on_health_sync``).
- :func:`overlap_model` / :func:`comm_receipt` /
  :func:`publish_comm_receipt` are the observability half: an
  analytic overlap-credited schedule (shared with scripts/scaling.py)
  published as ``comm.*`` gauges and per-bucket spans through the
  PR 4-5 observe stack.
"""

import math
import time

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["DEFAULT_BUCKET_MB", "Bucket", "BucketPlan", "plan_buckets",
           "bucketed_all_reduce", "flat_all_reduce", "overlap_model",
           "comm_receipt", "publish_comm_receipt", "shard_elems",
           "slot_matrix", "unslot_matrix", "chained_reduce_scatter",
           "gather_slots"]

#: default bucket size target.  25 MB rides the knee of the v5e ring
#: model: big enough that per-hop launch latency stays < 3 % of a
#: bucket's wire time, small enough that a ~250 MB AlexNet gradient
#: splits into ~10 buckets and the first all-reduce issues while ~90 %
#: of the backward is still outstanding.
DEFAULT_BUCKET_MB = 25.0

# jax API drift guard: optimization_barrier moved/appeared across
# releases; without it the buckets still all-reduce correctly, XLA is
# just free to re-combine them (the dist smoke test will catch that
# on toolchains where it matters)
_opt_barrier = getattr(lax, "optimization_barrier", None)


class Bucket(object):
    """One all-reduce payload: contiguous element spans of flattened
    gradient leaves.  ``slices`` holds ``(leaf_index, start, stop)``
    element ranges (into the leaf's 1-D view)."""

    __slots__ = ("slices", "elems", "nbytes")

    def __init__(self):
        self.slices = []
        self.elems = 0
        self.nbytes = 0

    def __repr__(self):
        return "<Bucket %d leaves %d elems %.2f MB>" % (
            len(self.slices), self.elems, self.nbytes / 2.0 ** 20)


class BucketPlan(object):
    """Static partition of a gradient pytree's leaves into buckets,
    ordered by backward-pass production (last layer first)."""

    __slots__ = ("buckets", "n_leaves", "total_elems", "total_bytes",
                 "bucket_bytes")

    def __init__(self, buckets, n_leaves, bucket_bytes):
        self.buckets = buckets
        self.n_leaves = n_leaves
        self.total_elems = sum(b.elems for b in buckets)
        self.total_bytes = sum(b.nbytes for b in buckets)
        self.bucket_bytes = bucket_bytes

    def __repr__(self):
        return "<BucketPlan %d buckets / %d leaves / %.1f MB>" % (
            len(self.buckets), self.n_leaves,
            self.total_bytes / 2.0 ** 20)


def _leaf_meta(leaf):
    """(n_elements, itemsize) for an array / ShapeDtypeStruct leaf."""
    size = int(math.prod(leaf.shape)) if leaf.shape else 1
    return size, int(jnp.dtype(leaf.dtype).itemsize)


def plan_buckets(leaves, bucket_bytes=None):
    """Partition ``leaves`` (arrays or ShapeDtypeStructs, in pytree
    order) into size-targeted buckets.

    Leaves are walked in REVERSE order — the backward pass produces
    the LAST layer's gradients first, so bucket 0 holds the grads that
    exist earliest and its all-reduce can overlap the rest of the
    backward.  A leaf that does not fit the current bucket's remaining
    capacity is split at the exact element boundary; an oversized leaf
    therefore spans several buckets.  ``bucket_bytes=None`` means the
    :data:`DEFAULT_BUCKET_MB` target; ``inf`` (or any target >= the
    total) yields ONE bucket — the flat single-tensor all-reduce,
    which doubles as the bit-equality reference.
    """
    if bucket_bytes is None:
        bucket_bytes = DEFAULT_BUCKET_MB * 2.0 ** 20
    elif bucket_bytes <= 0:
        raise ValueError("bucket_bytes must be positive, got %r"
                         % (bucket_bytes,))
    buckets = []
    cur = Bucket()
    for i in reversed(range(len(leaves))):
        size, item = _leaf_meta(leaves[i])
        pos = 0
        while pos < size:
            room = bucket_bytes - cur.nbytes
            if room < item and cur.slices:
                buckets.append(cur)
                cur = Bucket()
                room = bucket_bytes
            take = size - pos
            if room < take * item:
                # at least one element per span, so a bucket target
                # smaller than one element still makes progress
                take = max(int(room // item), 1)
            cur.slices.append((i, pos, pos + take))
            cur.elems += take
            cur.nbytes += take * item
            pos += take
            if cur.nbytes >= bucket_bytes:
                buckets.append(cur)
                cur = Bucket()
    if cur.slices:
        buckets.append(cur)
    return BucketPlan(buckets, len(leaves), bucket_bytes)


def _reduce_one(vec, axis_name, impl, compress, axis_size):
    """All-reduce ONE bucket vector over ``axis_name``."""
    wire = vec
    if compress == "bf16" and vec.dtype == jnp.float32:
        # lossy wire format; the step-level finiteness guard plus the
        # trainer's f32 fallback (docs/health.md) own the failure mode
        wire = vec.astype(jnp.bfloat16)
    elif compress not in (None, "bf16"):
        raise ValueError("unknown gradient compression %r" % (compress,))
    if impl == "ring":
        from veles_tpu.parallel.ring import ring_all_reduce
        if axis_size is None:
            raise ValueError("impl='ring' needs axis_size")
        out = ring_all_reduce(wire, axis_name, axis_size)
    elif impl == "psum":
        out = lax.psum(wire, axis_name)
    else:
        raise ValueError("unknown all-reduce impl %r" % (impl,))
    return out.astype(vec.dtype)


def bucketed_all_reduce(grads, axis_name, bucket_bytes=None, plan=None,
                        impl="psum", compress=None, axis_size=None,
                        chain=True):
    """Sum a gradient pytree over a mesh axis, one collective per
    bucket, inside a ``shard_map``-ed computation.

    ``chain=True`` threads each bucket's input through an
    ``optimization_barrier`` on the previous bucket's RESULT: the
    collectives stay distinct ops in the optimized HLO (XLA's
    all-reduce combiner would otherwise glue them back into the flat
    monolith) and issue in production order, which is what lets the
    latency-hiding scheduler overlap bucket k's wire time with the
    compute that produces buckets k+1.. .

    Bit-identity: ``psum`` is elementwise, the bucket concatenate /
    slice round-trip is exact, and dtypes never change (without
    ``compress``), so ANY bucketing — including pathological splits —
    produces results bit-identical to the flat single-tensor
    all-reduce.  ``impl="ring"`` (ppermute reduce-scatter +
    all-gather, parallel/ring.py) changes the summation ORDER and is
    therefore only ULP-close, not bit-equal, to psum.
    """
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    if plan is None:
        plan = plan_buckets(leaves, bucket_bytes)
    flats = [leaf.reshape((-1,)) for leaf in leaves]
    pieces = [[] for _ in leaves]
    token = None
    for bucket in plan.buckets:
        parts = [flats[i][start:stop]
                 for (i, start, stop) in bucket.slices]
        vec = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        if chain and token is not None and _opt_barrier is not None:
            vec, _ = _opt_barrier((vec, token))
        vec = _reduce_one(vec, axis_name, impl, compress, axis_size)
        token = vec
        offset = 0
        for (i, start, stop) in bucket.slices:
            n = stop - start
            pieces[i].append((start, vec[offset:offset + n]))
            offset += n
    out = []
    for i, leaf in enumerate(leaves):
        spans = sorted(pieces[i], key=lambda item: item[0])
        flat = (spans[0][1] if len(spans) == 1 else
                jnp.concatenate([piece for _, piece in spans]))
        out.append(flat.reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(treedef, out)


def flat_all_reduce(grads, axis_name, impl="psum", compress=None,
                    axis_size=None):
    """The flat single-tensor reference: ONE bucket spanning the whole
    pytree (what every distributed step did before bucketing)."""
    return bucketed_all_reduce(
        grads, axis_name, bucket_bytes=float("inf"), impl=impl,
        compress=compress, axis_size=axis_size, chain=False)


# -- ZeRO-1 reduce-scatter + all-gather (docs/distributed.md, "Elastic
#    mesh contract") ------------------------------------------------------
#
# The sharded-optimizer data plane replaces the flat all-reduce with
# the two halves it is made of: a reduce-scatter hands each device the
# SUMMED gradient rows of the shards it owns (where the solver update
# runs on 1/N of the state), and an all-gather re-replicates the
# updated params.  ``lax.psum_scatter(tiled=True)`` is bit-identical to
# ``psum`` + slice on every row (tests/test_mesh.py proves it), so the
# split costs no numerics.  Shard-to-device placement is a runtime
# *slot table* (int32, one logical-shard id per device slot, the pad
# id pointing at an all-zero row), so the compiled step is independent
# of WHICH device owns which shard — a reshard changes only the table,
# and the digest-keyed compile cache stays warm.

def shard_elems(size, n_shards):
    """Per-shard element count for a tensor of ``size`` elements split
    into ``n_shards`` logical shards (ceil-div; the last shard pads)."""
    return -(-int(size) // max(int(n_shards), 1))


def slot_matrix(flat, slots, n_shards, elems):
    """Arrange a flattened tensor into per-slot rows: pad ``flat`` to
    ``n_shards * elems``, reshape to (n_shards, elems), append one
    all-zero pad row (logical id ``n_shards``), and gather rows by the
    ``slots`` table — the (n_slots, elems) matrix whose row i is the
    shard device ``i // slots_per_device`` hosts in slot ``i``."""
    flat = flat.reshape((-1,))
    pad = n_shards * elems - flat.shape[0]
    if pad:
        flat = jnp.pad(flat, (0, pad))
    mat = flat.reshape((n_shards, elems))
    mat = jnp.concatenate([mat, jnp.zeros((1, elems), mat.dtype)])
    return jnp.take(mat, slots, axis=0)


def unslot_matrix(rows, slots, n_shards, size, shape, dtype):
    """Invert :func:`slot_matrix`: scatter slot rows back to canonical
    shard order (pad slots all target the dropped row ``n_shards``),
    strip the padding, and reshape to the tensor's ``shape``."""
    elems = rows.shape[-1]
    full = jnp.zeros((n_shards + 1, elems), dtype).at[slots].set(
        rows.astype(dtype))
    return full[:n_shards].reshape((-1,))[:size].reshape(shape)


def chained_reduce_scatter(mats, axis_name, chain=True):
    """Reduce-scatter each (n_slots, elems) slot matrix over
    ``axis_name``; device r receives the summed rows
    ``[r*k, (r+1)*k)`` (k = n_slots / axis size) — its owned shards.

    ``mats`` arrive in backward PRODUCTION order (last layer first) and
    ``chain=True`` threads each input through an
    ``optimization_barrier`` on the previous result, the same
    scheduling contract as :func:`bucketed_all_reduce`: collectives
    stay distinct and issue while the backward still runs.  Returns
    the per-device (k, elems) shard matrices, same order.
    ``psum_scatter`` sums in ``psum``'s order, so every returned row is
    bit-identical to the matching rows of a flat all-reduce."""
    out = []
    token = None
    for mat in mats:
        if chain and token is not None and _opt_barrier is not None:
            mat, _ = _opt_barrier((mat, token))
        part = lax.psum_scatter(mat, axis_name, scatter_dimension=0,
                                tiled=True)
        token = part
        out.append(part)
    return out


def gather_slots(part, axis_name):
    """All-gather the per-device (k, elems) shard rows back to the full
    (n_slots, elems) slot matrix — the replication half of the ZeRO-1
    update (params come back identical on every device)."""
    return lax.all_gather(part, axis_name, axis=0, tiled=True)


# -- analytic overlap model (shared with scripts/scaling.py) --------------

def overlap_model(grad_bytes, n_buckets, n_devices, step_seconds=None,
                  ici_gbps=100.0, hop_latency_s=1e-6, bwd_fraction=0.6):
    """Overlap-credited ring all-reduce schedule for one train step.

    Wire time is the standard ring bound 2(n-1)/n * bytes / bw; launch
    latency is paid PER BUCKET (2(n-1) hops each — reduce-scatter +
    all-gather), so more buckets buy overlap at a latency premium.
    Bucket k's all-reduce can hide behind the backward compute that
    produces buckets k+1.., i.e. behind ``bwd_fraction`` of the
    single-chip step scaled by (B-1)/B; the LAST bucket is never
    hidable (nothing runs behind it), so exposed comm is at least one
    bucket's share.  ``bwd_fraction`` defaults to 0.6 from MFU.json's
    round-5 attribution (backward+update dominates the step at 42 %
    MFU vs the forward's 71 %).  ``step_seconds=None`` (no measured
    step time yet) credits NO overlap — the model never invents a
    window it cannot size.
    """
    n = max(int(n_devices), 1)
    n_buckets = max(int(n_buckets), 1)
    bw = ici_gbps * 1e9
    t_wire = (2.0 * (n - 1) / n) * grad_bytes / bw if n > 1 else 0.0
    t_lat = n_buckets * 2 * (n - 1) * hop_latency_s
    t_comm = t_wire + t_lat
    if step_seconds and n_buckets > 1:
        window = (bwd_fraction * step_seconds *
                  (n_buckets - 1.0) / n_buckets)
    else:
        window = 0.0
    tail = t_comm / n_buckets
    hidden = min(max(t_comm - tail, 0.0), window)
    exposed = t_comm - hidden
    return {
        "n_devices": n,
        "n_buckets": n_buckets,
        "t_comm_s": t_comm,
        "t_comm_hidden_s": hidden,
        "t_comm_exposed_s": exposed,
        "overlap_pct": round(100.0 * hidden / t_comm, 2) if t_comm
        else 0.0,
        "bwd_fraction": bwd_fraction,
        "ici_usable_gbps": ici_gbps,
        "hop_latency_s": hop_latency_s,
    }


def comm_receipt(grad_leaves, n_devices, bucket_bytes=None,
                 step_seconds=None, compress=None, ici_gbps=100.0,
                 hop_latency_s=1e-6, bwd_fraction=0.6):
    """Build the per-step communication receipt for a gradient pytree:
    the exact bucket partition (``plan_buckets`` is deterministic, so
    this is the same plan the compiled step runs) plus the modeled
    overlap schedule.  ``compress="bf16"`` halves the wire bytes."""
    plan = plan_buckets(grad_leaves, bucket_bytes)
    bucket_sizes = [b.nbytes for b in plan.buckets]
    wire_bytes = plan.total_bytes
    if compress == "bf16":
        wire_bytes //= 2
    model = overlap_model(
        wire_bytes, len(bucket_sizes), n_devices,
        step_seconds=step_seconds, ici_gbps=ici_gbps,
        hop_latency_s=hop_latency_s, bwd_fraction=bwd_fraction)
    return {
        "allreduce_bytes": plan.total_bytes,
        "wire_bytes": wire_bytes,
        "compress": compress,
        "bucket_bytes": bucket_sizes,
        "bucket_target_bytes": (None if math.isinf(plan.bucket_bytes)
                                else int(plan.bucket_bytes)),
        "model": model,
    }


def publish_comm_receipt(receipt, tracer=None, registry=None):
    """Flow a :func:`comm_receipt` through the observe stack:
    ``comm.allreduce_bytes`` / ``comm.overlap_pct`` / ``comm.buckets``
    gauges, plus one ``comm.bucket`` span per bucket on the caller's
    trace track (the MODELED schedule, stamped as such in the span
    args — per-bucket device timing is not host-visible from inside
    one XLA dispatch; the compile-only collective-bytes receipts in
    SCALING.json are the measured half)."""
    from veles_tpu.observe.metrics import registry as _registry
    from veles_tpu.observe.trace import tracer as _tracer
    reg = registry if registry is not None else _registry
    model = receipt["model"]
    reg.gauge("comm.allreduce_bytes").set(receipt["allreduce_bytes"])
    reg.gauge("comm.buckets").set(len(receipt["bucket_bytes"]))
    reg.gauge("comm.overlap_pct").set(model["overlap_pct"])
    tr = tracer if tracer is not None else _tracer
    if not tr.active:
        return
    total = max(sum(receipt["bucket_bytes"]), 1)
    cursor = time.perf_counter()
    for index, nbytes in enumerate(receipt["bucket_bytes"]):
        dur = model["t_comm_s"] * nbytes / total
        tr.complete("comm.bucket", cursor, dur, cat="comm",
                    args={"index": index, "bytes": nbytes,
                          "modeled": True})
        cursor += dur
    tr.instant("comm.receipt", cat="comm",
               buckets=len(receipt["bucket_bytes"]),
               allreduce_bytes=receipt["allreduce_bytes"],
               overlap_pct=model["overlap_pct"],
               compress=receipt.get("compress") or "none")
