"""Tensor parallelism: transformer blocks sharded over a ``model``
mesh axis (Megatron-style head/column splits).

Going past pure data-parallel for models that don't fit one chip
("TensorFlow: A system for large-scale machine learning", PAPERS.md):
attention heads shard over the axis (each device projects, attends and
output-projects ITS heads), the position-wise MLP column-splits W1 /
row-splits W2 — so each block pays exactly TWO activation psums per
direction (one per sub-layer), placed on the residual trunk where they
compose with the bucketed data-axis gradient plane
(parallel/bucketed.py): activation psums ride the ``model`` axis inside
the step, gradient buckets ride the ``data`` axis after the backward,
and the numerics guard sees the model-axis-psummed global grad norm so
a poisoned step skips uniformly on every shard.

Autodiff caveat (empirically pinned, tests/test_transformer.py): with
``check_vma=False``, differentiating THROUGH ``lax.psum`` inside
``shard_map`` multiplies cotangents by the axis size (the documented
psum-transpose asymmetry).  The forward therefore uses the conjugate
custom_vjp pair :func:`psum_conjugates` — ``enter`` (identity forward /
psum backward) where a replicated activation enters a sharded region,
``leave`` (psum forward / identity backward) where partial results
merge — the f/g operators of the Megatron formulation, which make every
parameter gradient correct by construction: sharded params get their
complete local slice gradients, replicated params get bit-identical
full gradients on every model rank.

Parity contract: the TP step is ULP-BOUNDED against the single-device
fused step (the output projection becomes a psum of per-shard partial
contractions — a different f32 reduction grouping), receipted by the
3-chained-step bound in tests/test_parallel_transformer.py; a 1-sized
model axis stays within absolute float noise (only program-structure
fusion differences remain).
"""

import functools

import numpy

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.models.transformer import (TransformerBlock,
                                          _unpack, layer_norm)
from veles_tpu.parallel.mesh import shard_map

__all__ = ["psum_conjugates", "sharded_gsq",
           "block_param_sizes_local", "split_block_shards",
           "merge_block_shards", "place_tp_state", "gather_tp_state",
           "tp_block_apply", "build_tp_train_step"]


@functools.lru_cache(maxsize=None)
def psum_conjugates(axis):
    """(enter, leave): the Megatron f/g conjugate pair for ``axis``.

    ``enter`` — identity forward, psum backward: wraps a REPLICATED
    activation entering a sharded region, so the partial cotangents the
    region produces merge back into the full gradient.
    ``leave`` — psum forward, identity backward: merges the region's
    partial outputs; the replicated cotangent passes through unchanged
    (each shard's partial has coefficient 1 in the sum).
    """

    @jax.custom_vjp
    def enter(x):
        return x

    def enter_fwd(x):
        return x, None

    def enter_bwd(_, ct):
        return (lax.psum(ct, axis),)

    enter.defvjp(enter_fwd, enter_bwd)

    @jax.custom_vjp
    def leave(x):
        return lax.psum(x, axis)

    def leave_fwd(x):
        return lax.psum(x, axis), None

    def leave_bwd(_, ct):
        return (ct,)

    leave.defvjp(leave_fwd, leave_bwd)
    return enter, leave


def sharded_gsq(grads, sharded, axis):
    """The model-parallel numerics-guard norm: squared-sum of the
    gradient leaves with the SHARDED entries (``sharded`` = set of
    layer indices whose leaves live sliced on this rank) psummed over
    ``axis``, so every shard computes the SAME global norm and a
    poisoned step skips uniformly.  Replicated entries add locally —
    their leaves are bit-identical across ranks by construction.  One
    definition, shared by the TP and pipeline step builders."""
    shard_sq = jnp.zeros((), jnp.float32)
    repl_sq = jnp.zeros((), jnp.float32)
    for i, g in enumerate(grads):
        for leaf in jax.tree_util.tree_leaves(g):
            sq = jnp.sum(jnp.square(leaf.astype(jnp.float32)))
            if i in sharded:
                shard_sq = shard_sq + sq
            else:
                repl_sq = repl_sq + sq
    return lax.psum(shard_sq, axis) + repl_sq


# -- packed-layout shard plumbing -------------------------------------------


def block_param_sizes_local(d, hidden, n_shards):
    """Per-shard (name, shape) layout of one TP transformer block —
    the local counterpart of ``transformer.block_param_sizes``:
    Wq/Wk/Wv keep their head-slice columns, Wo its head-slice rows,
    W1 its hidden columns, W2 its hidden rows; LN gains and the
    post-psum biases (b_o, b2) replicate."""
    dl, hl = d // n_shards, hidden // n_shards
    weights = [("ln1_gamma", (d,)), ("w_qkv", (d, 3 * dl)),
               ("w_o", (dl, d)), ("ln2_gamma", (d,)),
               ("w1", (d, hl)), ("w2", (hl, d))]
    bias = [("ln1_beta", (d,)), ("b_qkv", (3 * dl,)), ("b_o", (d,)),
            ("ln2_beta", (d,)), ("b1", (hl,)), ("b2", (d,))]
    return weights, bias


def _pack(pieces, layout):
    return numpy.concatenate(
        [numpy.asarray(pieces[name]).ravel() for name, _ in layout])


def split_block_shards(weights, bias, d, heads, hidden, n_shards):
    """Global packed (weights, bias) -> (n_shards, L_local) stacked
    arrays, head-aligned: shard s owns heads [s*H/n, (s+1)*H/n)."""
    from veles_tpu.models.transformer import split_block_params
    if heads % n_shards or hidden % n_shards:
        raise ValueError("heads %d / hidden %d not divisible by "
                         "model shards %d" % (heads, hidden, n_shards))
    wp, bp = split_block_params(numpy.asarray(weights),
                                numpy.asarray(bias), d, hidden)
    dl, hl = d // n_shards, hidden // n_shards
    layout_w, layout_b = block_param_sizes_local(d, hidden, n_shards)
    w_rows, b_rows = [], []
    wq, wk, wv = (wp["w_qkv"][:, :d], wp["w_qkv"][:, d:2 * d],
                  wp["w_qkv"][:, 2 * d:])
    bq, bk, bv = bp["b_qkv"][:d], bp["b_qkv"][d:2 * d], bp["b_qkv"][2 * d:]
    for s in range(n_shards):
        cols = slice(s * dl, (s + 1) * dl)
        hcols = slice(s * hl, (s + 1) * hl)
        w_rows.append(_pack({
            "ln1_gamma": wp["ln1_gamma"],
            "w_qkv": numpy.concatenate(
                [wq[:, cols], wk[:, cols], wv[:, cols]], axis=1),
            "w_o": wp["w_o"][cols, :],
            "ln2_gamma": wp["ln2_gamma"],
            "w1": wp["w1"][:, hcols],
            "w2": wp["w2"][hcols, :],
        }, layout_w))
        b_rows.append(_pack({
            "ln1_beta": bp["ln1_beta"],
            "b_qkv": numpy.concatenate([bq[cols], bk[cols], bv[cols]]),
            "b_o": bp["b_o"],
            "ln2_beta": bp["ln2_beta"],
            "b1": bp["b1"][hcols],
            "b2": bp["b2"],
        }, layout_b))
    return numpy.stack(w_rows), numpy.stack(b_rows)


def merge_block_shards(w_stacked, b_stacked, d, heads, hidden):
    """Inverse of :func:`split_block_shards`: (n, L_local) stacks back
    to the global packed (weights, bias).  Replicated pieces (LN
    gains/betas, b_o, b2) are taken from shard 0 — the TP step keeps
    them bit-identical across shards by construction."""
    from veles_tpu.models.transformer import block_param_sizes
    n = w_stacked.shape[0]
    dl, hl = d // n, hidden // n
    layout_w, layout_b = block_param_sizes_local(d, hidden, n)
    locals_w = [_unpack(numpy.asarray(w_stacked[s]), layout_w)
                for s in range(n)]
    locals_b = [_unpack(numpy.asarray(b_stacked[s]), layout_b)
                for s in range(n)]
    wq = numpy.concatenate([lw["w_qkv"][:, :dl] for lw in locals_w], 1)
    wk = numpy.concatenate([lw["w_qkv"][:, dl:2 * dl]
                            for lw in locals_w], 1)
    wv = numpy.concatenate([lw["w_qkv"][:, 2 * dl:]
                            for lw in locals_w], 1)
    merged_w = {
        "ln1_gamma": locals_w[0]["ln1_gamma"],
        "w_qkv": numpy.concatenate([wq, wk, wv], axis=1),
        "w_o": numpy.concatenate([lw["w_o"] for lw in locals_w], 0),
        "ln2_gamma": locals_w[0]["ln2_gamma"],
        "w1": numpy.concatenate([lw["w1"] for lw in locals_w], 1),
        "w2": numpy.concatenate([lw["w2"] for lw in locals_w], 0),
    }
    merged_b = {
        "ln1_beta": locals_b[0]["ln1_beta"],
        "b_qkv": numpy.concatenate(
            [numpy.concatenate([lb["b_qkv"][i * dl:(i + 1) * dl]
                                for lb in locals_b])
             for i in range(3)]),
        "b_o": locals_b[0]["b_o"],
        "ln2_beta": locals_b[0]["ln2_beta"],
        "b1": numpy.concatenate([lb["b1"] for lb in locals_b]),
        "b2": locals_b[0]["b2"],
    }
    layout_gw, layout_gb = block_param_sizes(d, hidden)
    return _pack(merged_w, layout_gw), _pack(merged_b, layout_gb)


def _tp_plan(plan):
    return plan.forward_cls is TransformerBlock


def place_tp_state(mesh, plans, state, model_axis="model"):
    """Host state -> TP-placed device state: transformer-block entries
    split per shard and stacked (n, L_local) with the leading dim over
    ``model_axis`` (the pipeline stack_stage_params idiom); everything
    else replicates over the whole mesh."""
    n = mesh.shape[model_axis]
    shard = NamedSharding(mesh, P(model_axis))
    repl = NamedSharding(mesh, P())
    placed = []
    for plan, entry in zip(plans, state):
        if not _tp_plan(plan):
            placed.append({k: (None if v is None
                               else jax.device_put(v, repl))
                           for k, v in entry.items()})
            continue
        heads = plan.static["heads"]
        hidden = plan.static["hidden"]
        d = _packed_d(int(numpy.prod(numpy.shape(entry["weights"]))),
                      hidden)
        out = {}
        for wkey, bkey in (("weights", "bias"),
                           ("accum_weights", "accum_bias"),
                           ("accum2_weights", "accum2_bias")):
            wv, bv = entry.get(wkey), entry.get(bkey)
            if wv is None:
                out[wkey], out[bkey] = None, None
                continue
            ws, bs = split_block_shards(wv, bv, d, heads, hidden, n)
            out[wkey] = jax.device_put(ws, shard)
            out[bkey] = jax.device_put(bs, shard)
        placed.append(out)
    return placed


def gather_tp_state(plans, tp_state):
    """TP-placed state back to global host state (for adoption,
    snapshots, and the parity receipts)."""
    merged = []
    for plan, entry in zip(plans, tp_state):
        if not _tp_plan(plan):
            merged.append({k: (None if v is None else numpy.asarray(v))
                           for k, v in entry.items()})
            continue
        heads = plan.static["heads"]
        hidden = plan.static["hidden"]
        ws = numpy.asarray(entry["weights"])
        n = ws.shape[0]
        d = _packed_d(ws.shape[1], hidden, local=True, n=n)
        out = {}
        for wkey, bkey in (("weights", "bias"),
                           ("accum_weights", "accum_bias"),
                           ("accum2_weights", "accum2_bias")):
            wv, bv = entry.get(wkey), entry.get(bkey)
            if wv is None:
                out[wkey], out[bkey] = None, None
                continue
            gw, gb = merge_block_shards(
                numpy.asarray(wv), numpy.asarray(bv), d, heads, hidden)
            out[wkey], out[bkey] = gw, gb
        merged.append(out)
    return merged


def _packed_d(packed_len, hidden, local=False, n=1):
    """Solve the packed length for the feature dim d.

    Global: L = 2d + 4d^2 + 2*d*hidden.
    Local (per shard): L = 2d + d*(3d/n) + (d/n)*d + d*h/n + (h/n)*d
                         = 2d + 4d^2/n + 2*d*hidden/n.
    """
    for d in range(1, 1 << 16):
        if local:
            if n * (2 * d) + 4 * d * d + 2 * d * hidden == \
                    packed_len * n:
                return d
        elif 2 * d + 4 * d * d + 2 * d * hidden == packed_len:
            return d
    raise ValueError("packed length %d matches no feature dim"
                     % packed_len)


# -- the sharded forward -----------------------------------------------------


def tp_block_apply(w_local, b_local, x, *, heads, hidden, n_shards,
                   axis, eps=1e-5, pallas_bwd=None):
    """One pre-LN block over LOCAL packed params: LN and residuals run
    replicated; QKV/attention/W1 run on this shard's heads/columns via
    the SAME sub-layer cores the single-device block uses
    (``transformer.attention_heads`` / ``position_wise_mlp`` — one
    definition, the shard passes its column/row slices and local head
    count); the two ``leave`` psums merge the output projections and
    the post-psum biases (b_o, b2) add replicated.  The conjugate ops
    make the backward correct (module docstring)."""
    from veles_tpu.models.transformer import (attention_heads,
                                              position_wise_mlp)
    d = x.shape[-1]
    heads_l = heads // n_shards
    layout_w, layout_b = block_param_sizes_local(d, hidden, n_shards)
    wp = _unpack(w_local, layout_w)
    bp = _unpack(b_local, layout_b)
    enter, leave = psum_conjugates(axis)

    ln1 = layer_norm(x, wp["ln1_gamma"], bp["ln1_beta"], eps)
    o = attention_heads(enter(ln1), wp["w_qkv"], bp["b_qkv"], heads_l,
                        pallas_bwd)
    partial = jnp.einsum("btf,fg->btg", o, wp["w_o"],
                         preferred_element_type=jnp.float32)
    attn = leave(partial) + bp["b_o"]
    h = x + attn.astype(x.dtype)

    ln2 = layer_norm(h, wp["ln2_gamma"], bp["ln2_beta"], eps)
    part2 = position_wise_mlp(enter(ln2), wp["w1"], bp["b1"],
                              wp["w2"])
    return (h + (leave(part2) + bp["b2"]).astype(x.dtype)).astype(
        x.dtype)


def build_tp_train_step(plans, loss="softmax", mesh=None,
                        model_axis="model", data_axis=None,
                        grad_bucket_mb=None, grad_compress=None,
                        grad_allreduce_impl="psum", donate=True,
                        compiler_options=None):
    """Compile the tensor-parallel fused train step: shard_map over
    ``mesh`` with transformer-block entries stacked (n, L_local) over
    ``model_axis`` (see :func:`place_tp_state`) and, when ``data_axis``
    is given, the batch sharded over it with the BUCKETED gradient
    all-reduce (parallel/bucketed.py) merging grads across data rows —
    activation psums on the model axis, gradient buckets on the data
    axis, one shard_map program.

    Same fixed-arity contract as ``compiler.build_train_step``:
    fn(state, x, target, batch_size, step_key=None, grad_poison=None,
    loss_poison=None) -> (new_state, metrics), with ``.lower`` exposed
    for step-FLOPs introspection (live MFU attribution)."""
    import math as _math

    from veles_tpu import compiler as _compiler
    from veles_tpu.parallel import bucketed as _bucketed

    if mesh is None:
        raise ValueError("build_tp_train_step needs a mesh")
    n = mesh.shape[model_axis]
    tp_flags = [_tp_plan(p) for p in plans]
    if not any(tp_flags):
        raise ValueError("no transformer-block layers to shard over "
                         "%r" % model_axis)

    grad_sync = metric_sync = row_offset_fn = None
    _local_rows = [0]
    if data_axis is not None:
        bucket_bytes = (
            float("inf") if grad_bucket_mb is None
            or _math.isinf(float(grad_bucket_mb))
            else float(grad_bucket_mb) * 2.0 ** 20)

        def grad_sync(grads):
            return _bucketed.bucketed_all_reduce(
                grads, data_axis, bucket_bytes=bucket_bytes,
                impl=grad_allreduce_impl, compress=grad_compress,
                axis_size=mesh.shape[data_axis])

        def metric_sync(value):
            return lax.psum(value, data_axis)

        def row_offset_fn():
            return lax.axis_index(data_axis) * _local_rows[0]

    tp_indices = {i for i, flag in enumerate(tp_flags) if flag}

    def gsq_fn(grads):
        return sharded_gsq(grads, tp_indices, model_axis)

    def layer_fn(i, plan, p, h, key):
        if not tp_flags[i]:
            return None  # default layer walk
        return tp_block_apply(
            p["weights"][0], p["bias"][0], h,
            heads=plan.static["heads"], hidden=plan.static["hidden"],
            n_shards=n, axis=model_axis,
            eps=plan.static.get("eps", 1e-5))

    def forward_fn(params, x, key, remat):
        return _compiler._forward_for_loss(plans, params, x, key,
                                           remat=remat,
                                           layer_fn=layer_fn)

    raw = _compiler._build_step_fn(
        plans, loss, grad_sync=grad_sync, metric_sync=metric_sync,
        row_offset_fn=row_offset_fn, forward_fn=forward_fn,
        gsq_fn=gsq_fn)

    def local_step(state, x, target, batch_size, step_key,
                   grad_poison, loss_poison):
        _local_rows[0] = x.shape[0]
        if step_key is not None and data_axis is not None:
            # distinct dropout stream per DATA shard; model ranks share
            # the stream (their activations are replicated)
            step_key = jax.random.fold_in(
                step_key, lax.axis_index(data_axis))
        return raw(state, x, target, batch_size, step_key,
                   grad_poison, loss_poison)

    # one PREFIX spec per layer entry: every leaf of a TP entry rides
    # the stacked (n, L_local) layout, so the entry-level prefix covers
    # the dict (and sidesteps None-leaf structure mismatches)
    state_spec = [P(model_axis) if flag else P() for flag in tp_flags]
    batch_spec = P(data_axis) if data_axis is not None else P()
    spmd = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_spec, batch_spec, batch_spec, P(), P(), P(),
                  P()),
        out_specs=(state_spec, P()), check_vma=False)
    return _compiler._finalize_step(
        spmd, donate, compiler_options, mesh=mesh,
        model_axis=model_axis, data_axis=data_axis)
