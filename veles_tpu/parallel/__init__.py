"""Distributed execution over device meshes (ICI/DCN).

This package replaces the reference's entire L5 data plane (ZeroMQ
pickled tensors between master and slaves, SURVEY.md section 2.6) with
the TPU-native model: a ``jax.sharding.Mesh`` over the pod, sharding
annotations on the fused train step, and XLA-inserted collectives riding
ICI.  The master-slave *control* semantics (job bookkeeping, elastic
requeue) stay in veles_tpu.server/client as a host-side concern.

- mesh.py     — mesh discovery/construction (devices -> named axes)
- api.py      — shard/replicate placement helpers + DP/TP sharding
                rules for the fused train step
- bucketed.py — size-targeted gradient buckets all-reduced in backward
                production order (the overlap-credited SPMD data plane)
- ring.py     — ring + Ulysses sequence-parallel attention, plus the
                explicit ppermute ring all-reduce
- pipeline.py — GPipe wavefront pipeline parallelism + the
                stage-split transformer train step
- tensor.py   — Megatron-style tensor-parallel transformer train step
                (head-sharded attention, column/row-split MLP)
- moe.py      — sharded mixture-of-experts
"""

from veles_tpu.parallel.mesh import make_mesh, auto_mesh  # noqa: F401
from veles_tpu.parallel.api import (  # noqa: F401
    replicate, shard_batch, mlp_state_shardings, batch_sharding,
    shard_host_batch)
from veles_tpu.parallel.ring import (  # noqa: F401
    ring_attention, ulysses_attention, ring_all_reduce)
from veles_tpu.parallel.bucketed import (  # noqa: F401
    DEFAULT_BUCKET_MB, BucketPlan, plan_buckets, bucketed_all_reduce,
    flat_all_reduce, comm_receipt, publish_comm_receipt)
