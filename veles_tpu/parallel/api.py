"""Sharding placement helpers for the fused train step.

The recipe (scaling-book style): pick a mesh, annotate state/batch
shardings, jit, let XLA insert the collectives.  The data-parallel
gradient merge that the reference implemented as a ZMQ parameter-server
round-trip (server.py:401-430, workflow.py:531-548) becomes a psum over
ICI that XLA emits from these annotations.
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["replicate", "shard_batch", "batch_sharding",
           "mlp_state_shardings", "shard_host_batch"]


def replicate(mesh, tree):
    """Place every leaf replicated over the whole mesh."""
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.tree.map(
        lambda leaf: jax.device_put(leaf, sharding), tree)


def batch_sharding(mesh, data_axis="data"):
    """Leading-dim (batch) sharding spec."""
    return NamedSharding(mesh, PartitionSpec(data_axis))


def shard_batch(mesh, batch, data_axis="data"):
    return jax.device_put(batch, batch_sharding(mesh, data_axis))


def mlp_state_shardings(mesh, state, data_axis="data", model_axis=None):
    """Sharding pytree for the layer-state list of an MLP.

    DP only: everything replicated.  With ``model_axis`` (tensor
    parallelism): alternate layers shard fan_out / fan_in — Megatron-style
    column-then-row split, so activations between the pair need only one
    all-reduce, which XLA inserts automatically.
    """
    def spec_for(layer_idx, key, leaf):
        if leaf is None or model_axis is None:
            return PartitionSpec()
        column = (layer_idx % 2 == 0)
        if key in ("weights", "accum_weights", "accum2_weights"):
            if getattr(leaf, "ndim", 0) != 2:
                return PartitionSpec()
            return (PartitionSpec(None, model_axis) if column
                    else PartitionSpec(model_axis, None))
        if key in ("bias", "accum_bias", "accum2_bias"):
            return PartitionSpec(model_axis) if column else PartitionSpec()
        return PartitionSpec()

    shardings = []
    for i, entry in enumerate(state):
        shardings.append({
            key: NamedSharding(mesh, spec_for(i, key, leaf))
            for key, leaf in entry.items()})
    return shardings


def shard_host_batch(mesh, local_batch, data_axis="data"):
    """Build a GLOBAL batch-sharded array from each process's local
    minibatch slice (multi-host data loading: every host's Loader
    serves its own index window; this stitches the per-host slices
    into one mesh-spanning array, the multi-host replacement for the
    reference's master→slave minibatch shipping).

    Every process must pass the same local shape — the Loader contract
    guarantees this by zero-padding short final minibatches to
    ``max_minibatch_size`` and shipping the real count in
    ``batch_size`` (which the evaluators mask on).  The global shape is
    derived from the sharding, so mixed meshes (e.g. a model axis whose
    devices span processes) stitch correctly too.

    Single-process meshes fall through to a plain device_put.
    """
    if jax.process_count() == 1:
        return shard_batch(mesh, local_batch, data_axis)
    return jax.make_array_from_process_local_data(
        batch_sharding(mesh, data_axis), local_batch)
