"""Device-mesh construction.

Replaces the reference Launcher's socket handshake + SSH node discovery
(launcher.py:808-906) with JAX topology discovery: ``jax.devices()``
enumerates the slice; multi-host processes call
``jax.distributed.initialize`` (veles_tpu.launcher does this when
VELES_COORDINATOR is set) and get the same global view.
"""

import numpy

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "auto_mesh"]


def make_mesh(axes, devices=None):
    """axes: dict name -> size, e.g. {"data": 4, "model": 2}.

    Sizes must multiply to the device count; -1 once means "the rest".
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    sizes = [axes[n] for n in names]
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(numpy.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devices) % known:
            raise ValueError(
                "cannot infer -1 axis: %d devices over %d" %
                (len(devices), known))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(numpy.prod(sizes))
    if total != len(devices):
        raise ValueError("mesh %s needs %d devices, have %d" %
                         (axes, total, len(devices)))
    grid = numpy.array(devices, dtype=object).reshape(sizes)
    return Mesh(grid, names)


def auto_mesh(data_axis="data", devices=None):
    """All devices on one data-parallel axis — the reference's only
    tensor-level strategy (parameter-server DP, SURVEY.md section 2.6)."""
    devices = list(devices if devices is not None else jax.devices())
    return make_mesh({data_axis: len(devices)}, devices)
