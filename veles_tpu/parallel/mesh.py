"""Device-mesh construction.

Replaces the reference Launcher's socket handshake + SSH node discovery
(launcher.py:808-906) with JAX topology discovery: ``jax.devices()``
enumerates the slice; multi-host processes call
``jax.distributed.initialize`` (veles_tpu.launcher does this when
VELES_COORDINATOR is set) and get the same global view.
"""

import numpy

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "auto_mesh", "shard_map"]

# jax moved shard_map from jax.experimental.shard_map to the top-level
# namespace (and renamed check_rep -> check_vma) across releases;
# resolve whichever this jax ships and normalize the kwarg
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_SHARD_MAP_PARAMS = _inspect.signature(_shard_map).parameters


def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    if check_vma is not None:
        key = ("check_vma" if "check_vma" in _SHARD_MAP_PARAMS
               else "check_rep")
        kwargs[key] = check_vma
    # mesh by KEYWORD: the top-level API makes it keyword-only, and the
    # experimental one accepts it either way
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def make_mesh(axes, devices=None):
    """axes: dict name -> size, e.g. {"data": 4, "model": 2}.

    Sizes must multiply to the device count; -1 once means "the rest".
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    sizes = [axes[n] for n in names]
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(numpy.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devices) % known:
            raise ValueError(
                "cannot infer -1 axis: %d devices over %d" %
                (len(devices), known))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(numpy.prod(sizes))
    if total != len(devices):
        raise ValueError("mesh %s needs %d devices, have %d" %
                         (axes, total, len(devices)))
    grid = numpy.array(devices, dtype=object).reshape(sizes)
    return Mesh(grid, names)


def auto_mesh(data_axis="data", devices=None):
    """All devices on one data-parallel axis — the reference's only
    tensor-level strategy (parameter-server DP, SURVEY.md section 2.6)."""
    devices = list(devices if devices is not None else jax.devices())
    return make_mesh({data_axis: len(devices)}, devices)
