"""Device-mesh construction.

Replaces the reference Launcher's socket handshake + SSH node discovery
(launcher.py:808-906) with JAX topology discovery: ``jax.devices()``
enumerates the slice; multi-host processes call
``jax.distributed.initialize`` (veles_tpu.launcher does this when
VELES_COORDINATOR is set) and get the same global view.
"""

import numpy

import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "auto_mesh", "shard_map", "zero_slot_table",
           "zero_state", "unzero_state", "MeshManager", "mesh_snapshot"]

# jax moved shard_map from jax.experimental.shard_map to the top-level
# namespace (and renamed check_rep -> check_vma) across releases;
# resolve whichever this jax ships and normalize the kwarg
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

_SHARD_MAP_PARAMS = _inspect.signature(_shard_map).parameters


def shard_map(f, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    if check_vma is not None:
        key = ("check_vma" if "check_vma" in _SHARD_MAP_PARAMS
               else "check_rep")
        kwargs[key] = check_vma
    # mesh by KEYWORD: the top-level API makes it keyword-only, and the
    # experimental one accepts it either way
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def make_mesh(axes, devices=None):
    """axes: dict name -> size, e.g. {"data": 4, "model": 2}.

    Sizes must multiply to the device count; -1 once means "the rest".
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes)
    sizes = [axes[n] for n in names]
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(numpy.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devices) % known:
            raise ValueError(
                "cannot infer -1 axis: %d devices over %d" %
                (len(devices), known))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(numpy.prod(sizes))
    if total != len(devices):
        raise ValueError("mesh %s needs %d devices, have %d" %
                         (axes, total, len(devices)))
    grid = numpy.array(devices, dtype=object).reshape(sizes)
    return Mesh(grid, names)


def auto_mesh(data_axis="data", devices=None):
    """All devices on one data-parallel axis — the reference's only
    tensor-level strategy (parameter-server DP, SURVEY.md section 2.6)."""
    devices = list(devices if devices is not None else jax.devices())
    return make_mesh({data_axis: len(devices)}, devices)


# -- ZeRO-1 state layout (docs/distributed.md, "Elastic mesh contract") ---
#
# Optimizer state (the accum leaves) is split into ``n_shards``
# LOGICAL shards per tensor; each device hosts ``ceil(n_shards/N)``
# slots, and an int32 ``zero_slots`` table maps device slots to
# logical shard ids (the id ``n_shards`` marks a padding slot backed
# by an all-zero row).  The table is a runtime input of the compiled
# step, so shard OWNERSHIP (elastic.shard_owners) can change without
# recompiling — the elastic-mesh property the MeshManager builds on.

#: accum leaves — the state entries that live sharded in ZeRO form
ZERO_SHARDED_KEYS = ("accum_weights", "accum_bias", "accum2_weights",
                     "accum2_bias")


def _zero_ref_key(key):
    """The param tensor an accum leaf shadows (its shape source)."""
    return "bias" if key.endswith("bias") else "weights"


def zero_slot_table(n_shards, n_devices, owners=None):
    """Build the int32 ``(n_devices * ceil(n_shards/n_devices),)``
    slot table for an ownership map ``{shard: device_index}`` (default
    round-robin).  Device d's slots are ``[d*k, (d+1)*k)``, filled with
    its owned shard ids ascending and padded with the id ``n_shards``
    (the all-zero row the slot helpers append)."""
    m, n = int(n_shards), int(n_devices)
    k = -(-m // n)
    table = numpy.full((n * k,), m, numpy.int32)
    owned = {d: [] for d in range(n)}
    if owners is None:
        for shard in range(m):
            owned[shard % n].append(shard)
    else:
        for shard, d in owners.items():
            owned[int(d)].append(int(shard))
    for d in range(n):
        shards = sorted(owned[d])
        if len(shards) > k:
            raise ValueError(
                "device %d owns %d shards, capacity %d (n_shards=%d "
                "over %d devices)" % (d, len(shards), k, m, n))
        table[d * k:d * k + len(shards)] = shards
    return table


def zero_state(state, n_devices, n_shards=None, slots=None):
    """Pack a canonical state (full accum arrays) into ZeRO-1 form for
    ``compiler.build_train_step(zero=1)``: accum leaves become
    ``(n_slots, shard_elems)`` slot matrices (host numpy — the step's
    in_specs place them sharded on first dispatch) and every layer
    entry gains the replicated ``zero_slots`` table.  Params stay
    full/replicated.  ``n_shards`` defaults to one shard per device."""
    from veles_tpu.parallel.bucketed import shard_elems

    m = int(n_shards or n_devices)
    if slots is None:
        slots = zero_slot_table(m, n_devices)
    slots = numpy.asarray(slots, numpy.int32)
    out = []
    for entry in state:
        packed = {key: value for key, value in entry.items()}
        packed["zero_slots"] = slots
        for key in ZERO_SHARDED_KEYS:
            arr = entry.get(key)
            if arr is None:
                continue
            arr = numpy.asarray(arr)
            e = shard_elems(arr.size, m)
            flat = numpy.zeros(((m + 1) * e,), arr.dtype)
            flat[:arr.size] = arr.reshape((-1,))
            packed[key] = numpy.ascontiguousarray(
                flat.reshape((m + 1, e))[slots])
        out.append(packed)
    return out


def unzero_state(state, n_shards):
    """Invert :func:`zero_state`: reassemble full canonical accum
    arrays (host numpy) from the slot matrices by each entry's
    ``zero_slots`` table.  The round-trip is exact — rows move, bits
    never change — which is what makes reshard state movement safe."""
    m = int(n_shards)
    out = []
    for entry in state:
        slots = numpy.asarray(entry["zero_slots"])
        # every leaf comes back as HOST numpy — canonical state must
        # not stay committed to the old mesh's devices, or the next
        # mesh's step would refuse the placement
        plain = {key: None if value is None else numpy.asarray(value)
                 for key, value in entry.items()
                 if key != "zero_slots"}
        for key in ZERO_SHARDED_KEYS:
            rows = plain.get(key)
            if rows is None:
                continue
            rows = numpy.asarray(rows)
            ref = numpy.asarray(plain[_zero_ref_key(key)])
            e = rows.shape[-1]
            full = numpy.zeros((m + 1, e), rows.dtype)
            full[slots] = rows
            plain[key] = full[:m].reshape((-1,))[:ref.size].reshape(
                ref.shape)
        out.append(plain)
    return out


#: Mesh keys surfaced to dashboards/heartbeats: registry name -> short
#: name (the elastic-mesh mirror of observe.metrics._HEALTH_KEYS).
_MESH_KEYS = (
    ("mesh.size", "size"),
    ("mesh.epoch", "epoch"),
    ("mesh.reshards", "reshards"),
    ("mesh.bytes_moved", "bytes_moved"),
    ("mesh.coalesced_events", "coalesced_events"),
    ("mesh.compile_hits", "compile_hits"),
    ("mesh.compile_misses", "compile_misses"),
)


def mesh_snapshot(reg=None):
    """The elastic-mesh counters as a flat dict for the web-status
    mesh column and post-mortems: mesh size/epoch, reshard and
    bytes-moved accounting, compile-cache traffic, plus the
    ``mesh.reshard_s`` time-to-recover histogram.  {} on processes
    that never built a MeshManager."""
    from veles_tpu.observe.metrics import registry as _registry
    from veles_tpu.observe.metrics import snapshot_keys
    reg = reg if reg is not None else _registry
    out = snapshot_keys(_MESH_KEYS, reg)
    hist = reg.peek("mesh.reshard_s")
    if hist is not None and getattr(hist, "count", 0):
        out["reshard_s"] = hist.snapshot()
    return out


def _device_key(device):
    """Stable consistent-hash key for a jax device — id-based, so the
    same physical device hashes identically across reshards and
    process restarts (the property HRW ownership stability needs)."""
    return "d%d" % device.id


class MeshManager(object):
    """Elastic ZeRO-1 training mesh (docs/distributed.md, "Elastic
    mesh contract").

    Owns the live train state in ZeRO-1 form over a data-parallel mesh
    and survives membership churn: on a join/leave (``submit_membership``
    — fed by ``elastic.FleetView`` epochs via :meth:`sync_fleet`) the
    manager *quiesces at the step boundary* (events only mark a pending
    membership; :meth:`step` applies the newest one before touching the
    data plane, so back-to-back events coalesce into ONE reshard),
    takes a manifest-verified safety snapshot, recomputes consistent-
    hash shard ownership (:func:`veles_tpu.elastic.shard_owners`),
    moves ONLY the shards whose owner changed (on a single-host mesh
    the movement is a host-side row reassembly; ``bytes_moved``
    accounts the changed-owner rows that would cross the interconnect
    on a pod — the full-gather reference is ``n_shards`` rows), and
    resumes with a step from the digest-keyed compile cache (rejoining
    a previously-seen device set recompiles nothing).

    A crash mid-reshard (chaos point ``mesh.reshard=crash``, fired
    after the safety snapshot, before destructive movement) recovers
    via :meth:`resume` — the ``--resume auto`` semantics over
    ``snapshotter.latest_state_snapshot``.
    """

    def __init__(self, plans, state, loss="softmax", devices=None,
                 n_shards=None, data_axis="data", snapshot_dir=None,
                 donate=True, compiler_options=None, bwd_schedule=None,
                 bwd_remat=False):
        from veles_tpu.observe.metrics import registry as _registry
        self.plans = plans
        self.loss = loss
        self.data_axis = data_axis
        self.snapshot_dir = snapshot_dir
        self.donate = donate
        self.compiler_options = compiler_options
        self.bwd_schedule = bwd_schedule
        self.bwd_remat = bwd_remat
        self._devices = self._order(
            devices if devices is not None else jax.devices())
        if not self._devices:
            raise ValueError("MeshManager needs at least one device")
        #: logical shard count — the movement granularity.  Defaults to
        #: 4x the initial mesh so a single leave moves ~1/N of the
        #: optimizer state in ~4 row-sized pieces, and shrinking below
        #: the initial size never runs out of shards to spread.
        self.n_shards = int(n_shards or 4 * len(self._devices))
        if self.n_shards < len(self._devices):
            raise ValueError(
                "n_shards=%d < %d devices: every device needs at least "
                "one logical shard" % (self.n_shards,
                                       len(self._devices)))
        self.mesh_epoch = 0
        self.applied_steps = 0
        self._pending = None          # (devices, source_epoch) | None
        self._fleet_epoch_seen = None
        self._steps = {}              # digest -> compiled step fn
        self._owners = None
        #: per-reshard receipt rows (movement plan, bytes, timings)
        self.reshard_log = []
        self._reg = _registry
        self._adopt(state)
        self._publish_gauges()

    # -- membership ----------------------------------------------------

    @staticmethod
    def _order(devices):
        return tuple(sorted(devices, key=lambda d: d.id))

    @property
    def devices(self):
        return self._devices

    @property
    def size(self):
        return len(self._devices)

    def submit_membership(self, devices, epoch=None):
        """Queue a membership change (join/leave/swap).  Applied at
        the NEXT step boundary; a newer event before that boundary
        replaces the pending one — back-to-back churn coalesces into a
        single reshard (the counter ``mesh.coalesced_events`` audits
        it)."""
        devices = self._order(devices)
        if not devices:
            raise ValueError("membership event with zero devices")
        if self._pending is not None:
            self._reg.counter("mesh.coalesced_events").inc()
        self._pending = (devices, epoch)

    def sync_fleet(self, fleet, devices_for):
        """Feed membership from an :class:`veles_tpu.elastic.FleetView`:
        when its ``membership_epoch`` moved since the last sync, the
        union of ``devices_for(sid)`` over live members becomes the
        pending device set.  Returns True when an event was queued."""
        epoch = fleet.membership_epoch
        if epoch == self._fleet_epoch_seen:
            return False
        self._fleet_epoch_seen = epoch
        devices = []
        seen = set()
        for sid in fleet.members:
            for dev in devices_for(sid):
                if dev.id not in seen:
                    seen.add(dev.id)
                    devices.append(dev)
        self.submit_membership(devices, epoch=epoch)
        return True

    # -- state layout ---------------------------------------------------

    def _keys(self, devices=None):
        return [_device_key(d) for d in (devices or self._devices)]

    def _adopt(self, state, owners=None):
        """(Re)pack canonical state for the current device set."""
        from veles_tpu.elastic import shard_owners
        keys = self._keys()
        self._owners = shard_owners(self.n_shards, keys,
                                    previous=owners)
        index = {key: i for i, key in enumerate(keys)}
        slots = zero_slot_table(
            self.n_shards, len(keys),
            owners={s: index[m] for s, m in self._owners.items()})
        self._state = zero_state(state, len(keys),
                                 n_shards=self.n_shards, slots=slots)

    def canonical_state(self):
        """The full (unsharded) state as host numpy — snapshot /
        inspection form; the ZeRO round-trip is bit-exact."""
        return unzero_state(self._state, self.n_shards)

    def shard_bytes(self):
        """Bytes of optimizer state per logical shard (all layers, all
        accum leaves) — the unit ``bytes_moved`` accounts in."""
        from veles_tpu.parallel.bucketed import shard_elems
        total = 0
        for entry in self._state:
            for key in ZERO_SHARDED_KEYS:
                rows = entry.get(key)
                if rows is None:
                    continue
                rows = numpy.asarray(rows) if not hasattr(rows, "dtype") \
                    else rows
                total += int(rows.shape[-1]) * rows.dtype.itemsize
        return total

    # -- compile cache --------------------------------------------------

    def _digest(self):
        import hashlib
        meta = [(p.forward_cls.__name__, p.solver, p.include_bias,
                 tuple(sorted(p.hyper_full().items())),
                 tuple(sorted(p.static.items())))
                for p in self.plans]
        blob = repr((self._keys(), self.n_shards, self.loss,
                     self.data_axis, self.bwd_schedule, self.bwd_remat,
                     meta)).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def _get_step(self):
        from veles_tpu import compiler
        digest = self._digest()
        step = self._steps.get(digest)
        if step is not None:
            self._reg.counter("mesh.compile_hits").inc()
            return step
        self._reg.counter("mesh.compile_misses").inc()
        mesh = auto_mesh(self.data_axis, self._devices)
        step = compiler.build_train_step(
            self.plans, loss=self.loss, mesh=mesh,
            data_axis=self.data_axis, zero=1, zero_shards=self.n_shards,
            donate=self.donate, compiler_options=self.compiler_options,
            bwd_schedule=self.bwd_schedule, bwd_remat=self.bwd_remat)
        self._steps[digest] = step
        return step

    # -- reshard --------------------------------------------------------

    def maybe_reshard(self):
        """Apply the newest pending membership event (if any) at this
        step boundary; returns the reshard receipt row or None."""
        if self._pending is None:
            return None
        devices, epoch = self._pending
        self._pending = None
        if devices == self._devices:
            return None  # no-op churn (leave+rejoin of the same set)
        return self._reshard(devices, epoch)

    def _reshard(self, devices, source_epoch):
        import time as _time

        from veles_tpu import chaos
        from veles_tpu.elastic import movement_plan
        from veles_tpu.observe.trace import tracer as _tracer
        t0 = _time.perf_counter()
        canonical = self.canonical_state()
        snapshot_path = self.snapshot(reason="pre_reshard",
                                      state=canonical)
        if chaos.plan is not None:
            fault = chaos.plan.fire("mesh.reshard")
            if fault is not None and fault.action == "crash":
                # after the safety snapshot, before destructive
                # movement — the window a real crash would hit
                raise chaos.ChaosCrash("simulated crash mid-reshard")
        old_owners = self._owners
        old_size = len(self._devices)
        self._devices = devices
        self._adopt(canonical, owners=old_owners)
        plan = movement_plan(old_owners, self._owners)
        per_shard = self.shard_bytes()
        bytes_moved = plan["n_moved"] * per_shard
        self.mesh_epoch += 1
        cached = self._digest() in self._steps
        self._get_step()  # time-to-recover includes the (re)compile
        elapsed = _time.perf_counter() - t0
        event = {
            "mesh_epoch": self.mesh_epoch,
            "source_epoch": source_epoch,
            "step": self.applied_steps,
            "from_size": old_size,
            "to_size": len(self._devices),
            "n_shards": self.n_shards,
            "moved_shards": plan["n_moved"],
            "changed_fraction": plan["changed_fraction"],
            "bytes_moved": bytes_moved,
            "full_gather_bytes": self.n_shards * per_shard,
            "reshard_s": elapsed,
            "compile_cached": cached,
            "snapshot": snapshot_path,
        }
        self.reshard_log.append(event)
        self._reg.counter("mesh.reshards").inc()
        self._reg.counter("mesh.bytes_moved").inc(bytes_moved)
        self._reg.histogram("mesh.reshard_s").observe(elapsed)
        self._publish_gauges()
        if _tracer.active:
            _tracer.instant("mesh.resharded", cat="mesh", **{
                k: event[k] for k in ("mesh_epoch", "from_size",
                                      "to_size", "moved_shards",
                                      "bytes_moved", "reshard_s")})
        return event

    def _publish_gauges(self):
        self._reg.gauge("mesh.size").set(len(self._devices))
        self._reg.gauge("mesh.epoch").set(self.mesh_epoch)

    # -- snapshots ------------------------------------------------------

    def snapshot(self, reason="manual", state=None):
        """Manifest-verified safety snapshot of the canonical state
        (+ progress counters) via the snapshotter atomics; returns the
        path, or None when no ``snapshot_dir`` is configured."""
        if not self.snapshot_dir:
            return None
        import os

        from veles_tpu import snapshotter
        os.makedirs(self.snapshot_dir, exist_ok=True)
        path = os.path.join(
            self.snapshot_dir, "mesh_%s_e%d_s%d.pickle" %
            (reason, self.mesh_epoch, self.applied_steps))
        payload = {
            "state": state if state is not None
            else self.canonical_state(),
            "applied_steps": self.applied_steps,
            "mesh_epoch": self.mesh_epoch,
            "n_shards": self.n_shards,
        }
        snapshotter.write_state_snapshot(
            path, payload, workflow_name="MeshManager",
            epoch=self.mesh_epoch)
        return path

    @classmethod
    def resume(cls, snapshot_dir, plans, **kwargs):
        """Rebuild a manager from the newest verified safety snapshot
        in ``snapshot_dir`` (the ``--resume auto`` path) over whatever
        devices are live now.  State is bit-exact: the snapshot holds
        the canonical form, the repack moves rows, never values."""
        from veles_tpu import snapshotter
        snap = snapshotter.latest_state_snapshot(snapshot_dir)
        if snap is None:
            raise snapshotter.SnapshotError(
                "no verified mesh snapshot under %s" % snapshot_dir)
        payload = snapshotter.load_state_snapshot(snap)
        kwargs.setdefault("n_shards", payload.get("n_shards"))
        manager = cls(plans, payload["state"],
                      snapshot_dir=snapshot_dir, **kwargs)
        manager.applied_steps = int(payload.get("applied_steps", 0))
        manager.mesh_epoch = int(payload.get("mesh_epoch", 0))
        manager._publish_gauges()
        return manager

    # -- stepping -------------------------------------------------------

    def step(self, x, target, batch_size=None, step_key=None,
             grad_poison=None, loss_poison=None):
        """Run one train step on the current mesh, applying any
        pending membership event FIRST (the step-boundary quiesce).
        Returns the step metrics; state advances in place.  The global
        batch's leading dim must divide by the mesh size (the soak
        picks batch sizes divisible by every size in its schedule)."""
        self.maybe_reshard()
        n = len(self._devices)
        if x.shape[0] % n:
            raise ValueError(
                "global batch %d does not divide over %d devices — "
                "pick a batch size divisible by every mesh size the "
                "membership schedule can reach" % (x.shape[0], n))
        if batch_size is None:
            batch_size = numpy.float32(x.shape[0])
        step = self._get_step()
        self._state, metrics = step(self._state, x, target, batch_size,
                                    step_key, grad_poison, loss_poison)
        self.applied_steps += 1
        return metrics
