"""Expert parallelism: a mixture-of-experts layer sharded over an
``expert`` mesh axis.

No reference behavior to match (SURVEY.md section 2.6 item 4); native
capability.  Design: expert parameters carry a leading expert dim
sharded over the axis; the gate (softmax top-k) is computed everywhere;
each device evaluates ITS experts for all tokens and the gate-weighted
combine is a single psum over ICI.  This dense-dispatch formulation is
EXACT (no capacity-factor token dropping) and keeps the collective
pattern trivial; a capacity-based all_to_all dispatch path is the
documented follow-up for sparse regimes.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from veles_tpu.parallel.mesh import shard_map

__all__ = ["moe_apply", "moe_reference", "init_moe_params",
           "shard_moe_params"]


def init_moe_params(rng, n_experts, features, hidden, out_features):
    """Gate + per-expert 2-layer MLP."""
    import numpy
    def u(shape, fan_in):
        return (rng.uniform(-1, 1, shape) /
                numpy.sqrt(fan_in)).astype(numpy.float32)
    return {
        "gate": u((features, n_experts), features),
        "w1": u((n_experts, features, hidden), features),
        "b1": numpy.zeros((n_experts, hidden), numpy.float32),
        "w2": u((n_experts, hidden, out_features), hidden),
        "b2": numpy.zeros((n_experts, out_features), numpy.float32),
    }


def _expert_mlp(w1, b1, w2, b2, x):
    h = jnp.tanh(jnp.dot(x, w1, preferred_element_type=jnp.float32) + b1)
    return jnp.dot(h, w2, preferred_element_type=jnp.float32) + b2


def _gate_weights(params, x, top_k):
    logits = jnp.dot(x, params["gate"],
                     preferred_element_type=jnp.float32)
    n_experts = logits.shape[-1]
    if top_k >= n_experts:
        return jax.nn.softmax(logits, axis=-1)
    top_vals, _ = lax.top_k(logits, top_k)
    threshold = top_vals[..., -1:]
    masked = jnp.where(logits >= threshold, logits, -jnp.inf)
    return jax.nn.softmax(masked, axis=-1)


def moe_reference(params, x, top_k=2):
    """Single-device oracle."""
    gates = _gate_weights(params, x, top_k)  # (B, E)
    outs = jax.vmap(
        lambda w1, b1, w2, b2: _expert_mlp(w1, b1, w2, b2, x)
    )(params["w1"], params["b1"], params["w2"], params["b2"])  # (E,B,F)
    return jnp.einsum("be,ebf->bf", gates, outs).astype(x.dtype)


def shard_moe_params(mesh, params, axis="expert"):
    """Expert-dim leaves shard over the axis; the gate replicates."""
    out = {}
    for key, leaf in params.items():
        spec = P() if key == "gate" else P(axis)
        out[key] = jax.device_put(leaf, NamedSharding(mesh, spec))
    return out


def moe_apply(params, x, mesh, top_k=2, axis="expert",
              data_axis=None):
    """Expert-parallel forward: (B, F) -> (B, out).

    ``data_axis``: optionally shard tokens over a second mesh axis
    (dp x ep) — the gate-weighted combine still psums over the expert
    axis only; no cross-row traffic."""
    n_shards = mesh.shape[axis]

    def sharded(params_local, x_full):
        shard = lax.axis_index(axis)
        n_local = params_local["w1"].shape[0]
        gates = _gate_weights(
            {"gate": params_local["gate"]}, x_full,
            top_k)  # (B, E_total)
        local_out = jax.vmap(
            lambda w1, b1, w2, b2: _expert_mlp(w1, b1, w2, b2, x_full)
        )(params_local["w1"], params_local["b1"], params_local["w2"],
          params_local["b2"])  # (E_local, B, F_out)
        offset = shard * n_local
        local_gates = lax.dynamic_slice_in_dim(
            gates, offset, n_local, axis=1)  # (B, E_local)
        partial = jnp.einsum("be,ebf->bf", local_gates, local_out)
        return lax.psum(partial, axis).astype(x_full.dtype)

    fn = shard_map(
        sharded, mesh=mesh,
        in_specs=({"gate": P(), "w1": P(axis), "b1": P(axis),
                   "w2": P(axis), "b2": P(axis)}, P(data_axis)),
        out_specs=P(data_axis), check_vma=False)
    return fn(params, x)
