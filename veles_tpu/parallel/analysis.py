"""Compiled-program communication analysis.

Used by scripts/scaling.py to report how many bytes of collective
traffic one compiled train step actually issues (the honest input to
the ICI scaling model), and handy for eyeballing sharding regressions.
"""

import re

__all__ = ["parse_collective_bytes", "parse_collective_ops",
           "collective_bytes"]

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "f64": 8, "s64": 8, "u64": 8, "pred": 1, "s8": 1,
                "u8": 1, "s16": 2, "u16": 2, "c64": 8, "c128": 16}

# XLA:TPU rewrites collectives to async -start/-done pairs in optimized
# HLO; counting the -start (plus the sync forms CPU keeps) covers both
_COLLECTIVES = ("all-reduce(", "all-reduce-start(",
                "all-gather(", "all-gather-start(",
                "reduce-scatter(",
                "all-to-all(",
                "collective-permute(", "collective-permute-start(")


def _base(kind):
    return kind.rstrip("(").replace("-start", "")


def parse_collective_ops(hlo_text, kinds=_COLLECTIVES):
    """Per-OP collective inventory of optimized HLO text: a list of
    ``{"kind", "bytes"}`` in program order.  This is how the bucketed
    gradient all-reduce is audited (scripts/scaling.py, the dist smoke
    test): the flat path shows ONE ~250 MB all-reduce, the bucketed
    path one op per bucket — if XLA's combiner ever re-fuses them, the
    op count collapses and the regression is visible here."""
    ops = []
    for line in hlo_text.splitlines():
        if "=" not in line:
            continue
        for kind in kinds:
            if kind not in line:
                continue
            shapes_part = line.split("=", 1)[1].split(kind, 1)[0]
            nbytes = 0
            for dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", shapes_part):
                if dt not in _DTYPE_BYTES:
                    continue
                count = 1
                for d in dims.split(","):
                    if d:
                        count *= int(d)
                nbytes += count * _DTYPE_BYTES[dt]
            ops.append({"kind": _base(kind), "bytes": nbytes})
            break
    return ops


def parse_collective_bytes(hlo_text, kinds=_COLLECTIVES):
    """Sum result bytes of collective ops in optimized HLO text.

    Handles tuple-shaped results ("ar = (f32[96], f32[11,11,3,96], ...)
    all-reduce(...)").  Async -start forms count under their base kind
    ("all-reduce-start" -> "all-reduce").  Returns {kind: bytes} with a
    "total" key.
    """
    out = {_base(kind): 0 for kind in kinds}
    for op in parse_collective_ops(hlo_text, kinds):
        out[op["kind"]] += op["bytes"]
    out["total"] = sum(out.values())
    return out


def collective_bytes(jitted_fn, *example_args):
    """Compile ``jitted_fn`` for the example args and report its
    collective traffic: parse_collective_bytes of the optimized HLO."""
    compiled = jitted_fn.lower(*example_args).compile()
    return parse_collective_bytes(compiled.as_text())
