"""Latency-hiding ring collectives over mesh axes.

The reference (2015-era) has no attention; SURVEY.md section 5 marks
long-context as "no reference behavior to match".  This framework still
ships it as a first-class capability of the parallel layer, TPU-native:

- :func:`ring_attention` — blockwise (flash-style online-softmax)
  attention where K/V shards rotate around the mesh's sequence axis via
  ``lax.ppermute`` over ICI; memory per chip stays O(T_local^2-free):
  each step touches one (T_local x T_local) score block, so sequences
  scale linearly with the ring size.
- :func:`ulysses_attention` — the all-to-all alternative: resharding
  (seq-sharded -> head-sharded) with ``lax.all_to_all``, full local
  attention per head group, and the inverse all-to-all back.
- :func:`ring_all_reduce` — the same ppermute ring pattern applied to
  gradient summation: chunked reduce-scatter + all-gather, the
  explicit spelling of the bandwidth-optimal 2(n-1)/n ring bound that
  parallel/bucketed.py's per-bucket schedule models.  ``psum`` remains
  the default impl (XLA lowers it to the platform's tuned collective);
  the explicit ring is for meshes/toolchains where the hand-pipelined
  chunk rotation wins, and as the executable form of the scaling
  model's assumptions.

The attention variants support causal masking with globally-correct
positions and are exact (tested against a single-device oracle on the
virtual mesh).
"""

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from veles_tpu.parallel.mesh import shard_map

__all__ = ["ring_attention", "ulysses_attention", "attention_reference",
           "ring_all_reduce"]


def ring_all_reduce(x, axis_name, n_shards):
    """Sum a 1-D vector over ``axis_name`` with an explicit ring:
    chunked reduce-scatter then all-gather via ``lax.ppermute``.

    Each of the 2(n-1) steps moves one 1/n chunk to the next neighbor,
    so per-step wire time is 1/n of the payload — the pipelining that
    makes the ring bandwidth-optimal and lets a scheduler overlap the
    early hops with unrelated compute.  ``n_shards`` is the static
    axis size (callers inside shard_map know it from the mesh).

    Summation ORDER differs from ``lax.psum`` (partial sums travel the
    ring), so results are ULP-close but not bit-equal to psum; the
    bit-equality guarantees in parallel/bucketed.py hold within one
    impl, not across impls.
    """
    if n_shards == 1:
        return x
    length = x.shape[0]
    pad = (-length) % n_shards
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
    chunks = x.reshape(n_shards, -1)
    me = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
    # reduce-scatter: after n-1 rotations shard i owns the fully
    # reduced chunk (i+1) % n
    for step in range(n_shards - 1):
        send = (me - step) % n_shards
        recv = (me - step - 1) % n_shards
        block = lax.ppermute(
            jnp.take(chunks, send, axis=0), axis_name, perm)
        chunks = chunks.at[recv].add(block)
    # all-gather: rotate the reduced chunks around the ring
    for step in range(n_shards - 1):
        send = (me + 1 - step) % n_shards
        block = lax.ppermute(
            jnp.take(chunks, send, axis=0), axis_name, perm)
        chunks = chunks.at[(me - step) % n_shards].set(block)
    out = chunks.reshape(-1)
    return out[:length] if pad else out


def attention_reference(q, k, v, causal=False):
    """Single-device oracle: q,k,v (B, T, H, D) -> (B, T, H, D)."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _ring_body(my_index, n_shards, t_local, axis_name, causal, scale,
               q, k, v):
    """Per-shard ring loop; q,k,v are the LOCAL shards (B, Tl, H, D)."""
    batch, _, heads, depth = q.shape
    q_pos = my_index * t_local + jnp.arange(t_local)

    m = jnp.full((batch, heads, t_local), -jnp.inf, jnp.float32)
    l = jnp.zeros((batch, heads, t_local), jnp.float32)
    o = jnp.zeros((batch, heads, t_local, depth), jnp.float32)

    def body(carry, i):
        k_blk, v_blk, m, l, o = carry
        src = (my_index - i) % n_shards  # origin rank of current block
        k_pos = src * t_local + jnp.arange(t_local)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_blk,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        m = m_new
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, m, l, o), None

    # scan, not fori_loop: same trip count, but scan is
    # reverse-differentiable (ppermute transposes to the opposite
    # rotation), so the ring composes into TRAINING steps — long-context
    # models backprop through it (fori_loop would fail at jax.grad)
    (_, _, m, l, o), _ = lax.scan(
        body, (k, v, m, l, o), jnp.arange(n_shards))
    out = o / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ring_attention(q, k, v, mesh, seq_axis="seq", causal=False,
                   data_axis=None, head_axis=None):
    """q,k,v (B, T, H, D), T sharded over ``seq_axis``.

    ``data_axis``: optionally shard the batch dim over a second mesh
    axis (dp x sp on a pod-shaped mesh) — the ring rides the seq axis
    within each data-parallel row, no cross-row traffic.
    ``head_axis``: optionally shard the HEAD dim over a third mesh
    axis (dp x sp x tp): attention is embarrassingly parallel over
    heads, so a tensor-parallel axis composes with the ring at zero
    extra communication."""
    # math.sqrt, not jnp: the depth is a static shape, and the
    # function must stay traceable inside an outer jit (train steps)
    scale = 1.0 / math.sqrt(q.shape[-1])
    n_shards = mesh.shape[seq_axis]
    t_local = q.shape[1] // n_shards

    def sharded(q_s, k_s, v_s):
        my = lax.axis_index(seq_axis)
        return _ring_body(my, n_shards, t_local, seq_axis, causal,
                          scale, q_s, k_s, v_s)

    spec = P(data_axis, seq_axis, head_axis)
    fn = shard_map(
        sharded, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh, seq_axis="seq", causal=False,
                      data_axis=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style):
    reshard (T/n, H) -> (T, H/n), run full local attention on the head
    group, reshard back.  Requires heads %% n_shards == 0.
    ``data_axis`` additionally shards the batch dim (dp x sp)."""
    n_shards = mesh.shape[seq_axis]
    if q.shape[2] % n_shards:
        raise ValueError("heads %d not divisible by mesh axis %d" %
                         (q.shape[2], n_shards))

    def sharded(q_s, k_s, v_s):
        # local: (B, T/n, H, D) -> all_to_all -> (B, T, H/n, D)
        def spread(x):
            return lax.all_to_all(x, seq_axis, split_axis=2,
                                  concat_axis=1, tiled=True)

        def gather_back(x):
            return lax.all_to_all(x, seq_axis, split_axis=1,
                                  concat_axis=2, tiled=True)

        qh, kh, vh = spread(q_s), spread(k_s), spread(v_s)
        out = attention_reference(qh, kh, vh, causal=causal)
        return gather_back(out)

    spec = P(data_axis, seq_axis)
    fn = shard_map(
        sharded, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)
    return fn(q, k, v)
