"""Unit — the node of the dataflow/control-flow graph.

TPU-native counterpart of reference veles/units.py:59,108.  Preserved
semantics: control links (``link_from``), the AND gate protocol with
``gate_block`` / ``gate_skip`` / ``ignores_gate``, data links
(``link_attrs`` via LinkableAttribute), required-attribute declaration
(``demand``), timed + stop-checked ``run`` wrapping, and a registry of all
unit classes for introspection.

Scheduling difference (TPU-first): successors are scheduled through the
owning workflow's scheduler (worklist + thread pool), not by recursive
calls, so arbitrarily long training loops cannot blow the stack; and
accelerated subgraphs can be fused by veles_tpu.compiler into single XLA
computations while keeping this graph as the orchestration layer.
"""

import threading
import time
import uuid as uuid_module

from veles_tpu.config import root
from veles_tpu.distributable import Distributable
from veles_tpu.mutable import Bool, LinkableAttribute
from veles_tpu.observe.trace import tracer as _tracer

__all__ = ["Unit", "IUnit", "UnitRegistry", "RunAfterStopError",
           "nothing"]



class RunAfterStopError(RuntimeError):
    """A unit was scheduled to run after its workflow FINISHED without
    any stop request — a broken control-flow link (reference
    units.py:823-839 raised the same on post-stop runs)."""


class UnitRegistry(type):
    """Metaclass recording every Unit subclass (reference:
    veles/unit_registry.py:51)."""

    units = set()
    by_name = {}

    def __init__(cls, name, bases, namespace):
        super(UnitRegistry, cls).__init__(name, bases, namespace)
        # Classes that opt out (infrastructure like Workflow/StartPoint)
        # set hide_from_registry = True in their own namespace.
        if not namespace.get("hide_from_registry", False):
            UnitRegistry.units.add(cls)
            UnitRegistry.by_name[name] = cls
        # Merge KWATTRS / demanded hints up the MRO for introspection.
        kwattrs = set(namespace.get("KWATTRS", set()))
        for base in bases:
            kwattrs |= getattr(base, "KWATTRS", set())
        cls.KWATTRS = kwattrs
        # Units contributing CLI flags join the argparse registry (the
        # reference combined both metaclasses, cmdline.py:61-84)
        if "init_parser" in namespace or "apply_args" in namespace:
            from veles_tpu.cmdline import CommandLineArgumentsRegistry
            CommandLineArgumentsRegistry.classes.append(cls)


def nothing(*args, **kwargs):
    return None


class IUnit(object):
    """Interface contract: units must define initialize() and run()."""

    def initialize(self, **kwargs):
        """Allocate state; may be re-queued if demands are unsatisfied."""

    def run(self):
        """Do one step of work."""


class Unit(Distributable, metaclass=UnitRegistry):
    """A graph node with control gates and linked data attributes."""

    #: subclasses may set a stable UUID for the package-export factory
    #: (libVeles-parity; see veles_tpu/package.py)
    UNIT_UUID = None

    hide_from_registry = False

    def __init__(self, workflow, **kwargs):
        self.name = kwargs.pop("name", None)
        self.view_group = kwargs.pop("view_group", None)
        self.timings = kwargs.pop(
            "timings", root.common.get("timings", False))
        super(Unit, self).__init__(**kwargs)
        self._links_from = {}
        self._links_to = {}
        self._gate_block = Bool(False)
        self._gate_skip = Bool(False)
        self._ignores_gate = Bool(False)
        self._initialized = Bool(False)
        self._stopped = Bool(False)
        #: a re-run may clear this unit's stop flag; units whose stop()
        #: permanently tears down resources (sockets, server threads)
        #: set this False so a rerun leaves them suppressed instead of
        #: hanging on a dead resource
        self.restartable = True
        self._ran = False
        self._demanded = set()
        self.timers = {"run": 0.0}
        self.run_calls = 0
        self.id = str(uuid_module.uuid4())
        self._workflow = None
        self.workflow = workflow
        self.init_unpickled()

    def init_unpickled(self):
        super(Unit, self).init_unpickled()
        self._gate_lock_ = threading.RLock()
        self._run_lock_ = threading.RLock()
        self._is_initialized_ = False
        # data aliases need their class-level descriptors back when the
        # snapshot lands in a process that never built this graph
        from veles_tpu.mutable import LinkableAttribute
        LinkableAttribute.reinstall(self)

    def __repr__(self):
        return "<%s \"%s\">" % (type(self).__name__, self.name or
                                hex(id(self)))

    # -- naming / ownership ------------------------------------------------

    @property
    def name(self):
        if self._name is not None:
            return self._name
        return type(self).__name__

    @name.setter
    def name(self, value):
        self._name = value

    @property
    def workflow(self):
        return self._workflow

    @workflow.setter
    def workflow(self, value):
        if self._workflow is not None:
            self._workflow.del_ref(self)
        self._workflow = value
        if value is not None:
            value.add_ref(self)

    def detach(self):
        self.workflow = None

    @property
    def is_standalone(self):
        return self.workflow.workflow_mode == "standalone"

    @property
    def is_master(self):
        return self.workflow.workflow_mode == "master"

    @property
    def is_slave(self):
        return self.workflow.workflow_mode == "slave"

    @property
    def launcher(self):
        return self.workflow.launcher

    # -- gates & links -----------------------------------------------------

    @property
    def gate_block(self):
        return self._gate_block

    @gate_block.setter
    def gate_block(self, value):
        self._gate_block = value if isinstance(value, Bool) else Bool(value)

    @property
    def gate_skip(self):
        return self._gate_skip

    @gate_skip.setter
    def gate_skip(self, value):
        self._gate_skip = value if isinstance(value, Bool) else Bool(value)

    @property
    def ignores_gate(self):
        return self._ignores_gate

    @ignores_gate.setter
    def ignores_gate(self, value):
        self._ignores_gate = value if isinstance(value, Bool) else Bool(value)

    @property
    def links_from(self):
        return self._links_from

    @property
    def links_to(self):
        return self._links_to

    def link_from(self, *units):
        """Add control dependencies: self runs after each of ``units``."""
        with self._gate_lock_:
            for unit in units:
                self._links_from[unit] = False
                unit._links_to[self] = False
        return self

    def unlink_from(self, *units):
        with self._gate_lock_:
            for unit in units:
                self._links_from.pop(unit, None)
                unit._links_to.pop(self, None)

    def unlink_all(self):
        with self._gate_lock_:
            for unit in list(self._links_from):
                self.unlink_from(unit)
            for unit in list(self._links_to):
                unit.unlink_from(self)

    def open_gate(self, src):
        """Mark ``src`` done; True when ALL incoming links have fired
        (reference: units.py:524-543).  Resets flags on opening."""
        with self._gate_lock_:
            if bool(self._ignores_gate):
                return True
            if src in self._links_from:
                self._links_from[src] = True
            if all(self._links_from.values()):
                for key in self._links_from:
                    self._links_from[key] = False
                return True
            return False

    # -- data links --------------------------------------------------------

    def link_attrs(self, other, *names, two_way=False):
        """Alias attributes from ``other``.  Each name is either a string
        (same name both sides) or a tuple ``(mine, theirs)``."""
        for name in names:
            if isinstance(name, tuple):
                mine, theirs = name
            else:
                mine = theirs = name
            LinkableAttribute(self, mine, other, theirs, two_way=two_way)
        return self

    def demand(self, *names):
        """Declare attributes that must be set before initialize()."""
        self._demanded.update(names)

    def verify_demands(self):
        missing = []
        for name in self._demanded:
            try:
                if getattr(self, name) is None:
                    missing.append(name)
            except AttributeError:
                missing.append(name)
        return missing

    # -- lifecycle ---------------------------------------------------------

    @property
    def is_initialized(self):
        return self._is_initialized_

    def initialize(self, **kwargs):
        """Base initialize verifies demands.  Subclasses extend."""
        missing = self.verify_demands()
        if missing:
            raise AttributeError(
                "%s lacks demanded attributes: %s" % (self, missing))
        self._is_initialized_ = True
        return True

    @property
    def stopped(self):
        return bool(self._stopped)

    def stop(self):
        self._stopped <<= True

    def run(self):  # pragma: no cover - abstract
        pass

    # -- execution wrapping ------------------------------------------------

    def _timed_run(self):
        if not self._is_initialized_:
            raise RuntimeError("%s.run() before initialize()" % self)
        if self.stopped or (self.workflow is not None and
                            self.workflow.stopped):
            wf = self.workflow
            if (wf is not None and
                    getattr(wf, "finished", False) and
                    not getattr(wf, "stop_requested", True)):
                raise RunAfterStopError(
                    "%s scheduled to run after the workflow finished "
                    "— check its control links" % self)
            return False
        start = time.perf_counter()
        self.run()
        elapsed = time.perf_counter() - start
        self.timers["run"] += elapsed
        self.run_calls += 1
        if _tracer.active:
            # the trace span and the accumulated timer are the SAME
            # measurement — print_stats and Perfetto cannot disagree.
            # .active (tracing on OR flight ring on) so the black-box
            # recorder sees unit spans in ordinary untraced runs too
            _tracer.complete(self.name, start, elapsed, cat="unit")
        self._ran = True
        if self.timings:
            self.debug("%s ran in %.3f ms", self.name, elapsed * 1e3)
        return True

    def _check_gate_and_run(self, src):
        """Gate test + run + propagate (reference: units.py:782)."""
        if not self.open_gate(src):
            return
        if bool(self._gate_block):
            return
        with self._run_lock_:
            if bool(self._gate_skip):
                self.run_dependent()
                return
            if self._timed_run() is False:
                return
        self.run_dependent()

    def run_dependent(self):
        """Schedule every successor through the workflow scheduler."""
        wf = self.workflow
        if wf is None:
            for dst in list(self._links_to):
                dst._check_gate_and_run(self)
            return
        for dst in list(self._links_to):
            wf.schedule(dst, self)

    @property
    def dependent_units(self):
        """Transitive closure of links_to, including self."""
        result = []
        seen = set()
        stack = [self]
        while stack:
            unit = stack.pop()
            if id(unit) in seen:
                continue
            seen.add(id(unit))
            result.append(unit)
            stack.extend(unit._links_to)
        return result

    # -- pickling ----------------------------------------------------------

    def __getstate__(self):
        state = super(Unit, self).__getstate__()
        if self.stripped_pickle:
            state["_links_from"] = {}
            state["_links_to"] = {}
            state["_workflow"] = None
        return state
