"""Workflow compiler — fuse the training loop body into ONE jitted step.

The reference executed each iteration as a chain of per-unit kernel
launches with host scheduling in between (SURVEY.md section 3.2).  The
TPU-idiomatic replacement: trace the forward units' pure ``apply``
functions, differentiate the loss with ``jax.grad``, and apply the
per-layer solver updates — all inside a single XLA computation, so one
training iteration is one device dispatch with zero host round-trips.

The unit graph stays as orchestration (loader/decision/services); a
:class:`veles_tpu.models.fused.FusedTrainer` unit swaps itself in for the
forward+evaluator+GD chain.  Parity between the fused step and the
per-unit path is covered by tests/test_compiler.py.

Sharding: pass ``mesh`` + ``state_shardings``/``batch_sharding`` and the
step is jitted with those shardings; XLA inserts the ICI collectives
(psum for the data-parallel gradient merge) automatically — the
scaling-book recipe replacing the reference's ZMQ parameter-server data
plane.
"""

import functools

import numpy

from veles_tpu.models.nn_units import GradientDescentBase

__all__ = ["LayerPlan", "build_train_step", "build_train_epoch",
           "build_eval_epoch", "build_forward", "workflow_plan",
           "extract_state", "adopt_state"]


class LayerPlan(object):
    """Static per-layer compile info: forward class, solver, hyper."""

    def __init__(self, forward_cls, solver="momentum", hyper=None,
                 include_bias=True, static=None):
        self.forward_cls = forward_cls
        self.solver = solver
        self.hyper = hyper or {}
        self.include_bias = include_bias
        self.static = static or {}

    def hyper_full(self):
        base = {
            "learning_rate": 0.01, "learning_rate_bias": None,
            "weights_decay": 0.0, "weights_decay_bias": 0.0,
            "l1_vs_l2": 0.0, "gradient_moment": 0.0,
            "gradient_moment_bias": None, "adadelta_rho": 0.95,
            "solver_epsilon": 1e-6,
        }
        base.update(self.hyper)
        if base["learning_rate_bias"] is None:
            base["learning_rate_bias"] = base["learning_rate"]
        if base["gradient_moment_bias"] is None:
            base["gradient_moment_bias"] = base["gradient_moment"]
        return base


def workflow_plan(sw):
    """Extract LayerPlans from a StandardWorkflow."""
    plans = []
    for fwd, gd in zip(sw.forwards, sw.gds):
        plans.append(LayerPlan(
            type(fwd), solver=gd.solver, hyper=gd.hyper_dict(),
            include_bias=fwd.include_bias, static=fwd.static_config()))
    return plans


def extract_state(sw):
    """Pull per-layer param+solver-state pytree out of workflow Arrays."""
    state = []
    for fwd, gd in zip(sw.forwards, sw.gds):
        entry = {}
        for key, arr in (("weights", fwd.weights), ("bias", fwd.bias),
                         ("accum_weights", gd.accum_weights),
                         ("accum_bias", gd.accum_bias),
                         ("accum2_weights", gd.accum2_weights),
                         ("accum2_bias", gd.accum2_bias)):
            entry[key] = arr.devmem if arr else None
        state.append(entry)
    return state


def adopt_state(sw, new_state, device=None):
    """Stage a fused-step result back into the workflow's Arrays.

    The fused step donates its input state buffers (donate_argnums),
    so the Arrays must not keep references to ``new_state``'s leaves —
    the next step would delete them under the Arrays' feet.  Values
    are copied to host with overlapped async transfers and the device
    side detached; host is authoritative afterwards."""
    adopted = []
    for (fwd, gd), entry in zip(zip(sw.forwards, sw.gds), new_state):
        for key, arr in (("weights", fwd.weights), ("bias", fwd.bias),
                         ("accum_weights", gd.accum_weights),
                         ("accum_bias", gd.accum_bias),
                         ("accum2_weights", gd.accum2_weights),
                         ("accum2_bias", gd.accum2_bias)):
            if entry.get(key) is not None and arr:
                arr.set_device_array(entry[key], device or fwd.device)
                adopted.append(arr)
    for arr in adopted:
        arr.prefetch_host()   # start all transfers...
    for arr in adopted:
        arr.detach_device()   # ...then collect, dropping references


def _forward_for_loss(plans, params, x, key=None, remat=False,
                      layer_fn=None, fold_offset=0):
    """Forward pass; returns (pre-softmax logits | final output).

    ``key``: dropout rng; None (inference / keyless step) makes dropout
    layers identity (inverted dropout needs no eval-time rescale).

    ``remat=True`` wraps each layer's apply in ``jax.checkpoint``: the
    backward recomputes the layer forward instead of holding its
    activations live across the whole gradient graph — part of the
    backward-decongestion set (docs/kernels.md).  Recomputation replays
    identical ops, so gradients stay bit-identical; it trades MXU time
    for activation HBM pressure and is off by default.

    ``layer_fn(i, plan, p, h, key)``: optional per-layer override hook
    (the model-parallel builders swap a sharded apply in for specific
    layers); returning None falls through to the stock walk.
    ``fold_offset`` shifts the dropout key-fold index — a caller
    walking a SLICE of a larger model (the pipeline step's tail) must
    key dropout on the global layer index to match the fused step.
    """
    from veles_tpu.models.all2all import All2All, All2AllSoftmax
    from veles_tpu.models.dropout import DropoutForward
    import jax

    def layer(fn):
        return jax.checkpoint(fn) if remat else fn

    h = x
    for i, (plan, p) in enumerate(zip(plans, params)):
        if layer_fn is not None:
            override = layer_fn(i, plan, p, h, key)
            if override is not None:
                h = override
                continue
        if plan.forward_cls is All2AllSoftmax:
            # keep logits for a numerically-stable CE
            h = layer(All2All.apply)(p, h)
        elif issubclass(plan.forward_cls, DropoutForward):
            if key is not None:
                mask = DropoutForward.make_mask(
                    jax.random.fold_in(key, i + fold_offset), h.shape,
                    plan.static.get("dropout_ratio", 0.5), h.dtype)
                h = h * mask
        else:
            h = layer(functools.partial(
                plan.forward_cls.apply, **plan.static))(p, h)
    return h


def _chain_grad_barriers(grads):
    """Backward-decongestion scheduling hint (docs/kernels.md): thread
    the per-layer gradient dicts through ``lax.optimization_barrier``
    in backward PRODUCTION order (last layer first — its grads exist
    first), so XLA cannot hoist every layer's wgrad to the end of the
    schedule and pile them onto the MXU at once.  The barrier is an
    identity — results are bit-identical with or without the chain
    (tests/test_pallas_bwd.py proves it); only the schedule changes.
    Mirrors parallel/bucketed.py's collective chaining."""
    import jax
    from jax import lax

    barrier = getattr(lax, "optimization_barrier", None)
    if barrier is None:  # jax API drift: hint only, never required
        return grads
    out = list(grads)
    token = None
    for idx in range(len(out) - 1, -1, -1):
        leaves, treedef = jax.tree_util.tree_flatten(out[idx])
        if not leaves:
            continue
        if token is None:
            chained = barrier(tuple(leaves))
        else:
            chained = barrier(tuple(leaves) + (token,))[:-1]
        token = chained[0]
        out[idx] = jax.tree_util.tree_unflatten(treedef, list(chained))
    return out


def build_forward(plans):
    """Pure inference fn(params_list, x) -> output (probs for softmax)."""
    def forward(params, x):
        import jax
        from veles_tpu.models.all2all import All2AllSoftmax
        h = _forward_for_loss(plans, params, x)
        if plans and plans[-1].forward_cls is All2AllSoftmax:
            h = jax.nn.softmax(h, axis=-1)
        return h
    return forward


def _build_step_fn(plans, loss, grad_sync=None, metric_sync=None,
                   row_offset_fn=None, bwd_schedule=None,
                   bwd_remat=False, forward_fn=None, gsq_fn=None,
                   zero_update=None):
    """The raw (unjitted) train-step function shared by
    build_train_step (which jits one minibatch per dispatch) and
    build_train_epoch (which lax.scans it — one dispatch per epoch).

    SPMD hooks (used by the shard_map data plane, None elsewhere):
    ``grad_sync(grads)`` runs right after the backward — the bucketed
    cross-device all-reduce slots in here, BEFORE the numerics guard,
    so a poisoned gradient on ANY shard makes every replica skip the
    same step bit-exactly.  ``metric_sync(scalar)`` globalizes the
    loss/aux scalars (psum over the data axis).  ``row_offset_fn()``
    returns this shard's global row offset so the mse tail mask keys
    on GLOBAL row indices (a short minibatch's padded rows live in the
    last shard).

    Backward decongestion (docs/kernels.md): ``bwd_schedule`` (None ->
    follow the VELES_PALLAS_BWD knob) threads the per-layer gradients
    through an optimization_barrier chain in backward production order
    — a pure scheduling hint, bit-identical results; ``bwd_remat``
    checkpoints each layer's forward to cut activation pressure.

    Model-parallel hooks (parallel/tensor.py, parallel/pipeline.py):
    ``forward_fn(params, x, key, remat)`` replaces the stock layer walk
    (a tensor-parallel forward slices local shards and psums; a
    pipeline forward runs the stage wavefront) and ``gsq_fn(grads)``
    replaces the flat squared-sum for the numerics guard (sharded
    leaves need a model-axis psum so every shard sees the SAME global
    norm and a poisoned step skips uniformly).

    ZeRO hook (:func:`_build_zero1_spmd_train_step`):
    ``zero_update(state, grads)`` replaces the grad_sync + squared-sum
    + update loop as one unit — the gradient merge (reduce-scatter),
    the sharded solver update, and the param all-gather are coupled,
    and the global grad-norm falls out of the owned shards.  Returns
    ``(new_state, gsq)``; the finiteness guard and the skip-select
    still run here so the skip contract has exactly one definition."""
    import jax
    import jax.numpy as jnp

    if bwd_schedule is None:
        from veles_tpu.ops.common import pallas_bwd_enabled
        bwd_schedule = pallas_bwd_enabled()

    hypers = [p.hyper_full() for p in plans]

    def loss_fn(params, x, target, batch_size, key):
        if forward_fn is not None:
            out = forward_fn(params, x, key, bwd_remat)
        else:
            out = _forward_for_loss(plans, params, x, key,
                                    remat=bwd_remat)
        if loss == "softmax":
            labels = target
            valid = labels >= 0
            safe = jnp.where(valid, labels, 0)
            logp = jax.nn.log_softmax(out)
            picked = logp[jnp.arange(out.shape[0]), safe]
            total = -jnp.sum(picked * valid)
            pred = jnp.argmax(out, axis=-1)
            n_err = jnp.sum((pred != safe) & valid)
            return total / batch_size, n_err
        # mse
        out2 = out.reshape(out.shape[0], -1)
        t2 = target.reshape(target.shape[0], -1)
        rows = jnp.arange(out2.shape[0])
        if row_offset_fn is not None:
            rows = rows + row_offset_fn()
        mask = (rows < batch_size).astype(out2.dtype)[:, None]
        diff = (out2 - t2) * mask
        # aux: per-sample mean over features, summed over samples — the
        # same definition EvaluatorMSE uses, so train and eval epoch
        # RMSE (DecisionMSE) accumulate commensurate terms
        mse_sum = jnp.sum(jnp.sum(diff * diff, axis=1) / out2.shape[1])
        return jnp.sum(diff * diff) / batch_size, mse_sum

    def step(state, x, target, batch_size, step_key=None,
             grad_poison=None, loss_poison=None):
        params = [{"weights": s["weights"], "bias": s["bias"]}
                  for s in state]
        (loss_value, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, x, target, batch_size,
                                   step_key)
        # chaos nan-injection (docs/health.md): the poisons are traced
        # scalars, so the injection happens INSIDE the compiled step —
        # exactly where a real numeric fault would appear — and the
        # non-poisoned trace carries zero overhead (poison args are
        # None at trace time on the healthy path)
        if grad_poison is not None:
            grads = jax.tree_util.tree_map(
                lambda g: g + grad_poison.astype(g.dtype), grads)
        if loss_poison is not None:
            loss_value = loss_value + loss_poison
        if bwd_schedule:
            # scheduling hint only — identity on values (see
            # _chain_grad_barriers); sits before the all-reduce so the
            # buckets also issue in production order
            grads = _chain_grad_barriers(grads)
        if grad_sync is not None:
            # SPMD data plane: bucketed all-reduce of the LOCAL grads.
            # Poisons inject before the sync so a chaos fault on one
            # shard spreads (like a real bad chip) and the finiteness
            # guard below skips the step uniformly on every replica.
            grads = grad_sync(grads)
        if metric_sync is not None:
            loss_value = metric_sync(loss_value)
            aux = metric_sync(aux)

        # numerics guard: one all-isfinite reduction over the loss and
        # the global grad-norm.  A single inf/nan anywhere in the
        # gradients makes the squared-sum non-finite, so isfinite of
        # the norm covers every leaf; both flags stay LAZY device
        # scalars riding the existing metrics result — no host sync
        if zero_update is not None:
            # ZeRO-1: reduce-scatter + sharded update + all-gather in
            # one coupled unit; the grad-norm's squared-sum comes back
            # from the owned shards (psum over the data axis, so the
            # skip verdict below is uniform across ranks).  The
            # poisons above inject BEFORE the reduce-scatter, so a
            # fault on one shard still spreads like a real bad chip.
            new_state, gsq = zero_update(state, grads)
        elif gsq_fn is not None:
            gsq = gsq_fn(grads)
        else:
            gsq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                      for g in jax.tree_util.tree_leaves(grads))
        grad_norm = jnp.sqrt(gsq)
        step_finite = jnp.isfinite(loss_value) & jnp.isfinite(grad_norm)

        new_state = new_state if zero_update is not None else \
            _apply_solver(plans, hypers, state, grads)
        # a non-finite update is SKIPPED, not applied: every state leaf
        # falls back to its pre-step value, so one poisoned minibatch
        # leaves params (and solver accumulators) bit-identical to
        # never having served it (tests/test_health.py proves equality)
        new_state = [GradientDescentBase.select_state(step_finite,
                                                      entry, old)
                     for entry, old in zip(new_state, state)]
        if loss == "softmax":
            metrics = {"loss": loss_value, "n_err": aux}
        else:
            metrics = {"loss": loss_value,
                       "n_err": jnp.zeros((), jnp.int32),
                       "mse_sum": aux}
        metrics["grad_norm"] = grad_norm
        metrics["finite"] = step_finite
        metrics["skipped"] = (~step_finite).astype(jnp.int32)
        return new_state, metrics

    def _apply_solver(plans, hypers, state, grads):
        new_state = []
        for plan, hyper, s, g in zip(plans, hypers, state, grads):
            if s["weights"] is None:  # param-less layer (pooling, ...)
                new_state.append(dict(s))
                continue
            W = s["weights"]
            gw = GradientDescentBase.regularized(
                g["weights"].astype(W.dtype), W,
                hyper["weights_decay"], hyper["l1_vs_l2"])
            new_w, acc_w, acc2_w = GradientDescentBase.solver_update(
                plan.solver, W, gw, s["accum_weights"],
                s["accum2_weights"], hyper["learning_rate"],
                hyper["gradient_moment"], hyper["adadelta_rho"],
                hyper["solver_epsilon"])
            entry = {"weights": new_w, "accum_weights": acc_w,
                     "accum2_weights": acc2_w,
                     "bias": s["bias"], "accum_bias": s["accum_bias"],
                     "accum2_bias": s["accum2_bias"]}
            if plan.include_bias and s["bias"] is not None:
                b = s["bias"]
                gb = GradientDescentBase.regularized(
                    g["bias"].astype(b.dtype), b,
                    hyper["weights_decay_bias"], hyper["l1_vs_l2"])
                new_b, acc_b, acc2_b = GradientDescentBase.solver_update(
                    plan.solver, b, gb, s["accum_bias"], s["accum2_bias"],
                    hyper["learning_rate_bias"],
                    hyper["gradient_moment_bias"], hyper["adadelta_rho"],
                    hyper["solver_epsilon"])
                entry.update({"bias": new_b, "accum_bias": acc_b,
                              "accum2_bias": acc2_b})
            new_state.append(entry)
        return new_state

    return step


def step_compiler_options():
    """Per-chip XLA options for the fused step, from the autotune DB
    (None when the device kind has no tuned entry — e.g. CPU tests).

    Currently one knob: ``train_step:scoped_vmem_kib`` ->
    ``xla_tpu_scoped_vmem_limit_kib``.  Measured v5e, AlexNet b256
    bf16, interleaved A/B: 96 MiB scoped VMEM runs the whole step ~3 %
    faster than the default and 64 MiB runs ~2 % slower, so the value
    ships per device kind in devices/device_infos.json rather than as
    a blanket flag."""
    import jax

    from veles_tpu.backends import DeviceInfo
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    vmem = DeviceInfo(kind).get("train_step:scoped_vmem_kib")
    if not vmem:
        return None
    return {"xla_tpu_scoped_vmem_limit_kib": str(int(vmem))}


def build_train_step(plans, loss="softmax", mesh=None, data_axis="data",
                     state_shardings=None, batch_sharding=None,
                     donate=True, compiler_options=None,
                     grad_bucket_mb=None, grad_compress=None,
                     grad_allreduce_impl="psum", bwd_schedule=None,
                     bwd_remat=False, zero=None, zero_shards=None):
    """Compile fn(state, x, labels_or_targets, batch_size) ->
    (new_state, metrics).

    state: list of dicts (weights/bias/accum*); metrics: {"loss", "n_err"}
    (classification) or {"loss"} (mse), plus the numerics-health trio
    {"grad_norm", "finite", "skipped"} — all lazy device scalars.  A
    step whose loss or global grad-norm is non-finite does NOT update
    the state (``skipped`` = 1; params and solver accumulators keep
    their pre-step values bit-exactly); see docs/health.md.  The
    optional ``grad_poison`` / ``loss_poison`` keyword scalars are the
    chaos harness's in-graph nan-injection hooks (None costs nothing).
    batch_size is a traced scalar so
    short minibatches don't retrigger compilation.
    ``compiler_options``: per-program XLA options (see
    :func:`step_compiler_options` for the tuned per-chip set).

    Distributed variants (docs/distributed.md):

    - ``mesh`` + ``state_shardings``: the annotation (pjit) path — XLA
      inserts the data-parallel gradient psum from the shardings.
    - ``mesh`` + ``grad_bucket_mb``: the SPMD shard_map path — the
      inner loop is explicit per-device code and the gradient merge is
      a BUCKETED all-reduce (parallel/bucketed.py): one collective per
      ~``grad_bucket_mb`` MB of gradients, issued in backward
      production order so the wire time overlaps the remaining
      backward.  ``float("inf")`` means one flat bucket (the
      bit-equality reference).  ``grad_compress="bf16"`` halves the
      wire bytes (numerics-guard + trainer fallback own the risk);
      ``grad_allreduce_impl`` picks ``"psum"`` (default) or ``"ring"``
      (explicit ppermute ring from parallel/ring.py).

    Backward scheduling (docs/kernels.md): ``bwd_schedule`` (None ->
    the VELES_PALLAS_BWD knob) chains per-layer gradients through
    optimization_barriers in backward production order — bit-identical
    values, decongested MXU schedule; ``bwd_remat`` checkpoints layer
    forwards (recompute-over-store).

    ``zero=1`` (with ``mesh``) selects the ZeRO-1 shard_map path
    (docs/distributed.md, "Elastic mesh contract"): the gradient merge
    is a reduce-scatter in backward production order, the solver
    update runs on each device's OWNED shards only (optimizer state —
    the accum leaves — lives sharded over the data axis, ~1/N per
    device), and an all-gather re-replicates the updated params.
    Bit-identical params to the flat all-reduce path on a fixed mesh
    (``psum_scatter`` sums like ``psum``; tests/test_mesh.py); only
    the ``grad_norm`` metric may differ in last-ULP digits (its
    squared-sum associates per-shard).  State must be in ZeRO form
    (:func:`veles_tpu.parallel.mesh.zero_state`): accum leaves shaped
    (n_slots, shard_elems) and a replicated int32 ``zero_slots`` table
    per layer mapping device slots to the ``zero_shards`` logical
    shards (default: one shard per device).  The table is a RUNTIME
    input — moving shards between devices never recompiles.
    """
    import jax

    if zero:
        if int(zero) != 1:
            raise ValueError("only the ZeRO-1 rung is implemented, "
                             "got zero=%r" % (zero,))
        if mesh is None:
            raise ValueError("zero=1 needs a mesh (the optimizer "
                             "state shards over its data axis)")
        if grad_compress:
            raise ValueError("zero=1 does not take grad_compress "
                             "(the reduce-scatter is the wire format)")
        return _build_zero1_spmd_train_step(
            plans, loss, mesh, data_axis,
            zero_shards or mesh.shape[data_axis], donate,
            compiler_options, bwd_schedule, bwd_remat)
    if mesh is not None and grad_bucket_mb is not None:
        return _build_spmd_train_step(
            plans, loss, mesh, data_axis, grad_bucket_mb, grad_compress,
            grad_allreduce_impl, donate, compiler_options,
            bwd_schedule, bwd_remat)

    step = _build_step_fn(plans, loss, bwd_schedule=bwd_schedule,
                          bwd_remat=bwd_remat)

    jit_kwargs = {}
    if compiler_options:
        jit_kwargs["compiler_options"] = compiler_options
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    if mesh is not None and state_shardings is not None:
        # 7-tuple: the optional step_key (dropout PRNG) and the chaos
        # poison scalars all ride replicated.  Everything is passed
        # POSITIONALLY — pjit rejects kwargs once in_shardings is
        # specified — with fixed arity so the spec always matches
        # (None args are empty pytrees)
        jit_kwargs["in_shardings"] = (
            state_shardings, batch_sharding, batch_sharding and
            _labels_sharding(mesh, data_axis, loss), None, None,
            None, None)
        jit_kwargs["out_shardings"] = (state_shardings, None)
        jitted = jax.jit(step, **jit_kwargs)

        def sharded_step(state, x, target, batch_size, step_key=None,
                         grad_poison=None, loss_poison=None):
            return jitted(state, x, target, batch_size, step_key,
                          grad_poison, loss_poison)
        sharded_step.lower = _fixed_arity_lower(jitted)
        return sharded_step
    return jax.jit(step, **jit_kwargs)


def _fixed_arity_lower(jitted):
    """A ``.lower`` for the fixed-arity step wrappers, so callers that
    introspect the compiled program (step-FLOPs publication, the
    collective-bytes receipts) work on the wrapped paths too."""
    def lower(state, x, target, batch_size, step_key=None,
              grad_poison=None, loss_poison=None):
        return jitted.lower(state, x, target, batch_size, step_key,
                            grad_poison, loss_poison)
    return lower


def _finalize_step(fn, donate, compiler_options, **attrs):
    """The ONE jit + fixed-arity-wrapper + ``.lower`` scaffold shared
    by every shard_map step builder (the SPMD path here,
    parallel/tensor.py, parallel/pipeline.py) — extra ``attrs`` land
    on the returned step (mesh, axes, bucket sizes) for callers that
    introspect it."""
    import jax

    jit_kwargs = {}
    if compiler_options:
        jit_kwargs["compiler_options"] = compiler_options
    if donate:
        jit_kwargs["donate_argnums"] = (0,)
    jitted = jax.jit(fn, **jit_kwargs)

    def step(state, x, target, batch_size, step_key=None,
             grad_poison=None, loss_poison=None):
        return jitted(state, x, target, batch_size, step_key,
                      grad_poison, loss_poison)
    step.lower = _fixed_arity_lower(jitted)
    for key, value in attrs.items():
        setattr(step, key, value)
    return step


def _build_spmd_train_step(plans, loss, mesh, data_axis, grad_bucket_mb,
                           grad_compress, grad_allreduce_impl, donate,
                           compiler_options, bwd_schedule=None,
                           bwd_remat=False):
    """The pure-SPMD data plane: shard_map over ``mesh``'s data axis,
    per-device backward on the local batch shard, bucketed gradient
    all-reduce (parallel/bucketed.py), replicated update.  State and
    metrics ride replicated; batch/targets are sharded on the leading
    dim.  Returns the same fixed-arity step the other paths do."""
    import math as _math

    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from veles_tpu.parallel import bucketed as _bucketed
    from veles_tpu.parallel.mesh import shard_map

    n = mesh.shape[data_axis]
    bucket_bytes = (float("inf") if _math.isinf(float(grad_bucket_mb))
                    else float(grad_bucket_mb) * 2.0 ** 20)

    def grad_sync(grads):
        return _bucketed.bucketed_all_reduce(
            grads, data_axis, bucket_bytes=bucket_bytes,
            impl=grad_allreduce_impl, compress=grad_compress,
            axis_size=n)

    def metric_sync(value):
        return lax.psum(value, data_axis)

    def row_offset_fn():
        # recomputed lazily inside the traced step: local row count is
        # not known until the batch shard's shape is
        return lax.axis_index(data_axis) * _local_rows[0]

    _local_rows = [0]
    raw = _build_step_fn(plans, loss, grad_sync=grad_sync,
                         metric_sync=metric_sync,
                         row_offset_fn=row_offset_fn,
                         bwd_schedule=bwd_schedule,
                         bwd_remat=bwd_remat)

    def local_step(state, x, target, batch_size, step_key,
                   grad_poison, loss_poison):
        _local_rows[0] = x.shape[0]
        if step_key is not None:
            # distinct dropout stream per shard: the pjit path draws
            # ONE mask over the global batch; the SPMD shards must not
            # all reuse the same per-row noise
            step_key = jax.random.fold_in(
                step_key, lax.axis_index(data_axis))
        return raw(state, x, target, batch_size, step_key,
                   grad_poison, loss_poison)

    spmd = shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), P(data_axis), P(data_axis), P(), P(), P(), P()),
        out_specs=(P(), P()), check_vma=False)
    return _finalize_step(spmd, donate, compiler_options, mesh=mesh,
                          data_axis=data_axis,
                          bucket_bytes=bucket_bytes)


def _build_zero1_spmd_train_step(plans, loss, mesh, data_axis, n_shards,
                                 donate, compiler_options,
                                 bwd_schedule=None, bwd_remat=False):
    """The ZeRO-1 shard_map data plane (docs/distributed.md, "Elastic
    mesh contract"): per-device backward on the local batch shard, the
    gradient merge as a chained reduce-scatter in backward production
    order, the solver update on each device's OWNED logical shards
    only (accum leaves live sharded over ``data_axis`` — per-device
    optimizer memory is ~1/N), and an all-gather re-replicating the
    updated params.  Shard placement is the runtime ``zero_slots``
    table (parallel/bucketed.py slot helpers), so the compiled program
    depends on the mesh SIZE but never on which device owns which
    shard — the MeshManager moves shards without recompiling."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from veles_tpu.parallel import bucketed as _bucketed
    from veles_tpu.parallel.mesh import shard_map

    n = mesh.shape[data_axis]
    m = int(n_shards)
    k = -(-m // n)  # device slots; table pads with the zero-row id m
    hypers = [p.hyper_full() for p in plans]
    _local_rows = [0]

    def metric_sync(value):
        return lax.psum(value, data_axis)

    def row_offset_fn():
        return lax.axis_index(data_axis) * _local_rows[0]

    # (tensor key, accum keys, hyper keys) — the two per-layer tensors
    # the solver walks, same hyper wiring as the flat update loop
    _TENSORS = (
        ("weights", "accum_weights", "accum2_weights", "learning_rate",
         "gradient_moment", "weights_decay"),
        ("bias", "accum_bias", "accum2_bias", "learning_rate_bias",
         "gradient_moment_bias", "weights_decay_bias"),
    )

    def zero_update(state, grads):
        slots = next(s["zero_slots"] for s in state
                     if s.get("zero_slots") is not None)
        rank = lax.axis_index(data_axis)
        # backward PRODUCTION order (last layer first, weights before
        # bias — grads of a layer exist together), so each
        # reduce-scatter can issue while earlier layers' backward runs
        jobs = []
        for idx in range(len(plans) - 1, -1, -1):
            s = state[idx]
            if s["weights"] is None:
                continue
            jobs.append((idx, "weights"))
            if plans[idx].include_bias and s["bias"] is not None:
                jobs.append((idx, "bias"))
        mats = []
        for idx, tensor in jobs:
            g = grads[idx][tensor]
            e = _bucketed.shard_elems(g.size, m)
            mats.append(_bucketed.slot_matrix(g, slots, m, e))
        parts = _bucketed.chained_reduce_scatter(mats, data_axis)
        shard_of = dict(zip(jobs, parts))
        # global grad-norm from the owned shards: every element of the
        # summed gradient lives in exactly one shard (pad rows are
        # zero), so the psum'd squared-sum covers every leaf and the
        # skip verdict is uniform across ranks — association differs
        # from the flat path's, so grad_norm may differ in last ULPs
        gsq = lax.psum(
            sum(jnp.sum(jnp.square(p.astype(jnp.float32)))
                for p in parts), data_axis)
        my_slots = lax.dynamic_slice(slots, (rank * k,), (k,))
        new_state = []
        for idx, s in enumerate(state):
            if s["weights"] is None:  # param-less layer passthrough
                new_state.append(dict(s))
                continue
            plan, hyper = plans[idx], hypers[idx]
            entry = dict(s)
            for (tensor, acc_key, acc2_key, lr_key, mom_key,
                 dec_key) in _TENSORS:
                g_my = shard_of.get((idx, tensor))
                if g_my is None:
                    continue
                w = s[tensor]
                e = _bucketed.shard_elems(w.size, m)
                w_rows = _bucketed.slot_matrix(w, slots, m, e)
                w_my = lax.dynamic_slice(w_rows, (rank * k, 0), (k, e))
                gw = GradientDescentBase.regularized(
                    g_my.astype(w.dtype), w_my, hyper[dec_key],
                    hyper["l1_vs_l2"])
                # elementwise solver with per-layer SCALAR hypers: the
                # sharded update is the full-tensor update restricted
                # to owned elements — bit-identical per element
                new_my, new_acc, new_acc2 = \
                    GradientDescentBase.solver_update(
                        plan.solver, w_my, gw, s[acc_key], s[acc2_key],
                        hyper[lr_key], hyper[mom_key],
                        hyper["adadelta_rho"], hyper["solver_epsilon"])
                w_all = _bucketed.gather_slots(new_my, data_axis)
                entry[tensor] = _bucketed.unslot_matrix(
                    w_all, slots, m, w.size, w.shape, w.dtype)
                entry[acc_key] = new_acc
                entry[acc2_key] = new_acc2
            new_state.append(entry)
        return new_state, gsq

    raw = _build_step_fn(plans, loss, metric_sync=metric_sync,
                         row_offset_fn=row_offset_fn,
                         bwd_schedule=bwd_schedule, bwd_remat=bwd_remat,
                         zero_update=zero_update)

    def local_step(state, x, target, batch_size, step_key,
                   grad_poison, loss_poison):
        _local_rows[0] = x.shape[0]
        if step_key is not None:
            step_key = jax.random.fold_in(
                step_key, lax.axis_index(data_axis))
        return raw(state, x, target, batch_size, step_key,
                   grad_poison, loss_poison)

    _SHARDED = ("accum_weights", "accum_bias", "accum2_weights",
                "accum2_bias")

    def state_specs(state):
        # accum leaves ride sharded on the leading (slot) dim; params,
        # slot tables and None leaves ride replicated.  Built from the
        # traced state at trace time, so the one builder serves any
        # solver's state structure
        return [{key: (None if value is None else
                       P(data_axis) if key in _SHARDED else P())
                 for key, value in entry.items()} for entry in state]

    def spmd_fn(state, x, target, batch_size, step_key, grad_poison,
                loss_poison):
        specs = state_specs(state)
        fn = shard_map(
            local_step, mesh=mesh,
            in_specs=(specs, P(data_axis), P(data_axis), P(), P(), P(),
                      P()),
            out_specs=(specs, P()), check_vma=False)
        return fn(state, x, target, batch_size, step_key, grad_poison,
                  loss_poison)

    return _finalize_step(spmd_fn, donate, compiler_options, mesh=mesh,
                          data_axis=data_axis, zero=1, n_shards=m,
                          slots_per_device=k)


def _labels_sharding(mesh, data_axis, loss):
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(data_axis))


def _tail_schedule(order, batch, what):
    """Static tail plan shared by the train/eval epoch scans:
    ceil-div step count, edge-padded order (padded slots are masked
    out by the callers), per-step valid-row counts."""
    import jax.numpy as jnp

    n = order.shape[0]
    n_steps = -(-n // batch)
    if n_steps == 0:
        # a zero-iteration scan would return empty metrics with the
        # state silently unchanged
        raise ValueError("%s: order is empty (batch %d)" % (what, batch))
    pad = n_steps * batch - n
    if pad:
        order = jnp.pad(order, (0, pad), mode="edge")
    sizes = jnp.full((n_steps,), batch, jnp.int32)
    if pad:
        sizes = sizes.at[n_steps - 1].set(batch - pad)
    return order, sizes, n_steps, n


def build_train_epoch(plans, batch, loss="softmax", donate=True,
                      compiler_options=None):
    """Compile fn(state, dataset, targets, order, key=None) ->
    (new_state, epoch_metrics): the WHOLE epoch as one XLA dispatch.

    ``lax.scan`` walks ``order`` in ``batch``-sized windows, gathering
    each minibatch from the HBM-resident dataset (Pallas gather) and
    applying the same train step build_train_step compiles — so on a
    dispatch-bound model (small MLPs, remote-tunneled chips where each
    dispatch costs ~0.2-0.8 ms) per-step cost collapses to pure
    compute.  The per-step path remains the product default because
    the decision unit gates per minibatch; this is the turbo path for
    epoch-granular control (and what bench.py reports as mnist
    ``scan_*`` rows).

    ``targets``: int labels (softmax) or a float target array indexed
    like the dataset (mse).  ``order`` (int32 (N,)) defines epoch
    order; ceil(N / batch) steps run — a tail shorter than ``batch``
    executes as one masked step (padded slots carry sentinel labels /
    zeroed residuals, so they contribute nothing to gradients or
    metrics), giving exact N-sample coverage like the unit path.
    metrics: {"loss_mean", "n_err"} (+"mse_sum" for mse); loss_mean is
    the sample-weighted epoch mean.
    """
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.gather import gather_labels, gather_minibatch

    step = _build_step_fn(plans, loss)

    def epoch(state, dataset, targets, order, key=None):
        order, sizes, n_steps, n = _tail_schedule(
            order, batch, "build_train_epoch")
        sizes = sizes.astype(jnp.float32)  # step's batch_size arg

        def body(carry, scans):
            st = carry
            i, size = scans
            idx = jax.lax.dynamic_slice(order, (i * batch,), (batch,))
            x = gather_minibatch(dataset, idx)
            if loss == "softmax":
                y = gather_labels(targets, idx)
                # padded slots -> sentinel label: excluded from the CE
                # sum, n_err, and gradients by the loss's valid mask
                y = jnp.where(jnp.arange(batch) < size, y, -1)
            else:
                # mse loss masks rows >= batch_size itself
                y = gather_minibatch(targets, idx)
            k = None if key is None else jax.random.fold_in(key, i)
            st, m = step(st, x, y, size, k)
            return st, m

        state, ms = jax.lax.scan(body, state,
                                 (jnp.arange(n_steps), sizes))
        totals = {"loss_mean": jnp.sum(ms["loss"] * sizes) / n,
                  "n_err": ms["n_err"].sum(),
                  # steps whose update the numerics guard refused to
                  # apply (non-finite loss/grads); callers treat > 0 as
                  # a health signal (docs/health.md)
                  "skipped": ms["skipped"].sum()}
        if "mse_sum" in ms:
            totals["mse_sum"] = ms["mse_sum"].sum()
        return state, totals

    jit_kwargs = {"donate_argnums": (0,)} if donate else {}
    if compiler_options:
        jit_kwargs["compiler_options"] = compiler_options
    return jax.jit(epoch, **jit_kwargs)


def build_eval_epoch(plans, batch, loss="softmax",
                     compiler_options=None):
    """Compile fn(params, dataset, targets, order) -> metrics: the
    whole evaluation pass as one XLA dispatch.

    The eval twin of :func:`build_train_epoch` — scans ``order`` in
    ``batch``-sized windows, gathers each minibatch, runs the forward
    (dropout layers are identity at eval), and accumulates metrics on
    device: {"n_err", "samples"} for softmax, {"mse_sum", "samples"}
    for mse (same definitions the evaluator units use, so epoch error
    rates and RMSE are commensurate with the unit path).  ``params``
    is the [{"weights", "bias"}] list build_forward consumes.  A tail
    shorter than ``batch`` runs as one masked step, so metrics cover
    all N samples exactly; ``samples`` counts the rows that actually
    entered the metric (valid labels for softmax), making
    n_err/samples an undiluted error rate even with sentinel labels.
    """
    import jax
    import jax.numpy as jnp

    from veles_tpu.ops.gather import gather_labels, gather_minibatch

    def epoch(params, dataset, targets, order):
        order, sizes, n_steps, _ = _tail_schedule(
            order, batch, "build_eval_epoch")

        def body(carry, scans):
            total, count = carry
            i, size = scans
            idx = jax.lax.dynamic_slice(order, (i * batch,), (batch,))
            x = gather_minibatch(dataset, idx)
            out = _forward_for_loss(plans, params, x)
            slot = jnp.arange(batch) < size
            if loss == "softmax":
                y = gather_labels(targets, idx)
                valid = (y >= 0) & slot
                pred = jnp.argmax(out, axis=-1)
                m = jnp.sum((pred != y) & valid).astype(jnp.int32)
                c = jnp.sum(valid).astype(jnp.int32)
            else:
                t = gather_minibatch(targets, idx)
                diff = (out.reshape(out.shape[0], -1)
                        - t.reshape(t.shape[0], -1))
                diff = diff * slot[:, None].astype(diff.dtype)
                m = jnp.sum(jnp.mean(diff * diff, axis=1))
                c = size
            return (total + m, count + c), None

        init = ((jnp.zeros((), jnp.int32) if loss == "softmax"
                 else jnp.zeros((), jnp.float32)),
                jnp.zeros((), jnp.int32))
        (total, count), _ = jax.lax.scan(
            body, init, (jnp.arange(n_steps), sizes))
        name = "n_err" if loss == "softmax" else "mse_sum"
        return {name: total, "samples": count}

    return jax.jit(epoch, compiler_options=compiler_options or None)
