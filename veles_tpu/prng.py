"""Reproducible random number generation.

TPU-native counterpart of the reference's PRNG registry
(reference: veles/prng/random_generator.py:64,250-262).

Design mapping (documented per SURVEY.md section 7 hard part 2):

- The reference keeps *stateful* numpy RNGs keyed by name and replays exact
  numpy global state.  Host-side work here (shuffles, weight init on CPU,
  augmentation) uses a keyed registry of ``numpy.random.Generator`` objects
  whose state pickles with workflow snapshots, giving the same
  save/restore-reproducibility guarantee without monkey-patching
  ``numpy.random``.
- Device-side randomness maps to counter-based ``jax.random`` keys: every
  :class:`RandomGenerator` can mint a deterministic ``jax.random`` key
  stream via :meth:`jax_key`, derived from its seed and a fold-in counter,
  which is the idiomatic (and jit-safe) TPU design.
"""

import os
import threading

import numpy

__all__ = ["RandomGenerator", "get"]


class RandomGenerator(object):
    """A named, seedable, picklable RNG with a JAX key stream."""

    def __init__(self, key, seed=None):
        self.key = key
        self._lock = threading.Lock()
        self._seed = None
        self._jax_counter = 0
        self.seed(seed if seed is not None else self._default_seed())

    @staticmethod
    def _default_seed():
        env = os.environ.get("VELES_SEED")
        if env:
            return int(env, 0)
        return 1234567890  # fixed default: reproducible out of the box

    @property
    def seed_value(self):
        return self._seed

    def seed(self, seed, dtype=None, count=None):
        """Reset state.  ``seed`` may be int, bytes, or ndarray."""
        if isinstance(seed, (bytes, bytearray)):
            seed = int.from_bytes(bytes(seed[:8]).ljust(8, b"\0"), "little")
        elif isinstance(seed, numpy.ndarray):
            seed = int(numpy.asarray(seed).ravel()[0])
        with self._lock:
            self._seed = int(seed) & (2 ** 64 - 1)
            self._np = numpy.random.Generator(
                numpy.random.Philox(self._seed))
            self._jax_counter = 0

    # -- host-side sampling (numpy) ---------------------------------------

    def fill(self, arr, vmin=-1.0, vmax=1.0):
        """Fill an ndarray in-place with uniforms in [vmin, vmax)."""
        with self._lock:
            arr[...] = self._np.uniform(
                vmin, vmax, size=arr.shape).astype(arr.dtype)

    def fill_normal(self, arr, mean=0.0, stddev=1.0, clip_to_sigma=None):
        with self._lock:
            sample = self._np.normal(mean, stddev, size=arr.shape)
            if clip_to_sigma is not None:
                lo = mean - clip_to_sigma * stddev
                hi = mean + clip_to_sigma * stddev
                sample = numpy.clip(sample, lo, hi)
            arr[...] = sample.astype(arr.dtype)

    def normal(self, loc=0.0, scale=1.0, size=None):
        with self._lock:
            return self._np.normal(loc, scale, size)

    def uniform(self, low=0.0, high=1.0, size=None):
        with self._lock:
            return self._np.uniform(low, high, size)

    def random_sample(self, size=None):
        with self._lock:
            return self._np.random(size)

    def randint(self, low, high=None, size=None, dtype=numpy.int64):
        with self._lock:
            return self._np.integers(low, high, size, dtype=dtype)

    def shuffle(self, arr):
        with self._lock:
            self._np.shuffle(arr)

    def permutation(self, x):
        with self._lock:
            return self._np.permutation(x)

    def choice(self, a, size=None, replace=True):
        with self._lock:
            return self._np.choice(a, size, replace)

    # -- device-side key stream (jax) -------------------------------------

    def jax_key(self):
        """Return the next key in a deterministic ``jax.random`` stream.

        Key ``i`` derives from the FULL 64-bit seed (low and high halves
        folded in separately) plus the counter — stable across processes
        for multi-host SPMD as long as seeds match.
        """
        import jax
        with self._lock:
            counter = self._jax_counter
            self._jax_counter += 1
            seed = self._seed
        base = jax.random.PRNGKey(seed & (2 ** 31 - 1))
        high = seed >> 31
        if high:
            base = jax.random.fold_in(base, high & (2 ** 31 - 1))
            if high >> 31:
                base = jax.random.fold_in(base, high >> 31)
        return jax.random.fold_in(base, counter)

    # -- snapshot support ---------------------------------------------------

    def __getstate__(self):
        return {"key": self.key, "seed": self._seed,
                "np_state": self._np.bit_generator.state,
                "jax_counter": self._jax_counter}

    def __setstate__(self, state):
        self.key = state["key"]
        self._lock = threading.Lock()
        self._seed = state["seed"]
        self._np = numpy.random.Generator(numpy.random.Philox(self._seed))
        self._np.bit_generator.state = state["np_state"]
        self._jax_counter = state["jax_counter"]

    def save_state(self):
        return self.__getstate__()

    def restore_state(self, state):
        self.__setstate__(state)


_registry = {}
_registry_lock = threading.Lock()


def get(key="default"):
    """Return the process-wide :class:`RandomGenerator` named ``key``."""
    with _registry_lock:
        rng = _registry.get(key)
        if rng is None:
            rng = RandomGenerator(key)
            _registry[key] = rng
        return rng
