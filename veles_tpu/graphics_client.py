"""Graphics client: subscribes to the PUB socket and renders plots.

Reference veles/graphics_client.py:84 rendered with interactive
matplotlib backends (incl. WebAgg); this renderer defaults to Agg with
one PNG per plotter class (updated in place), which doubles as the
golden-file path used by tests.  Run as
``python -m veles_tpu.graphics_client --endpoint tcp://... --output d``.
"""

import argparse
import os

from veles_tpu import plotter as plotter_module

__all__ = ["render_plot", "main"]


def render_plot(plot, output_dir):
    import matplotlib
    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt
    fig, axes = plt.subplots(figsize=(6, 4), dpi=96)
    plot.render(axes)
    path = os.path.join(output_dir, "%s.png" % type(plot).__name__)
    fig.savefig(path)
    plt.close(fig)
    return path


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--endpoint", required=True)
    parser.add_argument("--output", default=".")
    parser.add_argument("--limit", type=int, default=0,
                        help="exit after N plots (0 = run forever)")
    args = parser.parse_args(argv)

    import zmq
    context = zmq.Context.instance()
    socket = context.socket(zmq.SUB)
    socket.connect(args.endpoint)
    socket.setsockopt(zmq.SUBSCRIBE, b"")
    os.makedirs(args.output, exist_ok=True)

    count = 0
    while True:
        blob = socket.recv()
        plot = plotter_module.loads(blob)
        render_plot(plot, args.output)
        count += 1
        if args.limit and count >= args.limit:
            break


if __name__ == "__main__":
    main()
