"""Master-side control plane: job farming with elastic-failure semantics.

TPU-native counterpart of reference veles/server.py:659.  Since the
SPMD split (docs/distributed.md) this plane is deliberately DEMOTED to
what it is uniquely good at — membership, elasticity, quarantine, and
checkpoint coordination: per-step gradients ride ICI inside the
compiled shard_map step (parallel/bucketed.py), never this protocol.
Update payloads are control records, which is what lets the master
validate them with the single-traversal inline walk
(``Workflow.apply_update_validated``) instead of a separate
whole-payload prewalk.

Preserved capabilities (SURVEY.md section 2.6/5):

- handshake validating the workflow CHECKSUM, slave id assignment;
- per-slave state tracking (the reference's fysom FSM collapses to a
  dict of outstanding jobs — asyncio replaces Twisted);
- job generation / update application deferred to a worker thread so the
  event loop never blocks on workflow code;
- sync points: a loader that answers "not ready" (False) parks the
  requester until the next update lands;
- ADAPTIVE TIMEOUT: a slave whose job takes longer than
  max(mean + 3 sigma of all job times, job_timeout) is dropped and
  BLACKLISTED (reference server.py:619-635);
- drop_slave -> workflow.drop_slave -> loaders requeue the minibatches;
- respawn hook with exponential backoff (the reference respawned over
  SSH; on TPU clusters process lifecycle belongs to the scheduler, so
  the hook takes a user callable).
"""

import asyncio
import threading
import time
from collections import deque

import numpy

from veles_tpu import chaos, health
from veles_tpu.cmdline import CommandLineArgumentsRegistry
from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.observe.cluster import TraceCollector
from veles_tpu.observe.flight import flight as _flight
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.network_common import (
    ProtocolError, ShmChannel, available_codecs, default_secret,
    machine_id, new_id, pack_payload, parse_address, read_frame,
    unpack_payload, write_frame)

__all__ = ["Server", "SlaveDescription"]


class SlaveDescription(object):
    """What workflow code sees as ``slave`` in the data contract."""

    def __init__(self, sid, mid, pid, power):
        self.id = sid
        self.mid = mid
        self.pid = pid
        self.power = power

    def __repr__(self):
        return "<Slave %s power=%.1f>" % (self.id[:8], self.power)


class _SlaveConn(object):
    def __init__(self, slave, reader, writer):
        self.slave = slave
        self.reader = reader
        self.writer = writer
        self.jobs_out = {}          # job_id -> dispatch timestamp
        self.job_times = deque(maxlen=50)
        self.parked = False
        self.shm_out = None         # master -> slave payload channel
        self.shm_in = None          # slave -> master payload channel

    def close_shm(self):
        for chan in (self.shm_out, self.shm_in):
            if chan is not None:
                chan.close()
        self.shm_out = self.shm_in = None


class Server(Logger, metaclass=CommandLineArgumentsRegistry):
    """Serve a workflow's jobs to connecting slaves."""

    #: sentinel returned by the update validator when a payload failed
    #: the finiteness check (either mode) -> quarantine path
    _POISONED = object()
    #: sentinel for an apply that raised (already acked 0); distinct
    #: from a legal None return, which counts as a served update
    _FAILED = object()

    @classmethod
    def init_parser(cls, parser):
        parser.add_argument(
            "--job-timeout", type=float, default=None,
            help="base seconds before a slave's job is considered "
                 "stuck (the adaptive threshold never drops below it)")
        parser.add_argument(
            "--codec", default=None, choices=available_codecs(),
            help="wire payload codec")
        parser.add_argument(
            "--no-shm", action="store_true", default=None,
            help="disable the same-host shared-memory payload bypass")
        parser.add_argument(
            "--blacklist-ttl", type=float, default=None,
            help="seconds a dropped/quarantined slave stays "
                 "blacklisted before it may rejoin")
        return parser

    @classmethod
    def apply_args(cls, args):
        cfg = {}
        if getattr(args, "job_timeout", None) is not None:
            cfg["job_timeout"] = args.job_timeout
        if getattr(args, "codec", None) is not None:
            cfg["codec"] = args.codec
        if getattr(args, "no_shm", None):
            cfg["shm"] = False
        if getattr(args, "blacklist_ttl", None) is not None:
            cfg["blacklist_ttl"] = args.blacklist_ttl
        root.common.network.update(cfg)

    def __init__(self, address, workflow, launcher=None, codec=None,
                 job_timeout=None, respawn_hook=None, secret=None,
                 use_shm=None, shm_size=None, blacklist_ttl=None):
        super(Server, self).__init__()
        net = root.common.network
        self.host, self.port = parse_address(address)
        self.workflow = workflow
        self.launcher = launcher
        self.codec = codec if codec is not None else net.get(
            "codec", "none")
        self.use_shm = use_shm if use_shm is not None else net.get(
            "shm", True)
        self.shm_size = shm_size if shm_size is not None else net.get(
            "shm_size", 1 << 24)
        self.shm_sends = 0
        self.job_timeout = job_timeout if job_timeout is not None \
            else net.get("job_timeout", 60.0)
        self.respawn_hook = respawn_hook
        self.secret = secret if secret is not None else default_secret()
        # mid -> expiry timestamp: blacklisting is a QUARANTINE with a
        # TTL, not a life sentence — a once-slow machine (or one that
        # sent one poisoned update) may rejoin after it expires
        self.blacklist_ttl = blacklist_ttl if blacklist_ttl is not None \
            else net.get("blacklist_ttl", 30.0)
        self.blacklist = {}
        #: per-slave consecutive respawn attempts (mid -> count); the
        #: respawn delay backs off on THIS, not on global blacklist
        #: size, and resets once the slave applies a productive update
        self._respawn_attempts = {}
        #: run-scoped trace id: propagated to every slave in the
        #: handshake ack so the whole job's spans — master and slave —
        #: stitch under ONE id in the merged cluster trace
        self.trace_id = new_id()
        #: shipped slave trace chunks + per-slave clock offsets
        #: (docs/observability.md, distributed tracing)
        self.trace_collector = TraceCollector()
        self.quarantined = 0
        self.slaves = {}
        self._waiting = deque()     # parked requesters (sync points)
        self._all_job_times = deque(maxlen=500)
        self._loop = None
        self._server = None
        self._finishing = False
        self._paused = False
        self._stop_event = None
        self._done = threading.Event()
        self._listening = threading.Event()
        self.bind_error = None
        self.jobs_dispatched = 0
        self.updates_applied = 0

    # -- public lifecycle ---------------------------------------------------

    def run(self):
        """Blocking: serve until the workflow finishes."""
        asyncio.run(self._main())

    def start_background(self):
        thread = threading.Thread(target=self.run, daemon=True)
        thread.start()
        return thread

    def wait_listening(self, timeout=10.0):
        """Block until the socket accepts connections.  Returns True
        when listening; False on bind failure (see ``bind_error``) or
        timeout — a background server that failed to bind would
        otherwise die silently on its daemon thread."""
        if not self._listening.wait(timeout):
            return False
        return self.bind_error is None

    def on_workflow_finished(self):
        self._finishing = True
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._signal_stop)

    def stop(self):
        self.on_workflow_finished()

    def pause(self):
        """Park all slaves: broadcast 'pause'; job requests queue up
        server-side until resume() (reference server.py:734-745)."""
        if self._loop is None:
            self._paused = True
            return
        self._loop.call_soon_threadsafe(self._do_pause)

    def resume(self):
        if self._loop is None:
            self._paused = False
            return
        self._loop.call_soon_threadsafe(
            lambda: asyncio.ensure_future(self._do_resume()))

    @property
    def paused(self):
        return self._paused

    def _do_pause(self):
        self._paused = True
        self._broadcast({"type": "pause"})

    async def _do_resume(self):
        self._paused = False
        self._broadcast({"type": "resume"})
        await self._release_parked()

    # -- asyncio internals ---------------------------------------------------

    def _signal_stop(self):
        self._broadcast_stop()
        if self._stop_event is not None:
            self._stop_event.set()

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        if self._finishing:
            self._stop_event.set()
        try:
            self._server = await asyncio.start_server(
                self._handle_conn, self.host, self.port)
        except OSError as exc:
            # surface the failure to waiters (start_background callers
            # can only see it through bind_error) instead of dying
            # silently on a daemon thread
            self.bind_error = exc
            self.error("failed to bind %s:%s: %s", self.host,
                       self.port, exc)
            self._listening.set()
            self._done.set()
            return
        self.port = self._server.sockets[0].getsockname()[1]
        self._listening.set()
        if getattr(self.workflow, "restored_from_snapshot_", False):
            self.info(
                "master listening on %s:%d (restored from snapshot; "
                "re-admitting slaves at epoch %s)", self.host, self.port,
                getattr(getattr(self.workflow, "loader", None),
                        "epoch_number", "?"))
        else:
            self.info("master listening on %s:%d", self.host, self.port)
        watchdog = asyncio.ensure_future(self._watchdog())
        try:
            await self._stop_event.wait()
        finally:
            self._finishing = True
            watchdog.cancel()
            self._broadcast_stop()
            for conn in list(self.slaves.values()):
                conn.close_shm()
            self._server.close()
            await self._server.wait_closed()
            self._done.set()

    async def _handle_conn(self, reader, writer):
        conn = None
        try:
            while True:
                msg, payload = await read_frame(reader, self.secret,
                                                peer="master")
                if conn is not None and conn.shm_in is not None \
                        and "shm" in msg:
                    off, length = msg["shm"]
                    payload = conn.shm_in.read(off, length)
                conn = await self._dispatch(
                    msg, payload, conn, reader, writer)
                if conn is None and msg.get("type") != "handshake":
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except ProtocolError as exc:
            self.warning("rejecting peer: %s", exc)
        except Exception:
            self.exception("connection handler failed")
        finally:
            if conn is not None:
                self._drop(conn, "disconnected")
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, msg, payload, conn, reader, writer):
        mtype = msg.get("type")
        if mtype == "handshake":
            return await self._handshake(msg, reader, writer)
        if conn is None:
            self._send(writer, {"type": "error",
                                "reason": "handshake required"})
            return None
        if mtype == "job_request":
            await self._serve_job(conn)
        elif mtype == "update":
            await self._apply_update(conn, msg, payload)
        elif mtype == "clock_probe":
            # NTP-style offset handshake (observe/cluster.py): answer
            # IN the event loop — a thread hop here would inflate the
            # apparent one-way delay the estimate divides by
            now = time.time()
            self._send(conn.writer, {
                "type": "clock_probe_ack", "t0": msg.get("t0"),
                "t1": now, "t2": now})
        elif mtype == "clock_report":
            offset = msg.get("offset")
            if isinstance(offset, (int, float)):
                # the client reports "server ahead by offset", i.e.
                # slave_wall + offset = master_wall — exactly the
                # additive correction merge_parts applies
                self.trace_collector.set_offset(
                    conn.slave.mid, float(offset), msg.get("delay"))
                self.debug("slave %s clock offset %.6fs (delay %.6fs)",
                           conn.slave.id[:8], offset,
                           msg.get("delay") or -1.0)
        elif mtype == "trace_chunk":
            try:
                chunk = unpack_payload(payload, msg.get("codec", "none"))
            except Exception as exc:
                self.warning("undecodable trace chunk from slave %s "
                             "dropped (%s: %s)", conn.slave.id[:8],
                             type(exc).__name__, exc)
            else:
                self.trace_collector.add_chunk(conn.slave.mid, chunk)
        return conn

    def _blacklist(self, mid):
        self.blacklist[mid] = time.time() + self.blacklist_ttl
        _registry.gauge("server.blacklist_size").set(len(self.blacklist))

    def _blacklisted(self, mid):
        """True while ``mid``'s quarantine TTL has not expired; expired
        entries are dropped on the way (the slave may rejoin)."""
        expiry = self.blacklist.get(mid)
        if expiry is None:
            return False
        if time.time() >= expiry:
            del self.blacklist[mid]
            _registry.gauge("server.blacklist_size").set(
                len(self.blacklist))
            self.info("blacklist TTL expired for slave %s; eligible "
                      "to rejoin", mid)
            return False
        return True

    async def _handshake(self, msg, reader, writer):
        if self._finishing:
            # a join racing shutdown must not allocate per-slave
            # resources (shm segments): the event loop may be torn
            # down before this handler's cleanup path ever runs,
            # leaking the segments past process exit
            self._send(writer, {"type": "stop"})
            return None
        checksum = msg.get("checksum")
        mid = msg.get("mid", "?")
        if checksum != self.workflow.checksum:
            self.warning("rejecting slave %s: checksum mismatch", mid)
            self._send(writer, {"type": "reject",
                                "reason": "checksum mismatch"})
            return None
        if self._blacklisted(mid):
            retry_after = max(self.blacklist[mid] - time.time(), 0.0)
            self.warning("rejecting blacklisted slave %s (%.1fs left)",
                         mid, retry_after)
            # retry_after marks the rejection TRANSIENT: the client
            # sleeps it out and retries instead of giving up for good
            self._send(writer, {"type": "reject",
                                "reason": "blacklisted",
                                "retry_after": retry_after})
            return None
        sid = new_id()
        slave = SlaveDescription(sid, mid, msg.get("pid", 0),
                                 msg.get("power", 1.0))
        conn = _SlaveConn(slave, reader, writer)
        # the run's trace id rides the protocol header: every span or
        # chunk the slave records correlates back to THIS master run
        ack = {"type": "handshake_ack", "id": sid,
               "trace": self.trace_id}
        epoch = getattr(getattr(self.workflow, "loader", None),
                        "epoch_number", None)
        if epoch is not None:
            # a slave (re)joining a restarted master learns the epoch
            # it is being admitted at — resume observability
            ack["epoch"] = int(epoch)
        if self.use_shm and msg.get("machine") == machine_id():
            # same host: payloads ride shared memory, not the socket
            # (reference SharedIO engagement, server.py:144-167)
            try:
                conn.shm_out = ShmChannel.create(self.shm_size)
                conn.shm_in = ShmChannel.create(self.shm_size)
                ack["shm"] = {"m2s": conn.shm_out.name,
                              "s2m": conn.shm_in.name}
                self.info("slave %s is local: shm payload bypass on",
                          sid[:8])
            except Exception:
                self.exception("shm setup failed; staying on socket")
                conn.close_shm()
        self.slaves[sid] = conn
        initial = await self._in_thread(
            self.workflow.generate_initial_data_for_slave, slave)
        self._send(writer, ack, payload=initial)
        if self._paused:
            self._send(writer, {"type": "pause"})
        self.info("slave %s connected (mid %s)", sid[:8], mid)
        return conn

    async def _serve_job(self, conn):
        if self._finishing:
            self._send(conn.writer, {"type": "stop"})
            return
        if self._paused:
            # parked until resume(); no reply — the slave already got
            # 'pause' and is not busy-waiting
            conn.parked = True
            self._waiting.append(conn)
            return
        data = await self._in_thread(
            self.workflow.generate_data_for_slave, conn.slave)
        if data is False:
            # sync point: park until an update unlocks new work
            conn.parked = True
            self._waiting.append(conn)
            self._send(conn.writer, {"type": "wait"})
            return
        if chaos.plan is not None:
            fault = chaos.plan.fire("server.serve")
            if fault is not None:
                if fault.action == "kill":
                    # mid-batch conn death: the minibatch is already
                    # reserved to this slave, so the drop path MUST
                    # requeue it (watchdog/drop_slave contract)
                    self.warning("fault injection: killing conn of "
                                 "slave %s mid-batch",
                                 conn.slave.id[:8])
                    conn.writer.close()
                    return
                if fault.action == "stall":
                    await asyncio.sleep(fault.param or 0.5)
        job_id = new_id()
        # perf_counter, not time.time: these stamps feed the adaptive
        # timeout and the job-latency stats, and a wall-clock NTP step
        # would fake a straggler (or hide one)
        conn.jobs_out[job_id] = time.perf_counter()
        self.jobs_dispatched += 1
        _registry.counter("server.jobs_dispatched").inc()
        _tracer.instant("proto.job_out", cat="proto",
                        slave=conn.slave.id[:8], job=job_id[:8],
                        trace=self.trace_id[:8])
        self._send(conn.writer, {"type": "job", "job_id": job_id},
                   payload=data, conn=conn)

    async def _apply_update(self, conn, msg, payload):
        update = unpack_payload(payload, msg.get("codec", "none"))
        job_id = msg.get("job_id")
        started = conn.jobs_out.pop(job_id, None)
        if started is not None:
            elapsed = time.perf_counter() - started
            conn.job_times.append(elapsed)
            self._all_job_times.append(elapsed)
        # numerics quarantine (docs/health.md): a NaN payload merged
        # into global state poisons every other slave's next job.
        # Validation + apply run in ONE executor hop; workflows whose
        # updates are control-plane records only (the SPMD split,
        # update_validation == "inline") validate each part DURING the
        # apply's own traversal — one payload walk — while legacy
        # delta-shipping workflows keep the all-or-nothing prewalk.
        _tracer.instant("proto.update_in", cat="proto",
                        slave=conn.slave.id[:8],
                        job=str(job_id or "")[:8],
                        trace=self.trace_id[:8])

        def check_and_apply():
            inline = getattr(self.workflow, "apply_update_validated",
                             None)
            if inline is not None and getattr(
                    self.workflow, "update_validation",
                    "prewalk") == "inline":
                try:
                    return inline(update, conn.slave)
                except health.PoisonedUpdate:
                    return Server._POISONED
            if not health.all_finite(update):
                return Server._POISONED
            return self.workflow.apply_data_from_slave(
                update, conn.slave)

        try:
            result = await self._in_thread(check_and_apply)
        except Exception:
            self.exception("update application failed")
            self._send(conn.writer, {"type": "update_ack", "result": 0})
            result = Server._FAILED
        if result is Server._POISONED:
            self.quarantined += 1
            _registry.counter("server.quarantined").inc()
            _tracer.instant("proto.quarantine", cat="proto",
                            slave=conn.slave.id[:8], mid=conn.slave.mid)
            self._blacklist(conn.slave.mid)
            self.warning(
                "quarantining slave %s (mid %s): non-finite update "
                "payload dropped, blacklisted for %.0fs",
                conn.slave.id[:8], conn.slave.mid, self.blacklist_ttl)
            # black-box dump: the quarantine decision plus the ring of
            # spans/heartbeats leading up to it, loadable post-mortem
            _flight.dump(reason="quarantine")
            self._send(conn.writer, {"type": "update_ack", "result": 0})
            self._drop(conn, "poisoned update")
            try:
                conn.writer.close()
            except Exception:
                pass
            return
        if result is not Server._FAILED:
            # a None return is a LEGAL apply (the IDistributable
            # contract declares no return value) — count and ack it
            # exactly like the pre-demotion code did
            self.updates_applied += 1
            _registry.counter("server.updates_applied").inc()
            # a productive update resets the slave's respawn backoff
            self._respawn_attempts.pop(conn.slave.mid, None)
            self._send(conn.writer, {"type": "update_ack",
                                     "result": 1 if result else 0})
        if self._finishing:
            self._broadcast_stop()
            return
        # updates may unlock parked requesters (sync point release)
        if not self._paused:
            await self._release_parked()

    async def _release_parked(self):
        while self._waiting and not self._paused:
            parked = self._waiting.popleft()
            if parked.slave.id in self.slaves and parked.parked:
                parked.parked = False
                await self._serve_job(parked)

    async def _watchdog(self):
        """Adaptive per-slave job timeout -> drop + blacklist; also
        the periodic parked-requester retry."""
        while True:
            await asyncio.sleep(0.5)
            # clients park PASSIVELY on 'wait' (no re-poll: a client-
            # side poll double-serves against the update-driven
            # release and grows per-connection backlogs without
            # bound).  Updates release parked requesters immediately;
            # this tick covers the update-free cases — work freed by a
            # dropped slave's requeue and stragglers crossing the
            # speculation threshold
            if not self._paused:
                await self._release_parked()
            threshold = self._timeout_threshold()
            now = time.perf_counter()
            for conn in list(self.slaves.values()):
                overdue = [jid for jid, t0 in conn.jobs_out.items()
                           if now - t0 > threshold]
                if overdue:
                    self.warning(
                        "slave %s exceeded %.1fs timeout; dropping + "
                        "blacklisting for %.0fs", conn.slave.id[:8],
                        threshold, self.blacklist_ttl)
                    self._blacklist(conn.slave.mid)
                    self._drop(conn, "timeout")
                    try:
                        conn.writer.close()
                    except Exception:
                        pass

    def _timeout_threshold(self):
        # numpy is imported at module scope: this runs every 0.5 s on
        # the watchdog tick, and repeated `import` statements still pay
        # a sys.modules lookup + lock on a hot loop
        times = list(self._all_job_times)
        if len(times) < 4:
            return self.job_timeout
        arr = numpy.array(times)
        return max(float(arr.mean() + 3 * arr.std()), self.job_timeout)

    def _respawn_delay(self, mid):
        """Exponential backoff on THIS slave's consecutive respawns
        (reset by a productive update) — keying it on global blacklist
        size punished healthy slaves for unrelated machines' sins."""
        attempts = self._respawn_attempts.get(mid, 0) + 1
        self._respawn_attempts[mid] = attempts
        return min(2.0 ** attempts, 30.0)

    def _drop(self, conn, reason):
        if self.slaves.pop(conn.slave.id, None) is None:
            return
        conn.close_shm()
        self.info("dropping slave %s (%s)", conn.slave.id[:8], reason)
        try:
            self.workflow.drop_slave(conn.slave)
        except Exception:
            self.exception("drop_slave failed")
        # the requeue may have freed work for parked requesters; with
        # passive clients nobody else would wake them until the next
        # update (which, with every other slave parked, never comes)
        if not self._paused:
            self._loop.call_soon_threadsafe(
                lambda: asyncio.ensure_future(self._release_parked()))
        if self.respawn_hook is not None and not self._finishing:
            delay = self._respawn_delay(conn.slave.mid)
            self._loop.call_later(
                delay, lambda: self.respawn_hook(conn.slave))

    def _broadcast(self, msg):
        for conn in list(self.slaves.values()):
            try:
                self._send(conn.writer, msg)
            except Exception:
                pass

    def _broadcast_stop(self):
        self._broadcast({"type": "stop"})

    _NO_PAYLOAD = object()

    def _send(self, writer, msg, payload=_NO_PAYLOAD, conn=None):
        if payload is not Server._NO_PAYLOAD:
            msg = dict(msg, codec=self.codec)
            raw = pack_payload(payload, self.codec)
            if conn is not None and conn.shm_out is not None:
                desc = conn.shm_out.write(raw)
                if desc is not None:
                    msg["shm"] = list(desc)
                    self.shm_sends += 1
                    raw = b""
        else:
            raw = b""
        write_frame(writer, msg, raw, self.secret, peer="master")

    async def _in_thread(self, fn, *args):
        return await self._loop.run_in_executor(None, fn, *args)
