"""Master-side control plane: job farming with elastic-failure semantics.

TPU-native counterpart of reference veles/server.py:659.  Since the
SPMD split (docs/distributed.md) this plane is deliberately DEMOTED to
what it is uniquely good at — membership, elasticity, quarantine, and
checkpoint coordination: per-step gradients ride ICI inside the
compiled shard_map step (parallel/bucketed.py), never this protocol.
Update payloads are control records, which is what lets the master
validate them with the single-traversal inline walk
(``Workflow.apply_update_validated``) instead of a separate
whole-payload prewalk.

Preserved capabilities (SURVEY.md section 2.6/5):

- handshake validating the workflow CHECKSUM, slave id assignment;
- per-slave state tracking (the reference's fysom FSM collapses to a
  dict of outstanding jobs — asyncio replaces Twisted);
- job generation / update application deferred to a worker thread so the
  event loop never blocks on workflow code;
- sync points: a loader that answers "not ready" (False) parks the
  requester until the next update lands;
- ADAPTIVE TIMEOUT: a slave whose job takes longer than
  max(mean + 3 sigma of all job times, job_timeout) is dropped and
  BLACKLISTED (reference server.py:619-635);
- drop_slave -> workflow.drop_slave -> loaders requeue the minibatches;
- respawn hook with exponential backoff (the reference respawned over
  SSH; on TPU clusters process lifecycle belongs to the scheduler, so
  the hook takes a user callable).

Elastic-fleet semantics (docs/distributed.md, "Elasticity contract"):

- MEMBERSHIP EPOCHS: every join/leave/quarantine bumps
  ``fleet.membership_epoch`` (veles_tpu/elastic.py) and repartitions
  the epoch's unserved remainder over the live fleet (power-weighted),
  pushing ``reshard`` frames so slaves learn the new split without a
  restart; an ``elastic.resharded`` instant records each change.
- EXACTLY-ONCE updates: a dropped slave's work is requeued at drop
  time, so its late in-flight update is rejected (``stale``) instead
  of applied — never both.  The requeue itself is DEFERRED while one
  of the slave's updates is mid-apply on the executor, closing the
  drop-vs-apply race (the same job must not requeue AND apply).
- SPECULATIVE BACKUP DISPATCH: jobfarm's job-stamp/backup-copy logic,
  lifted here — an idle requester at the sync point shadows the
  oldest straggling in-flight job (power-aware threshold,
  ``elastic.speculation_threshold``); the first result wins and the
  loser's duplicate is dropped before validation ever runs.
"""

import asyncio
import math
import threading
import time
from collections import deque

import numpy

from veles_tpu import chaos, elastic, health
from veles_tpu.cmdline import CommandLineArgumentsRegistry
from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.observe.cluster import TraceCollector
from veles_tpu.observe.timeseries import FleetTelemetry
from veles_tpu.observe.flight import flight as _flight
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.network_common import (
    ProtocolError, ShmChannel, available_codecs, default_secret,
    machine_id, new_id, pack_payload, parse_address, read_frame,
    unpack_payload, write_frame)

__all__ = ["Server", "SlaveDescription"]


class SlaveDescription(object):
    """What workflow code sees as ``slave`` in the data contract."""

    def __init__(self, sid, mid, pid, power):
        self.id = sid
        self.mid = mid
        self.pid = pid
        self.power = power

    def __repr__(self):
        return "<Slave %s power=%.1f>" % (self.id[:8], self.power)


class _SlaveConn(object):
    def __init__(self, slave, reader, writer):
        self.slave = slave
        self.reader = reader
        self.writer = writer
        self.jobs_out = {}          # job_id -> dispatch timestamp
        self.job_times = deque(maxlen=50)
        self.parked = False
        self.shm_out = None         # master -> slave payload channel
        self.shm_in = None          # slave -> master payload channel
        #: membership epoch this slave was admitted at (handshake)
        self.member_epoch = 0
        #: sample share from the last reshard push (None = unknown)
        self.share = None
        #: set by _drop: frames still in flight from this conn are
        #: STALE — its work was requeued, applying them would double
        self.dropped = False
        #: a generate_data_for_slave for this conn is in the executor:
        #: a reservation may exist that jobs_out does not show yet, so
        #: _speculate must not shadow this owner's in-flight job (the
        #: TOCTOU half of the single-reservation invariant)
        self.generating = False

    def close_shm(self):
        for chan in (self.shm_out, self.shm_in):
            if chan is not None:
                chan.close()
        self.shm_out = self.shm_in = None


class _InflightJob(object):
    """One dispatched-but-unapplied job: the stamp the speculation and
    exactly-once paths key on (the jobfarm's job-stamp logic, lifted).

    ``owner`` is the slave the workflow RESERVED the work for
    (``generate_data_for_slave``) — every copy's result applies under
    the owner's reservation so loader bookkeeping stays consistent;
    ``copies`` maps slave id -> dispatch stamp for the owner plus any
    speculative backups (first result wins, the rest are duplicates)."""

    __slots__ = ("job_id", "data", "owner", "copies")

    def __init__(self, job_id, data, owner, t0):
        self.job_id = job_id
        self.data = data
        self.owner = owner
        self.copies = {owner.id: t0}


class Server(Logger, metaclass=CommandLineArgumentsRegistry):
    """Serve a workflow's jobs to connecting slaves."""

    #: sentinel returned by the update validator when a payload failed
    #: the finiteness check (either mode) -> quarantine path
    _POISONED = object()
    #: sentinel for an apply that raised (already acked 0); distinct
    #: from a legal None return, which counts as a served update
    _FAILED = object()
    #: bound on the shutdown drain of in-flight applies (_main's
    #: teardown waits for apply bookkeeping, not forever on a wedged
    #: executor)
    APPLY_DRAIN_S = 10.0

    @classmethod
    def init_parser(cls, parser):
        parser.add_argument(
            "--job-timeout", type=float, default=None,
            help="base seconds before a slave's job is considered "
                 "stuck (the adaptive threshold never drops below it)")
        parser.add_argument(
            "--codec", default=None, choices=available_codecs(),
            help="wire payload codec")
        parser.add_argument(
            "--no-shm", action="store_true", default=None,
            help="disable the same-host shared-memory payload bypass")
        parser.add_argument(
            "--blacklist-ttl", type=float, default=None,
            help="seconds a dropped/quarantined slave stays "
                 "blacklisted before it may rejoin")
        parser.add_argument(
            "--speculation-factor", type=float, default=None,
            help="straggler bar: an in-flight job older than this "
                 "factor x the mean job duration is shadowed on an "
                 "idle slave (first result wins)")
        parser.add_argument(
            "--min-speculation-s", type=float, default=None,
            help="absolute floor (seconds) under the speculation "
                 "threshold, so millisecond-scale jobs don't "
                 "speculate their whole tail")
        return parser

    @classmethod
    def apply_args(cls, args):
        cfg = {}
        if getattr(args, "job_timeout", None) is not None:
            cfg["job_timeout"] = args.job_timeout
        if getattr(args, "codec", None) is not None:
            cfg["codec"] = args.codec
        if getattr(args, "no_shm", None):
            cfg["shm"] = False
        if getattr(args, "blacklist_ttl", None) is not None:
            cfg["blacklist_ttl"] = args.blacklist_ttl
        if getattr(args, "speculation_factor", None) is not None:
            cfg["speculation_factor"] = args.speculation_factor
        if getattr(args, "min_speculation_s", None) is not None:
            cfg["min_speculation_s"] = args.min_speculation_s
        root.common.network.update(cfg)

    def __init__(self, address, workflow, launcher=None, codec=None,
                 job_timeout=None, respawn_hook=None, secret=None,
                 use_shm=None, shm_size=None, blacklist_ttl=None,
                 speculation_factor=None, min_speculation_s=None):
        super(Server, self).__init__()
        net = root.common.network
        self.host, self.port = parse_address(address)
        self.workflow = workflow
        self.launcher = launcher
        self.codec = codec if codec is not None else net.get(
            "codec", "none")
        self.use_shm = use_shm if use_shm is not None else net.get(
            "shm", True)
        self.shm_size = shm_size if shm_size is not None else net.get(
            "shm_size", 1 << 24)
        self.shm_sends = 0
        self.job_timeout = job_timeout if job_timeout is not None \
            else net.get("job_timeout", 60.0)
        self.respawn_hook = respawn_hook
        self.secret = secret if secret is not None else default_secret()
        # mid -> expiry timestamp: blacklisting is a QUARANTINE with a
        # TTL, not a life sentence — a once-slow machine (or one that
        # sent one poisoned update) may rejoin after it expires
        self.blacklist_ttl = blacklist_ttl if blacklist_ttl is not None \
            else net.get("blacklist_ttl", 30.0)
        self.blacklist = {}
        #: per-slave consecutive respawn attempts (mid -> count); the
        #: respawn delay backs off on THIS, not on global blacklist
        #: size, and resets once the slave applies a productive update
        self._respawn_attempts = {}
        #: run-scoped trace id: propagated to every slave in the
        #: handshake ack so the whole job's spans — master and slave —
        #: stitch under ONE id in the merged cluster trace
        self.trace_id = new_id()
        #: shipped slave trace chunks + per-slave clock offsets
        #: (docs/observability.md, distributed tracing)
        self.trace_collector = TraceCollector()
        # master-side half of the fleet telemetry plane: per-slave
        # series chunks merged with the trace-merge clock offsets
        self.fleet_telemetry = FleetTelemetry()
        self.quarantined = 0
        self.slaves = {}
        self._waiting = deque()     # parked requesters (sync points)
        self._all_job_times = deque(maxlen=500)
        #: live-membership ledger: every join/leave bumps the
        #: membership epoch and triggers a reshard push
        self.fleet = elastic.FleetView()
        #: dispatched-but-unapplied jobs (job_id -> _InflightJob); the
        #: stamp speculation and the exactly-once duplicate drop key on.
        #: Workflows that run their OWN backup-copy bookkeeping (the
        #: jobfarm adapter dedups by result slot) set
        #: ``owns_speculation = True`` and opt out of both.
        self._inflight = {}
        self._workflow_speculates = bool(
            getattr(workflow, "owns_speculation", False))
        self.speculation_factor = speculation_factor \
            if speculation_factor is not None \
            else net.get("speculation_factor", 2.0)
        self.min_speculation_s = min_speculation_s \
            if min_speculation_s is not None \
            else net.get("min_speculation_s", 5.0)
        #: speculation_factor=inf is the off-switch (the threshold is
        #: infinite, nothing ever straggles past it); with it off the
        #: job stamps skip caching payloads — the stamp stays (the
        #: exactly-once duplicate/stale fences key on it) but the
        #: master no longer retains every in-flight job's payload
        self._speculation_on = math.isfinite(self.speculation_factor)
        #: updates currently mid-apply on the executor, keyed by the
        #: slave id the apply RETIRES A RESERVATION OF (the owner for
        #: speculated jobs, the sender otherwise).  _drop defers the
        #: requeue while that slave has an apply in flight — the
        #: drop-vs-apply race: the same job must not requeue AND
        #: apply.  Keying on the apply target (not the sender's conn)
        #: also covers dropping a straggling OWNER while its backup's
        #: winning update is mid-apply.
        self._applying = {}
        #: drops parked by _drop while an apply for that slave id is
        #: in flight: slave id -> (conn, reason); the apply path
        #: finishes them when the executor returns
        self._deferred_drops = {}
        #: optional parallel.mesh.MeshManager driving an elastic device
        #: mesh on this master: when set, reshard frames carry its
        #: ``mesh_epoch`` so slaves see which train-state layout their
        #: membership change produced
        self.mesh_manager = None
        # elastic-fleet accounting (mirrored into elastic.* metrics)
        self.reshards = 0
        self.speculated = 0
        self.duplicates_dropped = 0
        self.stale_updates = 0
        self.drops_deferred = 0
        self._loop = None
        self._server = None
        self._finishing = False
        self._paused = False
        self._stop_event = None
        self._done = threading.Event()
        self._listening = threading.Event()
        self.bind_error = None
        self.jobs_dispatched = 0
        self.updates_applied = 0

    # -- public lifecycle ---------------------------------------------------

    def run(self):
        """Blocking: serve until the workflow finishes."""
        asyncio.run(self._main())

    def start_background(self):
        thread = threading.Thread(target=self.run, daemon=True)
        thread.start()
        return thread

    def wait_listening(self, timeout=10.0):
        """Block until the socket accepts connections.  Returns True
        when listening; False on bind failure (see ``bind_error``) or
        timeout — a background server that failed to bind would
        otherwise die silently on its daemon thread."""
        if not self._listening.wait(timeout):
            return False
        return self.bind_error is None

    def on_workflow_finished(self):
        self._finishing = True
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._signal_stop)

    def stop(self):
        self.on_workflow_finished()

    def pause(self):
        """Park all slaves: broadcast 'pause'; job requests queue up
        server-side until resume() (reference server.py:734-745)."""
        if self._loop is None:
            self._paused = True
            return
        self._loop.call_soon_threadsafe(self._do_pause)

    def resume(self):
        if self._loop is None:
            self._paused = False
            return
        self._loop.call_soon_threadsafe(
            lambda: asyncio.ensure_future(self._do_resume()))

    @property
    def paused(self):
        return self._paused

    def _do_pause(self):
        self._paused = True
        self._broadcast({"type": "pause"})

    async def _do_resume(self):
        self._paused = False
        self._broadcast({"type": "resume"})
        await self._release_parked()

    # -- asyncio internals ---------------------------------------------------

    def _signal_stop(self):
        self._broadcast_stop()
        if self._stop_event is not None:
            self._stop_event.set()

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        if self._finishing:
            self._stop_event.set()
        try:
            self._server = await asyncio.start_server(
                self._handle_conn, self.host, self.port)
        except OSError as exc:
            # surface the failure to waiters (start_background callers
            # can only see it through bind_error) instead of dying
            # silently on a daemon thread
            self.bind_error = exc
            self.error("failed to bind %s:%s: %s", self.host,
                       self.port, exc)
            self._listening.set()
            self._done.set()
            return
        self.port = self._server.sockets[0].getsockname()[1]
        self._listening.set()
        if getattr(self.workflow, "restored_from_snapshot_", False):
            self.info(
                "master listening on %s:%d (restored from snapshot; "
                "re-admitting slaves at epoch %s)", self.host, self.port,
                getattr(getattr(self.workflow, "loader", None),
                        "epoch_number", "?"))
        else:
            self.info("master listening on %s:%d", self.host, self.port)
        watchdog = asyncio.ensure_future(self._watchdog())
        try:
            await self._stop_event.wait()
        finally:
            self._finishing = True
            watchdog.cancel()
            self._broadcast_stop()
            # Drain in-flight applies before tearing down.  A workflow
            # that completes INSIDE check_and_apply (decision latching
            # ``complete`` on the executor thread) schedules the stop
            # via call_soon_threadsafe BEFORE the executor future's own
            # continuation, so returning here would let asyncio.run
            # cancel the _apply_update coroutine mid-bookkeeping: the
            # weights already mutated but updates_applied / the ack /
            # deferred drops never ran (the kill-during-reshard
            # lost-update race).  The continuation from the executor
            # await through the counter bump has no awaits, so an empty
            # _applying map guarantees the bookkeeping finished.
            deadline = self._loop.time() + self.APPLY_DRAIN_S
            while self._applying and self._loop.time() < deadline:
                await asyncio.sleep(0.01)
            if self._applying:
                self.warning(
                    "shutdown drain timed out with %d apply(s) still "
                    "in flight", len(self._applying))
            for conn in list(self.slaves.values()):
                conn.close_shm()
            self._server.close()
            await self._server.wait_closed()
            self._done.set()

    async def _handle_conn(self, reader, writer):
        conn = None
        try:
            while True:
                msg, payload = await read_frame(reader, self.secret,
                                                peer="master")
                if conn is not None and conn.shm_in is not None \
                        and "shm" in msg:
                    off, length = msg["shm"]
                    payload = conn.shm_in.read(off, length)
                conn = await self._dispatch(
                    msg, payload, conn, reader, writer)
                if conn is None and msg.get("type") != "handshake":
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except ProtocolError as exc:
            self.warning("rejecting peer: %s", exc)
        except Exception:
            self.exception("connection handler failed")
        finally:
            if conn is not None:
                self._drop(conn, "disconnected")
            try:
                writer.close()
            except Exception:
                pass

    async def _dispatch(self, msg, payload, conn, reader, writer):
        mtype = msg.get("type")
        if mtype == "handshake":
            return await self._handshake(msg, reader, writer)
        if conn is None:
            self._send(writer, {"type": "error",
                                "reason": "handshake required"})
            return None
        if conn.dropped:
            # this membership is OVER: the slave's work was requeued at
            # drop time, so frames still buffered on the old session
            # must not act.  A late update is rejected as STALE (the
            # exactly-once half of the elasticity contract) and any
            # other traffic severs the conn — the slave reconnects and
            # rejoins at a fresh membership epoch.
            if mtype == "update":
                self._reject_stale(conn, msg)
            else:
                try:
                    conn.writer.close()
                except Exception:
                    pass
            return conn
        if mtype == "job_request":
            await self._serve_job(conn)
        elif mtype == "update":
            await self._apply_update(conn, msg, payload)
        elif mtype == "clock_probe":
            # NTP-style offset handshake (observe/cluster.py): answer
            # IN the event loop — a thread hop here would inflate the
            # apparent one-way delay the estimate divides by
            now = time.time()
            self._send(conn.writer, {
                "type": "clock_probe_ack", "t0": msg.get("t0"),
                "t1": now, "t2": now})
        elif mtype == "clock_report":
            offset = msg.get("offset")
            if isinstance(offset, (int, float)):
                # the client reports "server ahead by offset", i.e.
                # slave_wall + offset = master_wall — exactly the
                # additive correction merge_parts applies
                self.trace_collector.set_offset(
                    conn.slave.mid, float(offset), msg.get("delay"))
                # the fleet telemetry merge corrects with the SAME
                # estimate trace merging uses: "slave:" prefix matches
                # the label series chunks arrive under
                self.fleet_telemetry.set_offset(
                    "slave:" + conn.slave.mid, float(offset),
                    msg.get("delay"))
                self.debug("slave %s clock offset %.6fs (delay %.6fs)",
                           conn.slave.id[:8], offset,
                           msg.get("delay") or -1.0)
        elif mtype == "trace_chunk":
            try:
                chunk = unpack_payload(payload, msg.get("codec", "none"))
            except Exception as exc:
                self.warning("undecodable trace chunk from slave %s "
                             "dropped (%s: %s)", conn.slave.id[:8],
                             type(exc).__name__, exc)
            else:
                self.trace_collector.add_chunk(conn.slave.mid, chunk)
        elif mtype == "series_chunk":
            # telemetry buckets ride the same inline path as trace
            # chunks and get the same validate-and-drop discipline: a
            # malformed chunk costs the chunk, never the session
            try:
                chunk = unpack_payload(payload, msg.get("codec", "none"))
            except Exception as exc:
                self.warning("undecodable series chunk from slave %s "
                             "dropped (%s: %s)", conn.slave.id[:8],
                             type(exc).__name__, exc)
            else:
                self.fleet_telemetry.add_chunk(
                    "slave:" + conn.slave.mid, chunk)
        return conn

    def _blacklist(self, mid):
        self.blacklist[mid] = time.time() + self.blacklist_ttl
        _registry.gauge("server.blacklist_size").set(len(self.blacklist))

    def _blacklisted(self, mid):
        """True while ``mid``'s quarantine TTL has not expired; expired
        entries are dropped on the way (the slave may rejoin)."""
        expiry = self.blacklist.get(mid)
        if expiry is None:
            return False
        if time.time() >= expiry:
            del self.blacklist[mid]
            _registry.gauge("server.blacklist_size").set(
                len(self.blacklist))
            self.info("blacklist TTL expired for slave %s; eligible "
                      "to rejoin", mid)
            return False
        return True

    async def _handshake(self, msg, reader, writer):
        if self._finishing:
            # a join racing shutdown must not allocate per-slave
            # resources (shm segments): the event loop may be torn
            # down before this handler's cleanup path ever runs,
            # leaking the segments past process exit
            self._send(writer, {"type": "stop"})
            return None
        checksum = msg.get("checksum")
        mid = msg.get("mid", "?")
        if checksum != self.workflow.checksum:
            self.warning("rejecting slave %s: checksum mismatch", mid)
            self._send(writer, {"type": "reject",
                                "reason": "checksum mismatch"})
            return None
        if self._blacklisted(mid):
            retry_after = max(self.blacklist[mid] - time.time(), 0.0)
            self.warning("rejecting blacklisted slave %s (%.1fs left)",
                         mid, retry_after)
            # retry_after marks the rejection TRANSIENT: the client
            # sleeps it out and retries instead of giving up for good
            self._send(writer, {"type": "reject",
                                "reason": "blacklisted",
                                "retry_after": retry_after})
            return None
        sid = new_id()
        slave = SlaveDescription(sid, mid, msg.get("pid", 0),
                                 msg.get("power", 1.0))
        conn = _SlaveConn(slave, reader, writer)
        # the run's trace id rides the protocol header: every span or
        # chunk the slave records correlates back to THIS master run
        ack = {"type": "handshake_ack", "id": sid,
               "trace": self.trace_id}
        epoch = getattr(getattr(self.workflow, "loader", None),
                        "epoch_number", None)
        if epoch is not None:
            # a slave (re)joining a restarted master learns the epoch
            # it is being admitted at — resume observability
            ack["epoch"] = int(epoch)
        if self.use_shm and msg.get("machine") == machine_id():
            # same host: payloads ride shared memory, not the socket
            # (reference SharedIO engagement, server.py:144-167)
            try:
                conn.shm_out = ShmChannel.create(self.shm_size)
                conn.shm_in = ShmChannel.create(self.shm_size)
                ack["shm"] = {"m2s": conn.shm_out.name,
                              "s2m": conn.shm_in.name}
                self.info("slave %s is local: shm payload bypass on",
                          sid[:8])
            except Exception:
                self.exception("shm setup failed; staying on socket")
                conn.close_shm()
        self.slaves[sid] = conn
        # membership epoch: the join bumps it, the ack teaches the
        # slave which epoch it was admitted at, and the reshard below
        # republishes the unserved split over the grown fleet
        conn.member_epoch = self.fleet.join(sid, slave.power)
        ack["member_epoch"] = conn.member_epoch
        initial = await self._in_thread(
            self.workflow.generate_initial_data_for_slave, slave)
        self._send(writer, ack, payload=initial)
        if self._paused:
            self._send(writer, {"type": "pause"})
        self.info("slave %s connected (mid %s; membership epoch %d)",
                  sid[:8], mid, conn.member_epoch)
        self._reshard("join")
        return conn

    async def _serve_job(self, conn):
        if self._finishing:
            self._send(conn.writer, {"type": "stop"})
            return
        if self._paused:
            # parked until resume(); no reply — the slave already got
            # 'pause' and is not busy-waiting
            conn.parked = True
            self._waiting.append(conn)
            return
        if not self._workflow_speculates and (
                self._applying.get(conn.slave.id) or any(
                    len(job.copies) > 1
                    and job.owner.id == conn.slave.id
                    for job in self._inflight.values())):
            # an async (pipelining) owner asking for MORE work while
            # one of its jobs is speculated — or while a result is
            # mid-apply under its id (the backup's winning copy, once
            # it lands, applies under the OWNER's reservation): serving
            # it would open a second reservation under this owner and
            # the in-flight apply would retire the WRONG one (the
            # loader pops reservations LIFO per slave).  Park until
            # the speculated job resolves; both the apply and drop
            # paths release parked requesters.
            conn.parked = True
            self._waiting.append(conn)
            self._send(conn.writer, {"type": "wait"})
            return
        # fence the guard until the dispatch is stamped: the
        # reservation generate_data_for_slave creates is invisible to
        # jobs_out until conn.jobs_out is updated below (the executor
        # hop, and the chaos stall point, both yield the loop), and a
        # peer speculating this owner's job in that window would cross
        # the two reservations
        conn.generating = True
        try:
            data = await self._in_thread(
                self.workflow.generate_data_for_slave, conn.slave)
            if data is False:
                # nothing fresh: maybe shadow a straggler's in-flight
                # job (the jobfarm's backup-copy move, lifted here —
                # first result wins, the loser is dropped before
                # validation)
                conn.generating = False
                if self._speculate(conn):
                    return
                # sync point: park until an update unlocks new work
                conn.parked = True
                self._waiting.append(conn)
                self._send(conn.writer, {"type": "wait"})
                return
            if chaos.plan is not None:
                fault = chaos.plan.fire("server.serve")
                if fault is not None:
                    if fault.action == "kill":
                        # mid-batch conn death: the minibatch is
                        # already reserved to this slave, so the drop
                        # path MUST requeue it (watchdog/drop_slave
                        # contract)
                        self.warning("fault injection: killing conn "
                                     "of slave %s mid-batch",
                                     conn.slave.id[:8])
                        conn.writer.close()
                        return
                    if fault.action == "stall":
                        await asyncio.sleep(fault.param or 0.5)
            job_id = new_id()
            # perf_counter, not time.time: these stamps feed the
            # adaptive timeout and the job-latency stats, and a
            # wall-clock NTP step would fake a straggler (or hide one)
            t0 = time.perf_counter()
            conn.jobs_out[job_id] = t0
            if not self._workflow_speculates:
                # the job stamp: speculation re-serves this exact
                # payload and the exactly-once drop rejects the losing
                # duplicate
                self._inflight[job_id] = _InflightJob(
                    job_id, data if self._speculation_on else None,
                    conn.slave, t0)
        finally:
            conn.generating = False
        self.jobs_dispatched += 1
        _registry.counter("server.jobs_dispatched").inc()
        _tracer.instant("proto.job_out", cat="proto",
                        slave=conn.slave.id[:8], job=job_id[:8],
                        trace=self.trace_id[:8])
        self._send(conn.writer, {"type": "job", "job_id": job_id},
                   payload=data, conn=conn)

    def _speculate(self, conn):
        """Try to shadow the oldest straggling in-flight job on the
        idle requester ``conn``.  Returns True when a backup copy was
        dispatched.  The bar is the power-corrected MapReduce backup
        threshold (``elastic.speculation_threshold``); with no
        completed durations there is no credible mean and nothing
        speculates (immediate re-issue would duplicate every tail
        job).  A job is only eligible while its owner has no OTHER
        job outstanding: the loader contract pops reservations LIFO
        per slave, so shadowing one of several pipelined jobs could
        retire the wrong reservation."""
        if self._workflow_speculates or not self._speculation_on \
                or not self._all_job_times:
            return False
        mean = sum(self._all_job_times) / len(self._all_job_times)
        mean_power = elastic.fleet_mean_power(self.fleet.powers())
        now = time.perf_counter()
        best, best_age = None, 0.0
        for job in self._inflight.values():
            if conn.slave.id in job.copies:
                continue  # never a second copy on the same slave
            owner_conn = self.slaves.get(job.owner.id)
            if owner_conn is None:
                # departed owner: its stamp is about to be deleted and
                # its work requeued by the (possibly deferred) drop —
                # a backup copy would be guaranteed duplicate work
                continue
            if owner_conn.generating or len(owner_conn.jobs_out) > 1:
                continue
            age = now - min(job.copies.values())
            threshold = elastic.speculation_threshold(
                mean, self.speculation_factor, self.min_speculation_s,
                owner_power=job.owner.power, mean_power=mean_power)
            if age > threshold and age > best_age:
                best, best_age = job, age
        if best is None:
            return False
        best.copies[conn.slave.id] = now
        conn.jobs_out[best.job_id] = now
        self.speculated += 1
        # a backup copy is a dispatch like any other: count it and
        # emit the proto.job_out instant so the merged cluster trace
        # can pair the winner's proto.update_in with a dispatch event
        self.jobs_dispatched += 1
        _registry.counter("server.jobs_dispatched").inc()
        _tracer.instant("proto.job_out", cat="proto",
                        slave=conn.slave.id[:8], job=best.job_id[:8],
                        trace=self.trace_id[:8])
        _registry.counter("elastic.speculative_jobs").inc()
        _registry.gauge("elastic.speculative_inflight").set(
            self._speculative_inflight())
        _tracer.instant("elastic.speculate", cat="elastic",
                        job=best.job_id[:8], owner=best.owner.id[:8],
                        backup=conn.slave.id[:8],
                        age_s=round(best_age, 3))
        self.info("speculating job %s of straggler %s on idle slave "
                  "%s (%.2fs in flight)", best.job_id[:8],
                  best.owner.id[:8], conn.slave.id[:8], best_age)
        self._send(conn.writer, {"type": "job", "job_id": best.job_id},
                   payload=best.data, conn=conn)
        return True

    def _speculative_inflight(self):
        return sum(1 for job in self._inflight.values()
                   if len(job.copies) > 1)

    def _reject_stale(self, conn, msg):
        """Reject an update from a DEPARTED member: its work was
        requeued at drop time (membership epoch bumped past its
        admission), so applying the late duplicate would double."""
        job_id = str(msg.get("job_id") or "")[:8]
        self.stale_updates += 1
        _registry.counter("elastic.stale_updates").inc()
        _tracer.instant("elastic.stale_update", cat="elastic",
                        slave=conn.slave.id[:8], job=job_id,
                        member_epoch=conn.member_epoch,
                        fleet_epoch=self.fleet.membership_epoch)
        self.warning(
            "rejecting stale update (job %s) from departed slave %s: "
            "admitted at membership epoch %d, fleet is at %d — its "
            "work was requeued at drop time", job_id,
            conn.slave.id[:8], conn.member_epoch,
            self.fleet.membership_epoch)
        try:
            self._send(conn.writer, {"type": "update_ack", "result": 0})
        except Exception:
            pass

    async def _apply_update(self, conn, msg, payload):
        update = unpack_payload(payload, msg.get("codec", "none"))
        job_id = msg.get("job_id")
        started = conn.jobs_out.pop(job_id, None)
        if started is not None:
            elapsed = time.perf_counter() - started
            conn.job_times.append(elapsed)
            self._all_job_times.append(elapsed)
        # first result wins: pop the job stamp — a second copy of the
        # same job (speculation loser, or a backup finishing after its
        # owner was requeued) finds it gone and is dropped BEFORE
        # validation or apply ever run
        inflight = self._inflight.pop(job_id, None) \
            if job_id is not None else None
        if not self._workflow_speculates and job_id is not None \
                and inflight is None:
            self.duplicates_dropped += 1
            _registry.counter("elastic.duplicates_dropped").inc()
            _registry.gauge("elastic.speculative_inflight").set(
                self._speculative_inflight())
            _tracer.instant("elastic.duplicate_drop", cat="elastic",
                            slave=conn.slave.id[:8],
                            job=str(job_id)[:8])
            self.info("dropping duplicate update for job %s from "
                      "slave %s (another copy won)", str(job_id)[:8],
                      conn.slave.id[:8])
            self._send(conn.writer, {"type": "update_ack", "result": 0})
            if not self._paused:
                await self._release_parked()
            return
        # every copy's result applies under the OWNER's reservation:
        # the loader keyed the minibatch to the slave it generated the
        # job for, and a speculative winner must retire that exact
        # reservation, not open a phantom one of its own
        apply_slave = inflight.owner if inflight is not None \
            else conn.slave
        if inflight is not None and len(inflight.copies) > 1:
            # a speculated job just resolved (this copy won)
            _registry.gauge("elastic.speculative_inflight").set(
                self._speculative_inflight())
        # numerics quarantine (docs/health.md): a NaN payload merged
        # into global state poisons every other slave's next job.
        # Validation + apply run in ONE executor hop; workflows whose
        # updates are control-plane records only (the SPMD split,
        # update_validation == "inline") validate each part DURING the
        # apply's own traversal — one payload walk — while legacy
        # delta-shipping workflows keep the all-or-nothing prewalk.
        _tracer.instant("proto.update_in", cat="proto",
                        slave=conn.slave.id[:8],
                        job=str(job_id or "")[:8],
                        trace=self.trace_id[:8])

        def check_and_apply():
            inline = getattr(self.workflow, "apply_update_validated",
                             None)
            if inline is not None and getattr(
                    self.workflow, "update_validation",
                    "prewalk") == "inline":
                try:
                    return inline(update, apply_slave)
                except health.PoisonedUpdate:
                    return Server._POISONED
            if not health.all_finite(update):
                return Server._POISONED
            return self.workflow.apply_data_from_slave(
                update, apply_slave)

        apply_sid = apply_slave.id
        self._applying[apply_sid] = self._applying.get(apply_sid, 0) + 1
        try:
            result = await self._in_thread(check_and_apply)
        except Exception:
            self.exception("update application failed")
            self._send(conn.writer, {"type": "update_ack", "result": 0})
            result = Server._FAILED
        finally:
            left = self._applying.get(apply_sid, 1) - 1
            if left:
                self._applying[apply_sid] = left
            else:
                self._applying.pop(apply_sid, None)
                deferred = self._deferred_drops.pop(apply_sid, None)
                if deferred is not None:
                    # the drop that raced this apply: now that the
                    # update is fully applied (or failed), requeue
                    # what is STILL outstanding — never the job that
                    # just applied
                    self._finish_drop(*deferred)
        if result is Server._POISONED and inflight is not None \
                and conn.slave.id != inflight.owner.id \
                and len(inflight.copies) > 1 \
                and inflight.owner.id in self.slaves:
            # a poisoned SPECULATIVE backup must not lose the job: the
            # owner's copy is still running, so reinstate the stamp
            # (minus the poisoned sender) and let the owner's result
            # apply normally.  NOT when the owner itself was dropped
            # while this apply was in flight — its reservation was
            # already requeued by the (deferred) drop, so reinstating
            # would leave a phantom job racing the requeued minibatch
            inflight.copies.pop(conn.slave.id, None)
            self._inflight[inflight.job_id] = inflight
        if result is Server._FAILED and inflight is not None \
                and len(inflight.copies) > 1 \
                and inflight.owner.id in self.slaves:
            # a transient master-side apply failure must not orphan a
            # SPECULATED job: the other copy is still running, so
            # reinstate the stamp (minus the failed sender — owner or
            # backup) and let the surviving copy's result apply under
            # the owner's reservation instead of dropping as a
            # duplicate — exactly-once in the applied-zero-times
            # direction.  Same departed-owner exclusion as above.
            inflight.copies.pop(conn.slave.id, None)
            if inflight.copies:
                self._inflight[inflight.job_id] = inflight
        if result is Server._POISONED:
            self.quarantined += 1
            _registry.counter("server.quarantined").inc()
            _tracer.instant("proto.quarantine", cat="proto",
                            slave=conn.slave.id[:8], mid=conn.slave.mid)
            self._blacklist(conn.slave.mid)
            self.warning(
                "quarantining slave %s (mid %s): non-finite update "
                "payload dropped, blacklisted for %.0fs",
                conn.slave.id[:8], conn.slave.mid, self.blacklist_ttl)
            # black-box dump: the quarantine decision plus the ring of
            # spans/heartbeats leading up to it, loadable post-mortem
            _flight.dump(reason="quarantine")
            self._send(conn.writer, {"type": "update_ack", "result": 0})
            self._drop(conn, "poisoned update")
            try:
                conn.writer.close()
            except Exception:
                pass
            return
        if result is not Server._FAILED:
            # a None return is a LEGAL apply (the IDistributable
            # contract declares no return value) — count and ack it
            # exactly like the pre-demotion code did
            self.updates_applied += 1
            _registry.counter("server.updates_applied").inc()
            # a productive update resets the slave's respawn backoff
            self._respawn_attempts.pop(conn.slave.mid, None)
            self._send(conn.writer, {"type": "update_ack",
                                     "result": 1 if result else 0})
        if self._finishing:
            self._broadcast_stop()
            return
        # updates may unlock parked requesters (sync point release)
        if not self._paused:
            await self._release_parked()

    async def _release_parked(self):
        # one attempt per parked conn per pass: _serve_job may RE-park
        # the conn it was handed (speculation not yet eligible, or the
        # owner guard), and an unbounded `while self._waiting` would
        # pop the re-appended conn forever — a livelock that starves
        # the event loop of every other conn's frames (including the
        # very update whose apply would release the guard)
        for _ in range(len(self._waiting)):
            if self._paused:
                break
            parked = self._waiting.popleft()
            if parked.slave.id in self.slaves and parked.parked:
                parked.parked = False
                await self._serve_job(parked)

    async def _watchdog(self):
        """Adaptive per-slave job timeout -> drop + blacklist; also
        the periodic parked-requester retry."""
        while True:
            await asyncio.sleep(0.5)
            # clients park PASSIVELY on 'wait' (no re-poll: a client-
            # side poll double-serves against the update-driven
            # release and grows per-connection backlogs without
            # bound).  Updates release parked requesters immediately;
            # this tick covers the update-free cases — work freed by a
            # dropped slave's requeue and stragglers crossing the
            # speculation threshold
            if not self._paused:
                await self._release_parked()
            threshold = self._timeout_threshold()
            now = time.perf_counter()
            for conn in list(self.slaves.values()):
                overdue = [jid for jid, t0 in conn.jobs_out.items()
                           if now - t0 > threshold]
                if overdue:
                    self.warning(
                        "slave %s exceeded %.1fs timeout; dropping + "
                        "blacklisting for %.0fs", conn.slave.id[:8],
                        threshold, self.blacklist_ttl)
                    self._blacklist(conn.slave.mid)
                    self._drop(conn, "timeout")
                    try:
                        conn.writer.close()
                    except Exception:
                        pass

    def _timeout_threshold(self):
        # numpy is imported at module scope: this runs every 0.5 s on
        # the watchdog tick, and repeated `import` statements still pay
        # a sys.modules lookup + lock on a hot loop
        times = list(self._all_job_times)
        if len(times) < 4:
            return self.job_timeout
        arr = numpy.array(times)
        return max(float(arr.mean() + 3 * arr.std()), self.job_timeout)

    def _respawn_delay(self, mid):
        """Exponential backoff on THIS slave's consecutive respawns
        (reset by a productive update) — keying it on global blacklist
        size punished healthy slaves for unrelated machines' sins."""
        attempts = self._respawn_attempts.get(mid, 0) + 1
        self._respawn_attempts[mid] = attempts
        return min(2.0 ** attempts, 30.0)

    def _drop(self, conn, reason):
        if self.slaves.pop(conn.slave.id, None) is None:
            return
        conn.dropped = True
        self.fleet.leave(conn.slave.id)
        conn.close_shm()
        self.info("dropping slave %s (%s)", conn.slave.id[:8], reason)
        if self._applying.get(conn.slave.id):
            # drop-vs-apply race: an update that retires one of THIS
            # slave's reservations is mid-apply on the executor — its
            # own update, or a speculative backup's winning result
            # applying under this owner's reservation.  Requeueing now
            # would hand the applying job to another slave while its
            # update lands — the job both requeued AND applied.  Park
            # the requeue; the apply path finishes the drop the moment
            # the executor returns (stale rejection above already
            # fences any FURTHER frames from this conn).
            self.drops_deferred += 1
            _registry.counter("elastic.drops_deferred").inc()
            self.debug("deferring requeue for slave %s: an update is "
                       "mid-apply", conn.slave.id[:8])
            self._deferred_drops[conn.slave.id] = (conn, reason)
            return
        self._finish_drop(conn, reason)

    def _finish_drop(self, conn, reason):
        # retire this conn's job stamps: jobs it OWNED are requeued by
        # drop_slave below, so a backup copy's late result must drop
        # as a duplicate; jobs it merely backed keep the owner's copy
        for job_id, job in list(self._inflight.items()):
            job.copies.pop(conn.slave.id, None)
            if job.owner.id == conn.slave.id:
                del self._inflight[job_id]
        _registry.gauge("elastic.speculative_inflight").set(
            self._speculative_inflight())
        try:
            self.workflow.drop_slave(conn.slave)
        except Exception:
            self.exception("drop_slave failed")
        if not self._finishing:
            self._reshard("leave:" + reason)
        # the requeue may have freed work for parked requesters; with
        # passive clients nobody else would wake them until the next
        # update (which, with every other slave parked, never comes)
        if not self._paused:
            self._loop.call_soon_threadsafe(
                lambda: asyncio.ensure_future(self._release_parked()))
        if self.respawn_hook is not None and not self._finishing:
            delay = self._respawn_delay(conn.slave.mid)
            self._loop.call_later(
                delay, lambda: self.respawn_hook(conn.slave))

    # -- dynamic resharding -------------------------------------------------

    def _unserved_remainder(self):
        """How many work units of the current epoch are not yet
        APPLIED — the quantity a reshard repartitions.  Workflows may
        expose ``unserved_remainder()`` (the Workflow/Loader contract
        and the jobfarm adapter do); None = unknown, nothing to
        partition."""
        probe = getattr(self.workflow, "unserved_remainder", None)
        if probe is None:
            return None
        try:
            remaining = probe()
        except Exception:
            self.exception("unserved_remainder probe failed")
            return None
        return None if remaining is None else int(remaining)

    def _reshard(self, reason):
        """Membership changed: repartition the epoch's unserved
        remainder over the live fleet (power-weighted, exact largest-
        remainder split) and push each slave its new share, stamped
        with the membership epoch, so the fleet learns the split
        without restarting the run.

        The push is scheduled, not inline: the remainder probe is
        workflow code (the jobfarm adapter takes its master lock, user
        workflows run arbitrary counting), and this module's contract
        keeps workflow code off the event loop — a slow probe on every
        membership change would stall every connection.  Epoch and
        shares are read when the task runs, so back-to-back membership
        changes push the (identical) final split — idempotent for the
        absorbing client."""
        asyncio.ensure_future(self._do_reshard(reason))

    async def _do_reshard(self, reason):
        if self._finishing:
            return
        remaining = await self._in_thread(self._unserved_remainder)
        shares = self.fleet.shares(remaining)
        epoch = self.fleet.membership_epoch
        self.reshards += 1
        _registry.counter("elastic.reshards").inc()
        _registry.gauge("elastic.membership_epoch").set(epoch)
        _registry.gauge("elastic.fleet_live").set(len(self.fleet))
        _tracer.instant(
            "elastic.resharded", cat="elastic", reason=reason,
            epoch=epoch, fleet=len(self.fleet),
            remaining=-1 if remaining is None else remaining)
        self.info("resharded (%s): membership epoch %d, %d live, "
                  "remainder %s -> %s", reason, epoch, len(self.fleet),
                  remaining, {sid[:8]: n for sid, n in shares.items()}
                  or "n/a")
        for sid, member in list(self.slaves.items()):
            if chaos.plan is not None:
                fault = chaos.plan.fire("server.reshard")
                if fault is not None and fault.action == "kill":
                    # a slave vanishing DURING the reshard push: the
                    # kill-during-reshard case the exactly-once
                    # guarantee must survive (its work requeues, its
                    # late update is stale)
                    self.warning("fault injection: killing conn of "
                                 "slave %s mid-reshard", sid[:8])
                    try:
                        member.writer.close()
                    except Exception:
                        pass
                    continue
            member.share = shares.get(sid)
            msg = {"type": "reshard", "epoch": epoch,
                   "fleet": len(self.fleet)}
            # a master training on an elastic device mesh
            # (parallel.mesh.MeshManager) stamps its device-mesh epoch
            # so slaves can correlate membership churn with the
            # train-state reshard that followed it
            mesh_epoch = getattr(self.mesh_manager, "mesh_epoch", None)
            if mesh_epoch is not None:
                msg["mesh_epoch"] = mesh_epoch
            if member.share is not None:
                msg["share"] = member.share
            if remaining is not None:
                msg["remaining"] = remaining
            try:
                self._send(member.writer, msg)
            except Exception:
                pass
        hook = getattr(self.launcher, "on_fleet_change", None)
        if hook is not None:
            try:
                hook({"reason": reason, "epoch": epoch,
                      "live": len(self.fleet), "remaining": remaining})
            except Exception:
                self.exception("on_fleet_change hook failed")

    def _broadcast(self, msg):
        for conn in list(self.slaves.values()):
            try:
                self._send(conn.writer, msg)
            except Exception:
                pass

    def _broadcast_stop(self):
        self._broadcast({"type": "stop"})

    _NO_PAYLOAD = object()

    def _send(self, writer, msg, payload=_NO_PAYLOAD, conn=None):
        if payload is not Server._NO_PAYLOAD:
            msg = dict(msg, codec=self.codec)
            raw = pack_payload(payload, self.codec)
            if conn is not None and conn.shm_out is not None:
                desc = conn.shm_out.write(raw)
                if desc is not None:
                    msg["shm"] = list(desc)
                    self.shm_sends += 1
                    raw = b""
        else:
            raw = b""
        write_frame(writer, msg, raw, self.secret, peer="master")

    async def _in_thread(self, fn, *args):
        return await self._loop.run_in_executor(None, fn, *args)
