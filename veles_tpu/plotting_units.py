"""Concrete plotter units (reference veles/plotting_units.py:52-822).

Covered set: accumulating line plots (metric vs epoch), matrix/confusion
rendering, image display, histogram, multi-histogram, min/max table,
and per-slave statistics — each holds plain-python captured state so it
pickles small and renders anywhere (graphics client or tests).
"""

import numpy

from veles_tpu.plotter import Plotter

__all__ = ["AccumulatingPlotter", "MatrixPlotter", "ImagePlotter",
           "Histogram", "MultiHistogram", "TableMaxMin", "SlaveStats"]


class AccumulatingPlotter(Plotter):
    """Appends one scalar per run; renders the series
    (reference AccumulatingPlotter)."""

    def __init__(self, workflow, **kwargs):
        super(AccumulatingPlotter, self).__init__(workflow, **kwargs)
        self.input = None          # linked: object with the value
        self.input_field = kwargs.get("input_field")
        self.label = kwargs.get("label", "metric")
        self.plot_style = kwargs.get("plot_style", "-")
        self.values = []

    def capture(self):
        value = self.input
        if self.input_field is not None:
            if isinstance(value, (list, tuple, dict)):
                value = value[self.input_field]
            else:
                value = getattr(value, self.input_field)
        if value is not None:
            self.values.append(float(value))

    def render(self, axes):
        axes.plot(self.values, self.plot_style, label=self.label)
        axes.set_xlabel("updates")
        axes.set_ylabel(self.label)
        axes.legend()


class MatrixPlotter(Plotter):
    """Renders a matrix with cell annotations — the confusion-matrix
    plotter (reference MatrixPlotter)."""

    def __init__(self, workflow, **kwargs):
        super(MatrixPlotter, self).__init__(workflow, **kwargs)
        self.input = None  # linked Array or ndarray
        self.matrix = None

    def capture(self):
        arr = self.input
        if hasattr(arr, "map_read"):
            arr.map_read()
            arr = arr.mem
        if arr is not None:
            self.matrix = numpy.array(arr)

    def render(self, axes):
        axes.imshow(self.matrix, interpolation="nearest", cmap="Blues")
        n_rows, n_cols = self.matrix.shape
        for r in range(n_rows):
            for c in range(n_cols):
                axes.text(c, r, str(self.matrix[r, c]),
                          ha="center", va="center", fontsize=8)
        axes.set_xlabel("predicted")
        axes.set_ylabel("target")


class ImagePlotter(Plotter):
    """Shows sample images (reference ImagePlotter)."""

    def __init__(self, workflow, **kwargs):
        super(ImagePlotter, self).__init__(workflow, **kwargs)
        self.input = None
        self.count = kwargs.get("count", 1)
        self.images = None

    def capture(self):
        arr = self.input
        if hasattr(arr, "map_read"):
            arr.map_read()
            arr = arr.mem
        if arr is not None:
            self.images = numpy.array(arr[:self.count])

    def render(self, axes):
        img = self.images[0]
        if img.ndim == 3 and img.shape[-1] == 1:
            img = img[..., 0]
        axes.imshow(img, cmap="gray")
        axes.axis("off")


class Histogram(Plotter):
    """Value histogram of a tensor (reference Histogram)."""

    def __init__(self, workflow, **kwargs):
        super(Histogram, self).__init__(workflow, **kwargs)
        self.input = None
        self.n_bins = kwargs.get("n_bins", 30)
        self.counts = None
        self.edges = None

    def capture(self):
        arr = self.input
        if hasattr(arr, "map_read"):
            arr.map_read()
            arr = arr.mem
        if arr is not None:
            self.counts, self.edges = numpy.histogram(
                numpy.asarray(arr).ravel(), bins=self.n_bins)

    def render(self, axes):
        centers = (self.edges[:-1] + self.edges[1:]) / 2
        axes.bar(centers, self.counts,
                 width=(self.edges[1] - self.edges[0]) * 0.9)
        axes.set_ylabel("count")


class MultiHistogram(Plotter):
    """Grid of per-unit weight histograms (reference MultiHistogram)."""

    def __init__(self, workflow, **kwargs):
        super(MultiHistogram, self).__init__(workflow, **kwargs)
        self.inputs = []  # list of Arrays
        self.n_bins = kwargs.get("n_bins", 20)
        self.hists = []

    def capture(self):
        self.hists = []
        for arr in self.inputs:
            if hasattr(arr, "map_read"):
                arr.map_read()
                data = arr.mem
            else:
                data = arr
            self.hists.append(numpy.histogram(
                numpy.asarray(data).ravel(), bins=self.n_bins))

    def render(self, axes):
        for i, (counts, edges) in enumerate(self.hists):
            centers = (edges[:-1] + edges[1:]) / 2
            axes.plot(centers, counts, label="w%d" % i)
        axes.legend()


class TableMaxMin(Plotter):
    """Min/max table of watched tensors (reference TableMaxMin)."""

    def __init__(self, workflow, **kwargs):
        super(TableMaxMin, self).__init__(workflow, **kwargs)
        self.inputs = []
        self.names = []
        self.rows = []

    def capture(self):
        self.rows = []
        for name, arr in zip(self.names, self.inputs):
            if hasattr(arr, "map_read"):
                arr.map_read()
                data = arr.mem
            else:
                data = arr
            data = numpy.asarray(data)
            self.rows.append((name, float(data.min()),
                              float(data.max())))

    def render(self, axes):
        axes.axis("off")
        cells = [["%s" % n, "%.4g" % mn, "%.4g" % mx]
                 for n, mn, mx in self.rows]
        axes.table(cellText=cells, colLabels=["name", "min", "max"],
                   loc="center")


class SlaveStats(Plotter):
    """Per-slave job statistics from the control-plane server
    (reference SlaveStats)."""

    def __init__(self, workflow, **kwargs):
        super(SlaveStats, self).__init__(workflow, **kwargs)
        self.server = None  # linked veles_tpu.server.Server
        self.stats = []

    def capture(self):
        self.stats = []
        if self.server is None:
            return
        for conn in self.server.slaves.values():
            times = list(conn.job_times)
            self.stats.append({
                "id": conn.slave.id[:8],
                "power": conn.slave.power,
                "jobs": len(times),
                "mean_time": float(numpy.mean(times)) if times else 0.0,
            })

    def render(self, axes):
        axes.axis("off")
        cells = [[s["id"], "%.1f" % s["power"], str(s["jobs"]),
                  "%.3f" % s["mean_time"]] for s in self.stats]
        axes.table(cellText=cells or [["-", "-", "-", "-"]],
                   colLabels=["slave", "power", "jobs", "mean s"],
                   loc="center")
