"""Trivial graph-delimiting units (reference: veles/plumbing.py:17)."""

from veles_tpu.units import Unit

__all__ = ["StartPoint", "EndPoint", "Repeater", "FireStarter",
           "EpochCounter"]


class StartPoint(Unit):
    """The graph entry point; running it kicks off every successor."""

    hide_from_registry = True

    def initialize(self, **kwargs):
        self._is_initialized_ = True
        return True

    def run(self):
        pass


class EndPoint(StartPoint):
    """The graph exit; running it signals workflow completion."""

    def run(self):
        if self.workflow is not None:
            self.workflow.on_workflow_finished()


class Repeater(StartPoint):
    """Loop head: ignores its gate so the training loop can cycle back
    through it every iteration (reference behavior)."""

    def __init__(self, workflow, **kwargs):
        super(Repeater, self).__init__(workflow, **kwargs)
        self.ignores_gate <<= True


class FireStarter(StartPoint):
    """Resets the ``stopped`` flag of the given units when run; used to
    re-arm sub-loops (parity with the reference's plumbing extras)."""

    def __init__(self, workflow, **kwargs):
        self.units = kwargs.pop("units", [])
        super(FireStarter, self).__init__(workflow, **kwargs)

    def run(self):
        for unit in self.units:
            unit._stopped <<= False


class EpochCounter(Unit):
    """Raises ``complete`` after N loop passes — the minimal
    termination gate for repeater loops that have no Decision unit
    (SOM/RBM-style unsupervised training).  Pass count resets on
    (re-)initialize so a snapshot-resumed workflow runs its full
    budget again rather than terminating immediately."""

    def __init__(self, workflow, epochs, **kwargs):
        super(EpochCounter, self).__init__(workflow, **kwargs)
        from veles_tpu.mutable import Bool
        self.epochs = epochs
        self.passes = 0
        self.complete = Bool(False)

    def initialize(self, **kwargs):
        self.passes = 0
        self.complete <<= False
        return super(EpochCounter, self).initialize(**kwargs)

    def run(self):
        self.passes += 1
        if self.passes >= self.epochs:
            self.complete <<= True
