"""Pickling base and the master-slave distribution contract.

TPU-native counterpart of reference veles/distributable.py:48,136,222.

:class:`Pickleable` — attributes whose name ends with ``_`` are transient
and excluded from pickles; ``init_unpickled`` re-creates them after load.
``stripped_pickle`` mode produces wire-sized payloads for the control plane.

:class:`Distributable` — per-unit data-exchange methods used by the job
farming control plane (genetics / ensembles / elastic loaders).  On-pod
tensor exchange does NOT go through this path in the TPU build: gradient
and weight merging compiles to ``jax.lax.psum`` over ICI inside the jitted
step (see veles_tpu/parallel/).  This contract remains for job-level
elasticity, exactly the split SURVEY.md section 7 prescribes.
"""

import threading

from veles_tpu.logger import Logger

__all__ = ["Pickleable", "Distributable", "TriviallyDistributable",
           "IDistributable"]

#: Seconds to wait on the data lock before warning about a likely deadlock
#: (reference: distributable.py:139-157 uses 4 s).
DEADLOCK_TIMEOUT = 4.0


class Pickleable(Logger):
    """Base class with transient-attribute pickling rules."""

    def __init__(self, **kwargs):
        super(Pickleable, self).__init__(**kwargs)
        self.stripped_pickle = False
        self.init_unpickled()

    def init_unpickled(self):
        """(Re)create transient state. Called from ``__init__`` and after
        unpickling. Subclasses must call ``super().init_unpickled()``."""
        parent = super(Pickleable, self)
        if hasattr(parent, "init_unpickled"):
            parent.init_unpickled()

    def __getstate__(self):
        state = {}
        for key, value in self.__dict__.items():
            if key.endswith("_"):
                continue
            state[key] = value
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self.init_unpickled()


class IDistributable(object):
    """Documentation-only interface for the distribution contract."""

    def generate_data_for_master(self):
        """Return the update payload this unit sends to the master."""

    def generate_data_for_slave(self, slave):
        """Return the job payload for ``slave`` (None -> nothing to send;
        False -> not ready, the requester waits at the sync point)."""

    def apply_data_from_master(self, data):
        """Consume a job payload on the slave."""

    def apply_data_from_slave(self, data, slave):
        """Merge an update payload on the master."""

    def drop_slave(self, slave):
        """Called when ``slave`` dies; requeue its pending work."""


class Distributable(Pickleable):
    """Thread-safe implementation scaffold for :class:`IDistributable`."""

    DEADLOCK_TIMEOUT = DEADLOCK_TIMEOUT

    def __init__(self, **kwargs):
        self.negotiates_on_connect = kwargs.pop("negotiates_on_connect",
                                                False)
        super(Distributable, self).__init__(**kwargs)

    def init_unpickled(self):
        super(Distributable, self).init_unpickled()
        self._data_lock_ = threading.RLock()
        self._data_event_ = threading.Event()
        self._data_event_.set()

    def _data_threadsafe(self, fn, name):
        def wrapped(*args, **kwargs):
            if not self._data_lock_.acquire(timeout=self.DEADLOCK_TIMEOUT):
                self.warning(
                    "%s: could not take the data lock within %.0f s - "
                    "possible deadlock", name, self.DEADLOCK_TIMEOUT)
                self._data_lock_.acquire()
            try:
                return fn(*args, **kwargs)
            finally:
                self._data_lock_.release()
        return wrapped

    def __getattribute__(self, name):
        if name in ("generate_data_for_master", "generate_data_for_slave",
                    "apply_data_from_master", "apply_data_from_slave"):
            fn = super(Distributable, self).__getattribute__(name)
            return self._data_threadsafe(fn, name)
        return super(Distributable, self).__getattribute__(name)

    @property
    def has_data_for_slave(self):
        return self._data_event_.is_set()

    @has_data_for_slave.setter
    def has_data_for_slave(self, value):
        if value:
            self._data_event_.set()
        else:
            self._data_event_.clear()

    def wait_for_data_for_slave(self, timeout=10.0):
        if not self._data_event_.wait(timeout):
            raise TimeoutError(
                "%s: no data for slave within %.0f s" %
                (type(self).__name__, timeout))

    # Default no-op contract (reference TriviallyDistributable merged in).
    def generate_data_for_master(self):
        return None

    def generate_data_for_slave(self, slave=None):
        return None

    def apply_data_from_master(self, data):
        pass

    def apply_data_from_slave(self, data, slave=None):
        pass

    def drop_slave(self, slave=None):
        pass


class TriviallyDistributable(Distributable):
    """Explicit alias matching the reference's class name."""
