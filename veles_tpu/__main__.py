"""CLI entry point: ``python -m veles_tpu <workflow.py> [config.py]``.

TPU-native counterpart of reference veles/__main__.py:136.  This grows
with the framework; current stages: special opts, seeding, config apply
(python file via runpy + ``key=value`` overrides), workflow load, snapshot
restore, run modes (standalone; master/slave once the control plane
lands).
"""

import argparse
import os
import runpy
import sys

from veles_tpu import prng
from veles_tpu.config import load_site_configs, root
from veles_tpu.logger import set_event_file, set_file_logging, setup_logging

__all__ = ["Main", "main"]


class Main(object):
    """Drives one training/serving session."""

    EXIT_SUCCESS = 0
    EXIT_FAILURE = 1

    def init_parser(self):
        from veles_tpu.cmdline import build_parser
        parser = build_parser()
        parser.add_argument("workflow", nargs="?",
                            help="workflow python file or module")
        parser.add_argument("config", nargs="?", default=None,
                            help="config python file ('-' for none)")
        parser.add_argument("overrides", nargs="*", default=[],
                            help="config overrides: root.path.key=value")
        parser.add_argument("-r", "--random-seed", default=None,
                            help="seed (int, hex with 0x, or file path)")
        parser.add_argument("-d", "--device", default=None,
                            help="backend: tpu | cpu | numpy | auto")
        parser.add_argument("-w", "--snapshot", default=None,
                            help="restore from snapshot file")
        parser.add_argument("-f", "--log-file", default=None)
        parser.add_argument("--event-file", default=None,
                            help="JSON-lines trace event sink")
        parser.add_argument("-v", "--verbose", action="store_true")
        parser.add_argument("--result-file", default=None)
        parser.add_argument("--dry-run", choices=("load", "init"),
                            default=None)
        parser.add_argument(
            "--sync-run", action="store_true",
            help="block after every unit's device call for honest "
                 "per-unit timings")
        parser.add_argument(
            "--no-fuse", action="store_true",
            help="keep the per-unit dispatch loop on TPU instead of "
                 "auto-fusing the train step (debug path; 8-25x "
                 "slower on a real chip)")
        parser.add_argument("--dump-graph", default=None,
                            help="write the graphviz dot file and exit")
        parser.add_argument(
            "--dump-unit-attributes", default=None, nargs="?",
            const="no-arrays", choices=("all", "no-arrays"),
            metavar="all|no-arrays",
            help="after initialize, print every unit's attributes "
                 "(reference __main__.py:663) and exit; 'all' includes "
                 "large array contents")
        parser.add_argument(
            "--optimize", default=None, metavar="GENS:POP",
            help="genetic hyper-parameter optimization: the workflow "
                 "module must expose tunable_spec() and fitness(spec)")
        parser.add_argument(
            "--ensemble-train", default=None, metavar="N[:RATIO]",
            help="train an N-model ensemble; the module must expose "
                 "member_factory(index, seed[, train_ratio]) — the "
                 "optional third parameter receives RATIO (the "
                 "per-member train-set fraction, default 1.0)")
        parser.add_argument(
            "--ensemble-test", default=None, metavar="RESULTS_JSON",
            help="test a trained ensemble from its results file")
        parser.add_argument(
            "--ensemble-dir", default="ensemble",
            help="ensemble output directory")
        parser.add_argument(
            "--farm-slaves", type=int, default=0, metavar="N",
            help="farm --optimize/--ensemble-train/--ensemble-test "
                 "jobs over the control plane with N local workers; "
                 "the bound address is logged so remote workers can "
                 "join")
        parser.add_argument(
            "--farm-address", default="127.0.0.1:0", metavar="HOST:PORT",
            help="bind address for the job-farm master (use "
                 "0.0.0.0:PORT to accept off-host workers)")
        parser.add_argument(
            "--frontend", nargs="?", const="8080", default=None,
            metavar="PORT",
            help="serve the web command composer instead of running "
                 "(reference __main__.py:258-332)")
        parser.add_argument(
            "-b", "--background", action="store_true",
            help="daemonize: detach and keep running after the "
                 "terminal closes (log goes to --log-file)")
        return parser

    @staticmethod
    def _dump_unit_attributes(workflow, arrays=False):
        """Aligned dump of every unit's public attributes (reference
        __main__.py:663-685 used prettytable; plain columns here)."""
        rows = []
        for i, unit in enumerate(workflow.units_in_dependency_order):
            for key in sorted(vars(unit)):
                if key.startswith("_"):
                    continue
                value = vars(unit)[key]
                if (not arrays and hasattr(value, "__len__")
                        and not isinstance(value, (str, bytes))
                        and getattr(value, "ndim", 1) != 0
                        and len(value) > 32):
                    text = "<%s of length %d>" % (
                        type(value).__name__, len(value))
                else:
                    text = repr(value)
                if len(text) > 100:
                    text = text[:97] + "..."
                rows.append((str(i), type(unit).__name__, key, text))
        widths = [max(len(r[c]) for r in rows) for c in range(3)]
        for row in rows:
            print("%*s  %-*s  %-*s  %s" % (
                widths[0], row[0], widths[1], row[1],
                widths[2], row[2], row[3]))

    def _run_frontend(self, parser, port):
        from veles_tpu.frontend import FrontendServer
        server = FrontendServer(parser, port=int(port))
        server.start_background()
        print("composer on http://127.0.0.1:%d/ (Ctrl-C to stop)"
              % server.port, flush=True)
        try:
            import time
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            server.stop()
        return self.EXIT_SUCCESS

    @staticmethod
    def _daemonize(log_file):
        """Classic double fork; stdio re-pointed at the log file
        (reference vendored python-daemon for -b)."""
        if os.fork() > 0:
            os._exit(0)
        os.setsid()
        if os.fork() > 0:
            os._exit(0)
        sys.stdout.flush()
        sys.stderr.flush()
        target = log_file or os.devnull
        fd = os.open(target, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                     0o644)
        null = os.open(os.devnull, os.O_RDONLY)
        os.dup2(null, 0)
        os.dup2(fd, 1)
        os.dup2(fd, 2)
        os.close(null)
        if fd > 2:
            os.close(fd)

    def _seed(self, spec):
        if spec is None:
            return
        if os.path.exists(spec):
            with open(spec, "rb") as fin:
                seed = fin.read(8)
        else:
            seed = int(spec, 0)
        prng.get().seed(seed)
        prng.get("second").seed(seed if isinstance(seed, int)
                                else seed[::-1])

    def _apply_config(self, path, overrides):
        if path and path != "-":
            runpy.run_path(path, init_globals={"root": root})
        for override in overrides:
            if "=" not in override:
                raise ValueError("override must be key=value: %r" % override)
            key, value = override.split("=", 1)
            node = root
            parts = key.split(".")
            if parts[0] == "root":
                parts = parts[1:]
            for part in parts[:-1]:
                node = getattr(node, part)
            try:
                import ast
                value = ast.literal_eval(value)
            except (ValueError, SyntaxError):
                pass
            setattr(node, parts[-1], value)

    def _load_workflow_module(self, spec):
        if os.path.exists(spec):
            sys.path.insert(0, os.path.dirname(os.path.abspath(spec)))
            name = os.path.splitext(os.path.basename(spec))[0]
            import importlib
            return importlib.import_module(name)
        import importlib
        return importlib.import_module(spec)

    def run_workflow(self, workflow_class, config=None, snapshot=None,
                     device=None, **kwargs):
        """Programmatic run (the ``veles_tpu.run(...)`` path)."""
        from veles_tpu.launcher import Launcher
        if config:
            root.update(config)
        launcher = Launcher()
        if snapshot:
            from veles_tpu.workflow import restore_workflow
            workflow = restore_workflow(snapshot, launcher)
        else:
            # --resume (root.common.snapshot.resume) is honored inside
            # launcher.initialize via Launcher._maybe_resume
            workflow = workflow_class(launcher, **kwargs)
        launcher.initialize(device=device)
        launcher.run()
        return workflow

    def run(self, argv=None):
        load_site_configs()
        parser = self.init_parser()
        args, extra = parser.parse_known_args(argv)
        overrides = list(args.overrides) + [
            e for e in extra if "=" in e and not e.startswith("-")]
        setup_logging(level=10 if args.verbose else 20)
        if args.log_file:
            set_file_logging(args.log_file)
        if args.event_file:
            set_event_file(args.event_file)
        self._seed(args.random_seed)
        if args.device:
            root.common.engine.backend = args.device
        if args.result_file:
            root.common.result_file = args.result_file
        from veles_tpu.cmdline import apply_parsed_args
        apply_parsed_args(args)
        if args.sync_run:
            root.common.sync_run = True
        if args.no_fuse:
            root.common.engine.auto_fuse = False
        if args.frontend is not None:
            return self._run_frontend(parser, args.frontend)
        if args.background:
            self._daemonize(args.log_file)
        if not args.workflow:
            parser.print_help()
            return self.EXIT_FAILURE
        import veles_tpu
        veles_tpu.load_plugins()
        self._apply_config(args.config, overrides)
        module = self._load_workflow_module(args.workflow)
        if overrides:
            # workflow modules may install config defaults at import
            # time; command-line overrides must still win
            self._apply_config(None, overrides)
        if args.dry_run == "load":
            return self.EXIT_SUCCESS
        if args.optimize:
            return self._run_optimize(module, args)
        if args.ensemble_train:
            return self._run_ensemble_train(module, args)
        if args.ensemble_test:
            return self._run_ensemble_test(module, args)
        run_fn = getattr(module, "run", None)
        if run_fn is None:
            raise SystemExit(
                "workflow file must define run(load, main)")
        # The reference's run(load, main) protocol: load builds/restores
        # the workflow, main initializes+runs it.
        from veles_tpu.launcher import Launcher
        launcher = Launcher()
        state = {}

        def load(workflow_class, **kwargs):
            if args.snapshot:
                from veles_tpu.workflow import restore_workflow
                state["workflow"] = restore_workflow(args.snapshot,
                                                     launcher)
                return state["workflow"], True
            workflow_class(launcher, **kwargs)
            # --resume auto|PATH: one resume implementation — the
            # launcher's (idempotent: initialize() calling it again is
            # a no-op); it swaps the restored workflow in for the one
            # just constructed
            launcher._maybe_resume()
            state["workflow"] = launcher.workflow
            return (state["workflow"],
                    state["workflow"].restored_from_snapshot_)

        def main(**kwargs):
            if args.dump_graph:
                with open(args.dump_graph, "w") as fout:
                    fout.write(state["workflow"].generate_graph())
                return
            launcher.initialize(**kwargs)
            if args.dump_unit_attributes:
                self._dump_unit_attributes(
                    state["workflow"],
                    arrays=args.dump_unit_attributes == "all")
                return
            if args.dry_run == "init":
                return
            launcher.run()

        run_fn(load, main)
        workflow = state.get("workflow")
        if workflow is not None and args.result_file:
            workflow.write_results(args.result_file)
        return self.EXIT_SUCCESS


    # -- meta run modes (reference cmdline.py:182-204) ---------------------

    def _run_optimize(self, module, args):
        """--optimize GENS:POP (reference --optimize)."""
        from veles_tpu.genetics import GeneticsOptimizer
        gens, _, pop = args.optimize.partition(":")
        spec_fn = getattr(module, "tunable_spec", None)
        fitness = getattr(module, "fitness", None)
        if spec_fn is None or fitness is None:
            raise SystemExit("--optimize needs tunable_spec() and "
                             "fitness(spec) in the workflow module")
        optimizer = GeneticsOptimizer(
            spec_fn(), fitness, generations=int(gens),
            population=int(pop) if pop else 12,
            farm_slaves=args.farm_slaves,
            farm_address=args.farm_address)
        best_spec, best_fitness = optimizer.run()
        print("best fitness %.6f with %s" % (best_fitness, best_spec))
        if args.result_file:
            import json
            with open(args.result_file, "w") as fout:
                json.dump({"fitness": best_fitness,
                           "spec": best_spec}, fout, indent=1,
                          default=repr)
        return self.EXIT_SUCCESS

    def _run_ensemble_train(self, module, args):
        """--ensemble-train N[:RATIO] (reference cmdline.py:182)."""
        from veles_tpu.ensemble import EnsembleTrainer
        factory = getattr(module, "member_factory", None)
        if factory is None:
            raise SystemExit("--ensemble-train needs "
                             "member_factory(index, seed)")
        n, _, ratio = args.ensemble_train.partition(":")
        trainer = EnsembleTrainer(
            factory, size=int(n), directory=args.ensemble_dir,
            train_ratio=float(ratio) if ratio else 1.0,
            device=args.device, farm_slaves=args.farm_slaves,
            farm_address=args.farm_address)
        path = trainer.run()
        print("ensemble results -> %s" % path)
        return self.EXIT_SUCCESS

    def _run_ensemble_test(self, module, args):
        """--ensemble-test RESULTS_JSON: evaluate the stored members
        (reference ensemble/test_workflow.py reran snapshots and
        aggregated outputs).  The workflow module supplies the data
        via ``ensemble_test_data() -> (x, labels)``; with
        --farm-slaves/--farm-address the member evaluations run as
        control-plane jobs."""
        from veles_tpu.ensemble import EnsembleTester
        tester = EnsembleTester(
            args.ensemble_test, device=args.device,
            farm_slaves=args.farm_slaves,
            farm_address=args.farm_address)
        print("loaded %d ensemble members" % len(tester.results))
        data_fn = getattr(module, "ensemble_test_data", None)
        if data_fn is None:
            print("(module defines no ensemble_test_data(); "
                  "nothing evaluated)")
            return self.EXIT_SUCCESS
        x, labels = data_fn()
        err = tester.error_rate(x, labels)
        print("ensemble error rate: %.2f%% over %d samples"
              % (err, len(labels)))
        if args.result_file:
            import json
            with open(args.result_file, "w") as fout:
                json.dump({"ensemble_error_pct": err,
                           "samples": len(labels),
                           "members": len(tester.results)}, fout,
                          indent=1)
        return self.EXIT_SUCCESS


def main(argv=None):
    return Main().run(argv)


if __name__ == "__main__":
    sys.exit(main())
