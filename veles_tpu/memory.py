"""Host/device tensor abstraction.

TPU-native counterpart of reference veles/memory.py:110 (``Array`` with the
explicit ``map_read / map_write / map_invalidate / unmap`` coherence
protocol).  The protocol's *names and semantics* are preserved so unit code
ports unchanged, but the mechanics map onto JAX placement:

==================  =====================================================
reference call       TPU meaning
==================  =====================================================
``map_read``        ensure ``mem`` (numpy) reflects device state
                    (blocking ``numpy.asarray(devmem)`` if device-fresher)
``map_write``       like map_read, then mark host copy dirty
``map_invalidate``  mark host dirty WITHOUT reading device back
``unmap``           if host dirty, ``device_put`` the numpy buffer;
                    ``devmem`` becomes the fresh jax.Array
==================  =====================================================

jax.Arrays are immutable, so there is no zero-copy aliasing; instead the
dirty-bit state machine minimises transfers exactly like the reference's
OpenCL map/unmap path minimised them.  A :class:`Watcher` counts
HBM-resident bytes (reference: memory.py:56).  ``shallow_pickle`` ships
only shape+dtype over the wire (reference: memory.py:477-511).
"""

import threading

import numpy

from veles_tpu.distributable import Pickleable

__all__ = ["Array", "Watcher", "roundup"]


def roundup(num, align):
    rem = num % align
    return num if rem == 0 else num + (align - rem)


class Watcher(object):
    """Tracks bytes resident on devices across all Arrays."""

    _lock = threading.Lock()
    bytes_on_device = 0
    arrays_on_device = 0

    @classmethod
    def add(cls, nbytes):
        with cls._lock:
            cls.bytes_on_device += nbytes
            cls.arrays_on_device += 1

    @classmethod
    def remove(cls, nbytes):
        with cls._lock:
            cls.bytes_on_device -= nbytes
            cls.arrays_on_device -= 1


# coherence states
_HOST_ONLY = 0      # no device buffer
_IN_SYNC = 1        # host == device
_HOST_DIRTY = 2     # host newer than device
_DEVICE_DIRTY = 3   # device newer than host


class Array(Pickleable):
    """A named tensor with a host numpy buffer and an optional device
    (jax) buffer, synchronised through the map/unmap protocol."""

    def __init__(self, data=None, shallow_pickle=False):
        super(Array, self).__init__()
        self._mem = None
        self.shallow_pickle = shallow_pickle
        if data is not None:
            self.mem = data

    def init_unpickled(self):
        super(Array, self).init_unpickled()
        self._device_ = None
        self._devmem_ = None
        self._state_ = _HOST_ONLY
        self._lock_ = threading.RLock()
        self._watched_nbytes_ = 0  # exactly what we told Watcher.add
        # ping-pong host staging (see stage_init); transient by design:
        # a restored Array re-stages lazily on the first pipelined serve
        self._stage_bufs_ = None
        self._stage_pending_ = None
        self._stage_slot_ = 0

    # -- basic container behaviour ----------------------------------------

    @property
    def mem(self):
        return self._mem

    @mem.setter
    def mem(self, value):
        if value is None:
            self.reset()
            return
        self._mem = numpy.ascontiguousarray(value)
        # a wholesale buffer swap invalidates the staging slots (their
        # shape/identity no longer matches); re-staged lazily
        self._stage_bufs_ = None
        self._stage_pending_ = None
        if self._device_ is not None:
            self._state_ = _HOST_DIRTY

    @property
    def devmem(self):
        """Current device buffer (jax.Array), pushing host changes first."""
        self.unmap()
        return self._devmem_

    def device_array(self, device):
        """devmem, first attaching ``device`` when the Array is still
        host-only.  Streaming loaders (zmq/restful/interactive feeds)
        hand consumers unattached host Arrays; consumer units pass
        their own device here instead of crashing on a None devmem."""
        with self._lock_:
            if self._device_ is None and device is not None \
                    and device.exists and self._mem is not None:
                self._device_ = device
                self._state_ = _HOST_DIRTY
        return self.devmem

    def __bool__(self):
        return self._mem is not None and self._mem.size > 0

    def __len__(self):
        return 0 if self._mem is None else len(self._mem)

    def __getitem__(self, key):
        self.map_read()
        return self._mem[key]

    def __setitem__(self, key, value):
        self.map_write()
        self._mem[key] = value

    @property
    def shape(self):
        return None if self._mem is None else self._mem.shape

    @property
    def size(self):
        return 0 if self._mem is None else self._mem.size

    @property
    def dtype(self):
        return None if self._mem is None else self._mem.dtype

    @property
    def nbytes(self):
        return 0 if self._mem is None else self._mem.nbytes

    @property
    def sample_size(self):
        """Elements per sample (all dims but the first)."""
        if self._mem is None or self._mem.ndim == 0:
            return 0
        return self._mem.size // self._mem.shape[0]

    def reshape(self, shape):
        self.map_write()
        self._mem = self._mem.reshape(shape)

    def plain(self):
        self.map_read()
        return self._mem.ravel()

    # -- device lifecycle --------------------------------------------------

    @property
    def device(self):
        return self._device_

    def initialize(self, device):
        """Attach to ``device``; the first ``unmap`` uploads the data."""
        with self._lock_:
            if device is None or not device.exists:
                self._device_ = None
                self._state_ = _HOST_ONLY
                return
            if self._device_ is device and self._state_ != _HOST_ONLY:
                return
            self._device_ = device
            if self._mem is not None:
                self._state_ = _HOST_DIRTY

    def reset(self):
        with self._lock_:
            if self._watched_nbytes_:
                Watcher.remove(self._watched_nbytes_)
                self._watched_nbytes_ = 0
            self._mem = None
            self._devmem_ = None
            self._state_ = _HOST_ONLY
            self._stage_bufs_ = None
            self._stage_pending_ = None

    # -- ping-pong host staging (async input pipeline) ----------------------
    #
    # Ownership rules (docs/pipeline_input.md): between stage_begin(slot)
    # and the next stage_begin on the SAME slot, that slot's host buffer
    # belongs to the producer thread; consumers must read the minibatch
    # through the device array returned by stage_put / staged_capture,
    # never through ``mem``.

    @property
    def staged(self):
        return self._stage_bufs_ is not None

    def stage_init(self, nslots=2):
        """Allocate ``nslots`` host staging buffers; slot 0 adopts the
        existing host buffer, the rest are fresh allocations of the
        same shape/dtype."""
        with self._lock_:
            if self._mem is None:
                raise ValueError("stage_init() before mem is allocated")
            self._stage_bufs_ = [self._mem] + [
                numpy.empty_like(self._mem) for _ in range(nslots - 1)]
            self._stage_pending_ = [None] * nslots
            self._stage_slot_ = 0

    def stage_begin(self, slot):
        """Point ``mem`` at ``slot``'s host buffer for a staged fill
        (producer thread).  Blocks until the slot's previous async
        host->device transfer has finished reading the buffer, so an
        in-flight DMA is never overwritten.  No-op when unstaged."""
        with self._lock_:
            if self._stage_bufs_ is None:
                return
            pending = self._stage_pending_[slot]
            self._stage_pending_[slot] = None
        if pending is not None and hasattr(pending, "block_until_ready"):
            try:
                pending.block_until_ready()
            except Exception:
                pass  # a deleted/donated buffer cannot be in flight
        with self._lock_:
            if self._stage_bufs_ is None:
                return
            self._mem = self._stage_bufs_[slot]
            self._stage_slot_ = slot
            # the upcoming fill makes the host buffer authoritative; it
            # also guarantees map_read/map_write cannot replace _mem
            # with a device fetch mid-fill
            self._state_ = (_HOST_DIRTY if self._device_ is not None
                            else _HOST_ONLY)

    def stage_put(self, device):
        """Start the async host->device transfer of the CURRENT host
        buffer and return the resulting device array immediately (JAX
        transfers are asynchronous).  The coherence state is NOT
        touched: the caller owns the returned array, and the host
        buffer must not be refilled before ``stage_begin`` is called
        again on the same slot."""
        with self._lock_:
            dev = device.put(self._mem)
            if self._stage_bufs_ is not None:
                self._stage_pending_[self._stage_slot_] = dev
            self._track_device_bytes(self._mem.nbytes)
            return dev

    def staged_capture(self, device):
        """Device-side array for the just-served minibatch: the adopted
        device buffer when a device path already produced one
        (set_device_array), else an async ``stage_put`` of the staged
        host fill."""
        with self._lock_:
            if self._state_ == _DEVICE_DIRTY and self._devmem_ is not None:
                return self._devmem_
        return self.stage_put(device)

    # -- coherence protocol ------------------------------------------------

    def map_read(self):
        with self._lock_:
            if self._state_ == _DEVICE_DIRTY:
                self._mem = numpy.asarray(self._devmem_)
                self._state_ = _IN_SYNC

    def map_write(self):
        with self._lock_:
            self.map_read()
            if self._state_ != _HOST_ONLY:
                self._state_ = _HOST_DIRTY

    def map_invalidate(self):
        with self._lock_:
            if self._state_ != _HOST_ONLY:
                self._state_ = _HOST_DIRTY

    def unmap(self):
        with self._lock_:
            if self._state_ == _HOST_DIRTY or (
                    self._state_ == _IN_SYNC and self._devmem_ is None):
                if self._device_ is None:
                    return
                self._devmem_ = self._device_.put(self._mem)
                self._track_device_bytes(self._mem.nbytes)
                self._state_ = _IN_SYNC

    def _track_device_bytes(self, nbytes):
        """Keep Watcher in sync with exactly what this Array contributed."""
        if nbytes != self._watched_nbytes_:
            if self._watched_nbytes_:
                Watcher.remove(self._watched_nbytes_)
            if nbytes:
                Watcher.add(nbytes)
            self._watched_nbytes_ = nbytes

    def set_device_array(self, jax_array, device=None):
        """Adopt a fresh device-side result (the output of a jitted step)
        without a host round-trip; host copy becomes stale."""
        with self._lock_:
            if device is not None:
                self._device_ = device
            self._devmem_ = jax_array
            self._state_ = _DEVICE_DIRTY
            if self._mem is None:
                # keep shape/dtype metadata without materialising
                self._mem = numpy.zeros(jax_array.shape, jax_array.dtype)
            self._track_device_bytes(self._mem.nbytes)

    def detach_device(self):
        """Materialise the host copy and DROP the device reference.

        For adopting buffers another computation is about to donate
        (the fused train step donates its input state): keeping the
        reference would hand later devmem readers a deleted jax.Array.
        Host becomes authoritative; a future unmap re-uploads."""
        with self._lock_:
            self.map_read()
            if self._devmem_ is not None:
                self._devmem_ = None
                self._track_device_bytes(0)
                if self._device_ is not None:
                    self._state_ = _HOST_DIRTY

    def prefetch_host(self):
        """Start an async device->host copy when the device copy is
        authoritative.  A later map_read finds the bytes already local,
        so N arrays cost ~one round trip instead of N sequential ones
        (a whole-workflow snapshot over a tunneled chip measured
        ~1.9 s/pickle from serialized per-array fetches)."""
        with self._lock_:
            if self._state_ != _DEVICE_DIRTY:
                return
            if hasattr(self._devmem_, "copy_to_host_async"):
                try:
                    self._devmem_.copy_to_host_async()
                    return
                except Exception:
                    pass  # fall through to the eager fetch
            # backend without async D2H (or a failed async start): fetch
            # eagerly NOW so the caller's later map_read is still local
            # instead of silently degrading to N sequential round trips
            self._mem = numpy.asarray(self._devmem_)
            self._state_ = _IN_SYNC

    # -- pickling ----------------------------------------------------------

    def __getstate__(self):
        self.map_read()
        state = super(Array, self).__getstate__()
        if self.shallow_pickle or getattr(self, "stripped_pickle", False):
            state["_mem"] = None
            state["_shallow_shape"] = (
                None if self._mem is None
                else (self._mem.shape, self._mem.dtype.str))
        return state

    def __setstate__(self, state):
        shallow = state.pop("_shallow_shape", None)
        super(Array, self).__setstate__(state)
        if shallow is not None and self._mem is None:
            shape, dtype = shallow
            self._mem = numpy.zeros(shape, numpy.dtype(dtype))

    def __repr__(self):
        return "<Array shape=%s dtype=%s state=%d>" % (
            self.shape, self.dtype, self._state_)
