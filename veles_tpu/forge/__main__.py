"""Forge CLI: ``python -m veles_tpu.forge <cmd> <hub-url> ...``
(reference forge_client.py exposed the same verbs as ``veles forge``).
"""

import argparse
import json
import sys

from veles_tpu.forge import client


def main(argv=None):
    parser = argparse.ArgumentParser(prog="veles_tpu.forge")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("list", help="list hub packages")
    p.add_argument("url")

    p = sub.add_parser("details", help="package metadata + versions")
    p.add_argument("url")
    p.add_argument("name")

    p = sub.add_parser("fetch", help="download a package")
    p.add_argument("url")
    p.add_argument("name")
    p.add_argument("destination")
    p.add_argument("--version", default="latest")

    p = sub.add_parser("upload", help="publish a package")
    p.add_argument("url")
    p.add_argument("name")
    p.add_argument("version")
    p.add_argument("package")
    p.add_argument("--metadata", default="{}",
                   help="JSON metadata string")
    p.add_argument("--token", default=None,
                   help="bearer upload token ($VELES_FORGE_TOKEN)")

    args = parser.parse_args(argv)
    if args.cmd == "list":
        for pkg in client.list_packages(args.url):
            print("%s==%s  (%s bytes)" % (
                pkg.get("name"), pkg.get("version"), pkg.get("size")))
    elif args.cmd == "details":
        print(json.dumps(client.details(args.url, args.name),
                         indent=1, sort_keys=True))
    elif args.cmd == "fetch":
        _, version = client.fetch(args.url, args.name,
                                  args.destination,
                                  version=args.version)
        print("fetched %s==%s -> %s" % (args.name, version,
                                        args.destination))
    elif args.cmd == "upload":
        client.upload(args.url, args.name, args.version, args.package,
                      metadata=json.loads(args.metadata),
                      token=args.token)
        print("uploaded %s==%s" % (args.name, args.version))
    return 0


if __name__ == "__main__":
    sys.exit(main())
