"""Forge client: upload / fetch / list (reference
veles/forge/forge_client.py CLI ``veles forge fetch|upload``)."""

import json
import os
import urllib.parse
import urllib.request

__all__ = ["upload", "fetch", "list_packages", "details", "main"]


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.read(), dict(resp.headers)


def list_packages(base_url):
    data, _ = _get(base_url.rstrip("/") + "/service?query=list")
    return json.loads(data)["packages"]


def details(base_url, name):
    data, _ = _get(base_url.rstrip("/") +
                   "/service?query=details&name=" +
                   urllib.parse.quote(name))
    return json.loads(data)


def fetch(base_url, name, destination, version="latest"):
    data, headers = _get(
        base_url.rstrip("/") + "/fetch?name=%s&version=%s" % (
            urllib.parse.quote(name), urllib.parse.quote(version)))
    with open(destination, "wb") as fout:
        fout.write(data)
    return destination, headers.get("X-Package-Version")


def upload(base_url, name, version, package_path, metadata=None,
           token=None):
    import os
    with open(package_path, "rb") as fin:
        payload = fin.read()
    query = urllib.parse.urlencode({
        "name": name, "version": version,
        "metadata": json.dumps(metadata or {})})
    headers = {"Content-Type": "application/octet-stream"}
    token = token if token is not None else os.environ.get(
        "VELES_FORGE_TOKEN")
    if token:
        headers["Authorization"] = "Bearer %s" % token
    req = urllib.request.Request(
        base_url.rstrip("/") + "/upload?" + query, data=payload,
        headers=headers)
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read())


def main(argv=None):
    import argparse
    parser = argparse.ArgumentParser(prog="veles_tpu.forge")
    sub = parser.add_subparsers(dest="command", required=True)
    p_list = sub.add_parser("list")
    p_list.add_argument("url")
    p_fetch = sub.add_parser("fetch")
    p_fetch.add_argument("url")
    p_fetch.add_argument("name")
    p_fetch.add_argument("-o", "--output", default=None)
    p_fetch.add_argument("--version", default="latest")
    p_up = sub.add_parser("upload")
    p_up.add_argument("url")
    p_up.add_argument("name")
    p_up.add_argument("version")
    p_up.add_argument("package")
    args = parser.parse_args(argv)
    if args.command == "list":
        for meta in list_packages(args.url):
            print("%s==%s (%d bytes)" % (meta["name"], meta["version"],
                                         meta["size"]))
    elif args.command == "fetch":
        out = args.output or (args.name + ".tar")
        path, version = fetch(args.url, args.name, out, args.version)
        print("%s==%s -> %s" % (args.name, version, path))
    elif args.command == "upload":
        upload(args.url, args.name, args.version, args.package)
        print("uploaded %s==%s" % (args.name, args.version))


if __name__ == "__main__":
    main()
