"""Forge — the model-package hub (reference veles/forge/: tornado
service with upload/fetch + CLI client, per-package storage)."""

from veles_tpu.forge.server import ForgeServer  # noqa: F401
from veles_tpu.forge.client import (  # noqa: F401
    upload, fetch, list_packages, details)
