"""Forge server: package registry over HTTP.

Reference veles/forge/forge_server.py kept each package as a git repo
with email-confirmed uploads; this build stores versioned directories
(<root>/<name>/<version>/package.tar + metadata.json) by default, or —
with ``git_backed=True`` — one git repo per package whose worktree
holds the latest files and whose ``v/<version>`` tags hold history
(delta compression dedups near-identical package versions, same as the
reference).  Served endpoints:

  GET  /service?query=list                  -> JSON package index
  GET  /service?query=details&name=N        -> metadata + versions
  GET  /fetch?name=N[&version=V]            -> package bytes (latest)
  POST /upload?name=N&version=V             -> store package (body)

Versions order by natural numeric sort ("1.9.0" < "1.10.0"); "latest"
resolves to the numerically greatest version everywhere — index,
details, and fetch agree (reference forge_server.py resolved latest
from one place too, git HEAD).
"""

import json
import os
import re
import subprocess

from veles_tpu.logger import Logger

__all__ = ["ForgeServer"]

# Package names and versions become path components; anything outside
# this alphabet (or a leading dot) is rejected to block traversal.
_SAFE_COMPONENT = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._+-]*\Z")


def _safe_component(value, what):
    if not _SAFE_COMPONENT.match(value or "") or ".." in value:
        raise ValueError("illegal %s %r" % (what, value))
    return value


def _version_key(version):
    # natural sort: "1.10.0" > "1.9.0"
    return [int(part) if part.isdigit() else part
            for part in re.split(r"(\d+)", version)]


class ForgeServer(Logger):
    """``upload_token``: when set, POST /upload requires
    ``Authorization: Bearer <token>`` (the reference's forge used
    email-confirmed tokens, forge_server.py; a shared bearer token is
    this build's equivalent).  Reads default from $VELES_FORGE_TOKEN."""

    def __init__(self, root_dir, port=0, upload_token=None,
                 git_backed=False):
        super(ForgeServer, self).__init__()
        self.root_dir = root_dir
        os.makedirs(root_dir, exist_ok=True)
        self.port = port
        self.upload_token = (upload_token if upload_token is not None
                             else os.environ.get("VELES_FORGE_TOKEN"))
        self.git_backed = git_backed
        self._server_ = None
        # per-package version-list cache: ``git tag --list`` is a
        # subprocess spawn, and index() calls versions() for every
        # package on every /service?query=list — without the cache the
        # endpoint is O(packages) process spawns per request.  The
        # server owns the store, so store() is the only invalidation
        # point needed.
        self._versions_cache = {}

    # -- git backing ----------------------------------------------------------

    def _git(self, name, *args, binary=False):
        pdir = os.path.join(self.root_dir,
                            _safe_component(name, "package name"))
        env = dict(os.environ,
                   GIT_CONFIG_GLOBAL=os.devnull,
                   GIT_CONFIG_SYSTEM=os.devnull)
        try:
            out = subprocess.run(
                ["git", "-C", pdir, "-c", "user.name=forge",
                 "-c", "user.email=forge@localhost",
                 # payloads are binary: host autocrlf/gitattributes
                 # must never rewrite them
                 "-c", "core.autocrlf=false"] + list(args),
                capture_output=True, check=True, env=env)
        except FileNotFoundError:
            raise RuntimeError("git binary not available "
                               "(git_backed forge requires it)")
        except subprocess.CalledProcessError as exc:
            stderr = exc.stderr.decode(errors="replace").strip()
            self.warning("git %s failed for %s: %s",
                         args[0] if args else "", name, stderr)
            raise RuntimeError("git %s failed: %s"
                               % (args[0] if args else "", stderr))
        return out.stdout if binary else out.stdout.decode()

    def _git_store(self, name, version, payload, meta):
        pdir = os.path.join(self.root_dir,
                            _safe_component(name, "package name"))
        _safe_component(version, "version")
        # drop the cached version list up front: even a failed store
        # may have advanced the underlying repo (e.g. crash between
        # commit and tag), so the next read must re-list
        self._versions_cache.pop(name, None)
        os.makedirs(pdir, exist_ok=True)
        if not os.path.isdir(os.path.join(pdir, ".git")):
            self._git(name, "init", "-q")
        if version in self._git_versions(name):
            raise ValueError("version %s already published" % version)
        with open(os.path.join(pdir, "package.tar"), "wb") as fout:
            fout.write(payload)
        with open(os.path.join(pdir, "metadata.json"), "w") as fout:
            json.dump(meta, fout, indent=1, sort_keys=True)
        self._git(name, "add", "-A")
        # --allow-empty: a crash between commit and tag leaves the
        # version unpublished (no tag) but retriable — the retry's
        # identical content still commits and the tag lands
        self._git(name, "commit", "-q", "--allow-empty",
                  "-m", version)
        self._git(name, "tag", "v/%s" % version)
        # the already-published check above re-populated the cache
        # with the pre-tag list — drop it again now that the tag lands
        self._versions_cache.pop(name, None)

    def _git_versions(self, name):
        cached = self._versions_cache.get(name)
        if cached is not None:
            return list(cached)
        versions = self._git_versions_uncached(name)
        self._versions_cache[name] = list(versions)
        return versions

    def _git_versions_uncached(self, name):
        pdir = os.path.join(self.root_dir,
                            _safe_component(name, "package name"))
        if not os.path.isdir(os.path.join(pdir, ".git")):
            if os.path.isdir(pdir) and os.listdir(pdir):
                # plain-directory versions from a non-git deployment:
                # hiding them (or committing them as junk) would be
                # silent data loss — refuse loudly
                raise RuntimeError(
                    "package %r holds non-git version directories; "
                    "migrate them or run without git_backed" % name)
            return []
        tags = self._git(name, "tag", "--list", "v/*").split()
        return sorted((t[2:] for t in tags), key=_version_key)

    def _git_show(self, name, version, filename, binary=False):
        return self._git(
            name, "show", "v/%s:%s" % (
                _safe_component(version, "version"), filename),
            binary=binary)

    # -- storage ------------------------------------------------------------

    def _package_dir(self, name, version):
        path = os.path.join(self.root_dir,
                            _safe_component(name, "package name"),
                            _safe_component(version, "version"))
        root = os.path.realpath(self.root_dir)
        if not os.path.realpath(path).startswith(root + os.sep):
            raise ValueError("package path escapes root_dir")
        return path

    def versions(self, name):
        if self.git_backed:
            return self._git_versions(name)
        pdir = os.path.join(self.root_dir,
                            _safe_component(name, "package name"))
        if not os.path.isdir(pdir):
            return []
        return sorted(os.listdir(pdir), key=_version_key)

    def store(self, name, version, payload, metadata=None):
        meta = dict(metadata or {})
        meta.update({"name": name, "version": version,
                     "size": len(payload)})
        if self.git_backed:
            self._git_store(name, version, payload, meta)
            self.info("stored %s==%s (%d bytes, git)", name, version,
                      len(payload))
            return
        pdir = self._package_dir(name, version)
        os.makedirs(pdir, exist_ok=True)
        with open(os.path.join(pdir, "package.tar"), "wb") as fout:
            fout.write(payload)
        with open(os.path.join(pdir, "metadata.json"), "w") as fout:
            json.dump(meta, fout, indent=1, sort_keys=True)
        self.info("stored %s==%s (%d bytes)", name, version,
                  len(payload))

    def _worktree_version(self, name):
        """Version held by the git worktree (= most recent upload),
        or None."""
        path = os.path.join(
            self.root_dir, _safe_component(name, "package name"),
            "metadata.json")
        try:
            with open(path) as fin:
                return json.load(fin).get("version")
        except (OSError, ValueError):
            return None

    def load(self, name, version="latest"):
        if version == "latest":
            versions = self.versions(name)
            if not versions:
                raise KeyError("unknown package %s" % name)
            version = versions[-1]
        if self.git_backed:
            if version == self._worktree_version(name):
                # worktree fast path, but only when it actually holds
                # the requested version — out-of-order uploads (1.0.1
                # backfilled after 1.1.0) leave the worktree behind
                # "latest" and must go through the tag
                pdir = os.path.join(
                    self.root_dir,
                    _safe_component(name, "package name"))
                with open(os.path.join(pdir, "package.tar"),
                          "rb") as fin:
                    return fin.read(), version
            if version not in self._git_versions(name):
                raise KeyError("unknown version %s" % version)
            return (self._git_show(name, version, "package.tar",
                                   binary=True), version)
        pdir = self._package_dir(name, version)
        with open(os.path.join(pdir, "package.tar"), "rb") as fin:
            return fin.read(), version

    def metadata(self, name, version):
        if self.git_backed:
            if version not in self._git_versions(name):
                raise KeyError("unknown version %s" % version)
            return json.loads(
                self._git_show(name, version, "metadata.json"))
        with open(os.path.join(self._package_dir(name, version),
                               "metadata.json")) as fin:
            return json.load(fin)

    def index(self):
        out = []
        for name in sorted(os.listdir(self.root_dir)):
            if self.git_backed:
                versions = self.versions(name)
                if not versions:
                    continue
                if versions[-1] == self._worktree_version(name):
                    # worktree fast path: one file read, no git show
                    path = os.path.join(self.root_dir, name,
                                        "metadata.json")
                    with open(path) as fin:
                        out.append(json.load(fin))
                else:
                    out.append(self.metadata(name, versions[-1]))
                continue
            versions = self.versions(name)
            if versions:
                out.append(self.metadata(name, versions[-1]))
        return out

    # -- HTTP ---------------------------------------------------------------

    def start_background(self):
        import tornado.web

        forge = self

        class ServiceHandler(tornado.web.RequestHandler):
            def get(self):
                query = self.get_argument("query", "list")
                if query == "list":
                    self.write({"packages": forge.index()})
                elif query == "details":
                    name = self.get_argument("name")
                    try:
                        versions = forge.versions(name)
                    except ValueError:
                        self.set_status(400)
                        self.write({"error": "illegal name"})
                        return
                    if not versions:
                        self.set_status(404)
                        self.write({"error": "unknown package"})
                        return
                    self.write({
                        "name": name, "versions": versions,
                        "metadata": forge.metadata(name, versions[-1])})
                else:
                    self.set_status(400)
                    self.write({"error": "unknown query"})

        class FetchHandler(tornado.web.RequestHandler):
            def get(self):
                name = self.get_argument("name")
                version = self.get_argument("version", "latest")
                try:
                    payload, version = forge.load(name, version)
                except ValueError:
                    self.set_status(400)
                    return
                except (KeyError, OSError):
                    self.set_status(404)
                    return
                self.set_header("Content-Type",
                                "application/octet-stream")
                self.set_header("X-Package-Version", version)
                self.write(payload)

        class UploadHandler(tornado.web.RequestHandler):
            def post(self):
                if forge.upload_token:
                    import hmac as hmac_mod
                    auth = self.request.headers.get("Authorization", "")
                    want = "Bearer %s" % forge.upload_token
                    if not hmac_mod.compare_digest(auth, want):
                        self.set_status(401)
                        self.write({"error": "upload token required"})
                        return
                name = self.get_argument("name")
                version = self.get_argument("version")
                meta_json = self.get_argument("metadata", "{}")
                try:
                    forge.store(name, version, self.request.body,
                                json.loads(meta_json))
                except ValueError as exc:
                    # distinguish "already published" from a malformed
                    # name so publishers debug the right thing
                    self.set_status(400)
                    self.write({"error": str(exc)})
                    return
                self.write({"result": "ok"})

        app = tornado.web.Application([
            (r"/service", ServiceHandler),
            (r"/fetch", FetchHandler),
            (r"/upload", UploadHandler),
        ])
        from veles_tpu.http_util import BackgroundHTTPServer
        self._server_ = BackgroundHTTPServer(
            app, port=self.port, max_buffer_size=1 << 30)
        thread = self._server_.start()
        self.port = self._server_.port
        self.info("forge on http://127.0.0.1:%d/", self.port)
        return thread

    def stop(self):
        if self._server_ is not None:
            self._server_.stop()
