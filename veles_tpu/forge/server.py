"""Forge server: package registry over HTTP.

Reference veles/forge/forge_server.py kept each package as a git repo
with email-confirmed uploads; this build stores versioned directories
(<root>/<name>/<version>/package.tar + metadata.json) and serves:

  GET  /service?query=list                  -> JSON package index
  GET  /service?query=details&name=N        -> metadata + versions
  GET  /fetch?name=N[&version=V]            -> package bytes (latest)
  POST /upload?name=N&version=V             -> store package (body)

Versions order lexicographically ("1.0.0" style); "latest" resolves to
the highest.
"""

import json
import os
import re

from veles_tpu.logger import Logger

__all__ = ["ForgeServer"]

# Package names and versions become path components; anything outside
# this alphabet (or a leading dot) is rejected to block traversal.
_SAFE_COMPONENT = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._+-]*\Z")


def _safe_component(value, what):
    if not _SAFE_COMPONENT.match(value or "") or ".." in value:
        raise ValueError("illegal %s %r" % (what, value))
    return value


class ForgeServer(Logger):
    """``upload_token``: when set, POST /upload requires
    ``Authorization: Bearer <token>`` (the reference's forge used
    email-confirmed tokens, forge_server.py; a shared bearer token is
    this build's equivalent).  Reads default from $VELES_FORGE_TOKEN."""

    def __init__(self, root_dir, port=0, upload_token=None):
        super(ForgeServer, self).__init__()
        self.root_dir = root_dir
        os.makedirs(root_dir, exist_ok=True)
        self.port = port
        self.upload_token = (upload_token if upload_token is not None
                             else os.environ.get("VELES_FORGE_TOKEN"))
        self._server_ = None

    # -- storage ------------------------------------------------------------

    def _package_dir(self, name, version):
        path = os.path.join(self.root_dir,
                            _safe_component(name, "package name"),
                            _safe_component(version, "version"))
        root = os.path.realpath(self.root_dir)
        if not os.path.realpath(path).startswith(root + os.sep):
            raise ValueError("package path escapes root_dir")
        return path

    def versions(self, name):
        pdir = os.path.join(self.root_dir,
                            _safe_component(name, "package name"))
        if not os.path.isdir(pdir):
            return []
        return sorted(os.listdir(pdir))

    def store(self, name, version, payload, metadata=None):
        pdir = self._package_dir(name, version)
        os.makedirs(pdir, exist_ok=True)
        with open(os.path.join(pdir, "package.tar"), "wb") as fout:
            fout.write(payload)
        meta = dict(metadata or {})
        meta.update({"name": name, "version": version,
                     "size": len(payload)})
        with open(os.path.join(pdir, "metadata.json"), "w") as fout:
            json.dump(meta, fout, indent=1, sort_keys=True)
        self.info("stored %s==%s (%d bytes)", name, version,
                  len(payload))

    def load(self, name, version="latest"):
        if version == "latest":
            versions = self.versions(name)
            if not versions:
                raise KeyError("unknown package %s" % name)
            version = versions[-1]
        pdir = self._package_dir(name, version)
        with open(os.path.join(pdir, "package.tar"), "rb") as fin:
            return fin.read(), version

    def metadata(self, name, version):
        with open(os.path.join(self._package_dir(name, version),
                               "metadata.json")) as fin:
            return json.load(fin)

    def index(self):
        out = []
        for name in sorted(os.listdir(self.root_dir)):
            versions = self.versions(name)
            if versions:
                out.append(self.metadata(name, versions[-1]))
        return out

    # -- HTTP ---------------------------------------------------------------

    def start_background(self):
        import tornado.web

        forge = self

        class ServiceHandler(tornado.web.RequestHandler):
            def get(self):
                query = self.get_argument("query", "list")
                if query == "list":
                    self.write({"packages": forge.index()})
                elif query == "details":
                    name = self.get_argument("name")
                    try:
                        versions = forge.versions(name)
                    except ValueError:
                        self.set_status(400)
                        self.write({"error": "illegal name"})
                        return
                    if not versions:
                        self.set_status(404)
                        self.write({"error": "unknown package"})
                        return
                    self.write({
                        "name": name, "versions": versions,
                        "metadata": forge.metadata(name, versions[-1])})
                else:
                    self.set_status(400)
                    self.write({"error": "unknown query"})

        class FetchHandler(tornado.web.RequestHandler):
            def get(self):
                name = self.get_argument("name")
                version = self.get_argument("version", "latest")
                try:
                    payload, version = forge.load(name, version)
                except ValueError:
                    self.set_status(400)
                    return
                except (KeyError, OSError):
                    self.set_status(404)
                    return
                self.set_header("Content-Type",
                                "application/octet-stream")
                self.set_header("X-Package-Version", version)
                self.write(payload)

        class UploadHandler(tornado.web.RequestHandler):
            def post(self):
                if forge.upload_token:
                    import hmac as hmac_mod
                    auth = self.request.headers.get("Authorization", "")
                    want = "Bearer %s" % forge.upload_token
                    if not hmac_mod.compare_digest(auth, want):
                        self.set_status(401)
                        self.write({"error": "upload token required"})
                        return
                name = self.get_argument("name")
                version = self.get_argument("version")
                meta_json = self.get_argument("metadata", "{}")
                try:
                    forge.store(name, version, self.request.body,
                                json.loads(meta_json))
                except ValueError:
                    self.set_status(400)
                    self.write({"error": "illegal name or version"})
                    return
                self.write({"result": "ok"})

        app = tornado.web.Application([
            (r"/service", ServiceHandler),
            (r"/fetch", FetchHandler),
            (r"/upload", UploadHandler),
        ])
        from veles_tpu.http_util import BackgroundHTTPServer
        self._server_ = BackgroundHTTPServer(
            app, port=self.port, max_buffer_size=1 << 30)
        thread = self._server_.start()
        self.port = self._server_.port
        self.info("forge on http://127.0.0.1:%d/", self.port)
        return thread

    def stop(self):
        if self._server_ is not None:
            self._server_.stop()
