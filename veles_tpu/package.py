"""Workflow package export for the native inference runtime.

Counterpart of reference Workflow.package_export (workflow.py:868) which
zipped ``contents.json`` + per-array ``NNNN_name.npy`` for libVeles
(libVeles/src/workflow_loader.cc:41).  Design choices for this build,
documented for parity review:

- container is an uncompressed POSIX tar (the C++ runtime embeds a
  ~100-line ustar reader instead of vendoring zip/libarchive as the
  reference did via empty submodules);
- ``contents.json`` lists the INFERENCE chain (forward units only) in
  execution order, each with its stable class UUID (the C++
  UnitFactory key, reference unit_factory.cc:1-65), properties, and
  array file names;
- arrays are standard .npy; ``precision="float16"`` stores fp16 that the
  native loader widens to f32 on load (reference
  numpy_array_loader.cc fp16 path);
- dropout units are omitted (inverted dropout is identity at
  inference).
"""

import io
import json
import tarfile

import numpy

__all__ = ["export_workflow", "UNIT_UUIDS"]

#: stable class-name -> UUID registry mirrored in native/src/units.cc
UNIT_UUIDS = {
    "All2All":          "5a51b268-0001-4000-8000-76656c6573aa",
    "All2AllTanh":      "5a51b268-0002-4000-8000-76656c6573aa",
    "All2AllRELU":      "5a51b268-0003-4000-8000-76656c6573aa",
    "All2AllStrictRELU": "5a51b268-0004-4000-8000-76656c6573aa",
    "All2AllSigmoid":   "5a51b268-0005-4000-8000-76656c6573aa",
    "All2AllSoftmax":   "5a51b268-0006-4000-8000-76656c6573aa",
    "Conv":             "5a51b268-0011-4000-8000-76656c6573aa",
    "ConvTanh":         "5a51b268-0012-4000-8000-76656c6573aa",
    "ConvRELU":         "5a51b268-0013-4000-8000-76656c6573aa",
    "ConvStrictRELU":   "5a51b268-0014-4000-8000-76656c6573aa",
    "ConvSigmoid":      "5a51b268-0015-4000-8000-76656c6573aa",
    "MaxPooling":       "5a51b268-0021-4000-8000-76656c6573aa",
    "AvgPooling":       "5a51b268-0022-4000-8000-76656c6573aa",
    "MaxAbsPooling":    "5a51b268-0023-4000-8000-76656c6573aa",
    "ForwardTanh":      "5a51b268-0031-4000-8000-76656c6573aa",
    "ForwardRELU":      "5a51b268-0032-4000-8000-76656c6573aa",
    "ForwardStrictRELU": "5a51b268-0033-4000-8000-76656c6573aa",
    "ForwardSigmoid":   "5a51b268-0034-4000-8000-76656c6573aa",
    "InputJoiner":      "5a51b268-0041-4000-8000-76656c6573aa",
}


def _npy_bytes(arr, precision):
    if precision == "float16":
        arr = arr.astype(numpy.float16)
    else:
        arr = arr.astype(numpy.float32)
    buf = io.BytesIO()
    numpy.save(buf, arr)
    return buf.getvalue()


def _unit_properties(fwd):
    props = {"include_bias": bool(getattr(fwd, "include_bias", False))}
    for name in ("kx", "ky", "n_kernels", "sliding", "padding",
                 "output_sample_shape", "factor"):
        value = getattr(fwd, name, None)
        if value is not None:
            props[name] = list(value) if isinstance(value, tuple) else value
    return props


def _resolve_inputs(fwd, producer_by_array, loader):
    """Producer names for a unit, matched by Array object identity.

    Multi-input units expose ``inputs`` (a list of Arrays, e.g.
    InputJoiner); everything else exposes ``input``."""
    arrays = getattr(fwd, "inputs", None)
    if not arrays:
        arrays = [getattr(fwd, "input", None)]
    names = []
    for arr in arrays:
        if arr is None:
            continue
        key = id(arr)
        if key in producer_by_array:
            names.append(producer_by_array[key])
        elif loader is not None and arr is loader.minibatch_data:
            names.append("__input__")
        else:
            raise ValueError(
                "cannot resolve the producer of %s.input; the source "
                "must be another exported unit's output or the "
                "loader's minibatch_data" % type(fwd).__name__)
    return names


def export_workflow(workflow, path, precision="float32", units=None):
    """Write the inference package; returns the path.

    ``units``: explicit unit list for non-linear graphs (defaults to
    ``workflow.forwards``).  Links are recorded per unit (format 2) so
    the native runtime rebuilds the general DAG (reference
    workflow_loader.cc:73-120)."""
    from veles_tpu.models.dropout import DropoutForward

    loader = getattr(workflow, "loader", None)
    candidates = list(units if units is not None else workflow.forwards)
    forwards = [f for f in candidates
                if not isinstance(f, DropoutForward)]
    # outputs of dropped dropout units: consumers of these fall back to
    # the dropout's own producer chain (identity at inference)
    dropped_outputs = {
        id(f.output) for f in candidates
        if isinstance(f, DropoutForward) and f.output is not None}
    out_units = []
    files = {}
    counter = 0
    producer_by_array = {}
    names = []
    for i, fwd in enumerate(forwards):
        cls_name = type(fwd).__name__
        uuid = UNIT_UUIDS.get(cls_name)
        if uuid is None:
            raise ValueError(
                "%s has no stable UUID; extend UNIT_UUIDS + the native "
                "factory" % cls_name)
        name = "u%03d_%s" % (i, cls_name)
        names.append(name)
        arrays = {}
        for aname in ("weights", "bias"):
            arr = getattr(fwd, aname, None)
            if arr is not None and arr:
                arr.map_read()
                fname = "%04d_%s.npy" % (counter, aname)
                files[fname] = _npy_bytes(arr.mem, precision)
                arrays[aname] = fname
                counter += 1
        out_units.append({
            "uuid": uuid, "class": cls_name, "name": name,
            "properties": _unit_properties(fwd),
            "arrays": arrays,
        })
        output = getattr(fwd, "output", None)
        if output is not None:
            producer_by_array[id(output)] = name

    # link pass (dropout units were dropped: look through them by
    # resolving against the kept producers only)
    for fwd, unit_json in zip(forwards, out_units):
        prev_index = out_units.index(unit_json) - 1
        try:
            unit_json["inputs"] = _resolve_inputs(
                fwd, producer_by_array, loader)
        except ValueError:
            # a dropped dropout sat between this unit and its real
            # producer: fall back to the previous kept unit
            if prev_index < 0:
                unit_json["inputs"] = ["__input__"]
            else:
                unit_json["inputs"] = [names[prev_index]]

    units = out_units
    input_shape = (list(loader.minibatch_data.shape[1:])
                   if loader is not None and loader.minibatch_data
                   else None)
    contents = {
        "format": 2,
        "workflow": type(workflow).__name__,
        "checksum": workflow.checksum,
        "precision": precision,
        "input_shape": input_shape,
        "units": units,
    }
    files["contents.json"] = json.dumps(
        contents, indent=1, sort_keys=True).encode()

    with tarfile.open(path, "w") as tar:
        for name in sorted(files):
            data = files[name]
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    return path
