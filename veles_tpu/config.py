"""Global configuration tree.

TPU-native equivalent of the reference's autovivifying config system
(reference: veles/config.py:60,152,165). A :class:`Config` node creates child
nodes on attribute access, can be called to update leaves in bulk, supports
per-key protection against accidental overwrite, and renders itself as a tree.

Site overrides load in this order (later wins):
``/etc/default/veles_tpu`` -> ``~/.veles_tpu`` -> ``./site_config.py``.
Each is a Python file executed with ``root`` in scope.
"""

import os
import runpy
import threading

__all__ = ["Config", "root", "get", "validate_kwargs"]


class Config(object):
    """A node in the configuration tree.

    Attribute access auto-creates child ``Config`` nodes, so
    ``root.common.engine.precision = "float32"`` just works.  Calling a node
    with a mapping (or keyword arguments) updates the subtree recursively.
    """

    def __init__(self, path):
        self.__dict__["_path_"] = path
        self.__dict__["_protected_"] = set()

    @property
    def path(self):
        return self.__dict__["_path_"]

    def __call__(self, *args, **kwargs):
        if len(args) > 1:
            raise TypeError("Config accepts at most one positional mapping")
        if args:
            self.update(args[0])
        if kwargs:
            self.update(kwargs)
        return self

    def update(self, mapping):
        """Recursively merge ``mapping`` into this subtree."""
        if isinstance(mapping, Config):
            mapping = mapping.as_dict()
        if not isinstance(mapping, dict):
            raise TypeError("Config.update requires a dict, got %s" %
                            type(mapping))
        for key, value in mapping.items():
            if isinstance(value, dict):
                node = getattr(self, key)
                if not isinstance(node, Config):
                    node = Config("%s.%s" % (self.path, key))
                    setattr(self, key, node)
                node.update(value)
            else:
                setattr(self, key, value)
        return self

    def protect(self, *names):
        """Forbid future reassignment of the given child keys."""
        self.__dict__["_protected_"].update(names)

    def __getattr__(self, name):
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        node = Config("%s.%s" % (self.__dict__["_path_"], name))
        self.__dict__[name] = node
        return node

    def __setattr__(self, name, value):
        if name in self.__dict__["_protected_"]:
            raise AttributeError(
                "Config key %s.%s is protected" % (self.path, name))
        self.__dict__[name] = value

    def __contains__(self, name):
        return name in self.__dict__ and not name.endswith("_")

    def get(self, name, default=None):
        """Return the leaf value if it was explicitly set, else ``default``."""
        value = self.__dict__.get(name, default)
        if isinstance(value, Config):
            return default
        return value

    def as_dict(self):
        out = {}
        for key, value in self.__dict__.items():
            if key.endswith("_"):
                continue
            if isinstance(value, Config):
                sub = value.as_dict()
                if sub:
                    out[key] = sub
            else:
                out[key] = value
        return out

    def print_(self, indent=0, out=None):
        import sys
        out = out or sys.stdout
        for key, value in sorted(self.__dict__.items()):
            if key.endswith("_"):
                continue
            if isinstance(value, Config):
                out.write("%s%s:\n" % ("  " * indent, key))
                value.print_(indent + 1, out)
            else:
                out.write("%s%s: %r\n" % ("  " * indent, key, value))

    def __repr__(self):
        return "<Config %s: %s>" % (self.path, self.as_dict())

    # Pickle support: Config participates in workflow snapshots.
    def __getstate__(self):
        return {"path": self.path, "tree": self.as_dict(),
                "protected": sorted(self.__dict__["_protected_"])}

    def __setstate__(self, state):
        self.__dict__["_path_"] = state["path"]
        self.__dict__["_protected_"] = set()
        self.update(state["tree"])
        self.__dict__["_protected_"].update(state.get("protected", ()))


def get(node, default=None):
    """Return ``node`` unless it is an unset Config placeholder."""
    if isinstance(node, Config):
        return default
    return node


def validate_kwargs(caller, **kwargs):
    """Warn about keyword arguments that are unset Config placeholders."""
    for name, value in kwargs.items():
        if isinstance(value, Config):
            import warnings
            warnings.warn(
                "%s: keyword argument %r is an unset config key %s" %
                (type(caller).__name__, name, value.path))


#: The global configuration tree.
root = Config("root")

_DEFAULT_CACHE = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
    "veles_tpu")

root.common.update({
    "dirs": {
        "cache": _DEFAULT_CACHE,
        "datasets": os.environ.get(
            "VELES_DATA", os.path.join(_DEFAULT_CACHE, "datasets")),
        "snapshots": os.path.join(_DEFAULT_CACHE, "snapshots"),
        "user": os.path.expanduser("~/.veles_tpu_dir"),
    },
    "engine": {
        # Numeric precision for model math.  bfloat16 keeps the MXU fed;
        # float32 is the reference-compatible default for parity tests.
        "precision_type": os.environ.get("VELES_PRECISION", "float32"),
        # Speed/digits ladder (reference PRECISION_LEVEL analog):
        # 0 (default): fastest — f32 matmul products run a bf16x3 MXU
        #    decomposition (~5e-7 max rel err; |x| >= ~3.39e38 or inf
        #    is out of domain and yields NaN) with plain f32
        #    accumulation;
        # 1: true-f32 (HIGHEST) products + Kahan-compensated sums;
        # 2: level 1 plus Neumaier compensation (most digits, ~2x
        #    slower than level 1).  See ops/matmul.py.
        "precision_level": int(os.environ.get("VELES_PRECISION_LEVEL", "0")),
        "backend": os.environ.get("VELES_BACKEND", "auto"),
        # On TPU the per-unit dispatch loop is 8-25x slower than the
        # fused single-dispatch train step (QUALITY.json results_tpu
        # history), so StandardWorkflow fuses automatically when the
        # resolved device is a TPU.  Set VELES_AUTO_FUSE=0 (or the CLI
        # --no-fuse) to keep the per-unit graph for debugging.
        "auto_fuse": os.environ.get("VELES_AUTO_FUSE", "1") != "0",
        # Async double-buffered input pipeline riding on the fused
        # step (pipeline_input.Prefetcher): host fill + H2D of
        # minibatch k+1 overlap step k.  Applies to the auto-fused
        # path; VELES_PIPELINE_INPUT=0 opts out.
        "pipeline_input": os.environ.get(
            "VELES_PIPELINE_INPUT", "1") != "0",
    },
    "snapshot": {
        # --resume auto|PATH: restore the validated _current target (or
        # the given snapshot) before initialize; empty = fresh start
        "resume": "",
        # retention: keep only the newest N snapshots (+ best-by-metric
        # and the _current target); 0 = unlimited, reference parity
        "keep": 0,
    },
    "trace": {
        "run": False,
        "event_file": None,
    },
    "timings": False,
    "disable": {
        "plotting": False,
        "snapshotting": False,
        "publishing": False,
    },
    "test_dataset_root": os.environ.get("VELES_TEST_DATA", "/tmp/veles_tpu"),
    "web": {
        "host": "localhost",
        "port": 8090,
        "notification_interval": 1,
    },
    "graphics": {"multicast_address": "239.192.1.1"},
})

_site_lock = threading.Lock()
_site_loaded = False


def load_site_configs():
    """Execute site override files (idempotent)."""
    global _site_loaded
    with _site_lock:
        if _site_loaded:
            return
        _site_loaded = True
        for path in ("/etc/default/veles_tpu",
                     os.path.expanduser("~/.veles_tpu"),
                     os.path.join(os.getcwd(), "site_config.py")):
            if os.path.exists(path):
                try:
                    runpy.run_path(path, init_globals={"root": root})
                except Exception as exc:  # pragma: no cover
                    import warnings
                    warnings.warn("failed to load site config %s: %s" %
                                  (path, exc))
