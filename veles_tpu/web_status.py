"""Web status service: collects launcher status posts, serves a
dashboard.

Reference veles/web_status.py:113 (tornado + MongoDB): masters POST
periodic JSON status (launcher.py:852-885); the dashboard lists every
known session.  MongoDB is absent from this image, so retention is an
in-memory ring with optional JSONL persistence — the HTTP surface
(POST /update, GET /status.json, GET /) is equivalent.
"""

import json
import threading
import time
from collections import OrderedDict

from veles_tpu.logger import Logger

__all__ = ["WebStatusServer", "StatusReporter"]

_PAGE = """<!DOCTYPE html>
<html><head><title>veles-tpu status</title></head>
<body><h1>veles-tpu sessions</h1><table border=1 cellpadding=4>
<tr><th>id</th><th>workflow</th><th>mode</th><th>epoch</th>
<th>metrics</th><th>slaves</th><th>updated</th></tr>
%s</table></body></html>"""


class WebStatusServer(Logger):
    def __init__(self, port=0, persist_path=None, max_sessions=100):
        super(WebStatusServer, self).__init__()
        import tornado.web

        self.sessions = OrderedDict()
        self.max_sessions = max_sessions
        self.persist_path = persist_path
        server_self = self

        class UpdateHandler(tornado.web.RequestHandler):
            def post(self):
                data = json.loads(self.request.body or b"{}")
                server_self.record(data)
                self.write({"result": "ok"})

        class StatusHandler(tornado.web.RequestHandler):
            def get(self):
                self.set_header("Content-Type", "application/json")
                self.write(json.dumps(list(
                    server_self.sessions.values())))

        class PageHandler(tornado.web.RequestHandler):
            def get(self):
                rows = []
                for s in server_self.sessions.values():
                    rows.append(
                        "<tr>" + "".join(
                            "<td>%s</td>" % s.get(k, "")
                            for k in ("id", "workflow", "mode", "epoch",
                                      "metrics", "slaves", "updated")) +
                        "</tr>")
                self.write(_PAGE % "\n".join(rows))

        self.app = tornado.web.Application([
            (r"/update", UpdateHandler),
            (r"/status.json", StatusHandler),
            (r"/", PageHandler),
        ])
        self.port = port
        self._loop = None
        self._thread = None

    def record(self, data):
        data = dict(data)
        data["updated"] = time.strftime("%H:%M:%S")
        sid = data.get("id", "?")
        self.sessions[sid] = data
        self.sessions.move_to_end(sid)
        while len(self.sessions) > self.max_sessions:
            self.sessions.popitem(last=False)
        if self.persist_path:
            with open(self.persist_path, "a") as fout:
                fout.write(json.dumps(data) + "\n")

    def start_background(self):
        import asyncio

        import tornado.httpserver

        started = threading.Event()

        def serve():
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            server = tornado.httpserver.HTTPServer(self.app)
            sockets = tornado.netutil.bind_sockets(
                self.port, address="127.0.0.1")
            self.port = sockets[0].getsockname()[1]
            server.add_sockets(sockets)
            started.set()
            loop.run_forever()

        import tornado.netutil
        self._thread = threading.Thread(target=serve, daemon=True)
        self._thread.start()
        started.wait(5)
        self.info("web status on http://127.0.0.1:%d/", self.port)
        return self._thread

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


class StatusReporter(object):
    """Posts periodic session status to a WebStatusServer (the
    launcher-side half, reference launcher.py:852-885)."""

    def __init__(self, url, session_id, workflow):
        self.url = url.rstrip("/")
        self.session_id = session_id
        self.workflow = workflow

    def snapshot(self):
        decision = getattr(self.workflow, "decision", None)
        launcher = self.workflow.launcher
        return {
            "id": self.session_id,
            "workflow": type(self.workflow).__name__,
            "mode": getattr(launcher, "workflow_mode", "standalone"),
            "epoch": getattr(decision, "epoch_number", None),
            "metrics": getattr(decision, "epoch_metrics", None),
            "slaves": len(getattr(
                getattr(launcher, "_agent", None), "slaves", {}) or {}),
        }

    def post(self):
        import urllib.request
        req = urllib.request.Request(
            self.url + "/update",
            data=json.dumps(self.snapshot()).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())
