"""Web status service: collects launcher status posts, serves a
dashboard.

Reference veles/web_status.py:113 (tornado + MongoDB): masters POST
periodic JSON status (launcher.py:852-885) and structured log events;
the dashboard lists every known session with per-session history pages.
MongoDB is absent from this image, so persistence is sqlite (the same
stand-in the snapshot DB sink uses) — the HTTP surface covers the
reference roles: POST /update, POST /event, GET /status.json,
GET /session/<id>.json (status history), GET /events/<id>.json,
GET / (dashboard) and GET /session/<id> (detail page with metric
history).
"""

import json
import sqlite3
import threading
import time
from collections import OrderedDict

from veles_tpu.logger import Logger

__all__ = ["WebStatusServer", "StatusReporter"]

# Categorical series palette in fixed order, validated per mode with
# the dataviz six-checks validator (lightness band, chroma floor, CVD
# ΔE >= 8 adjacent, normal-vision floor, contrast vs surface); text in
# text tokens, light/dark selected (not auto-flipped).
_STYLE = """
:root {
  color-scheme: light;
  --surface-1: #fcfcfb; --text-primary: #0b0b0b;
  --text-secondary: #52514e; --grid: #e4e3df;
  --series-1: #2a78d6; --series-2: #d97706;
  --series-3: #0f8a6d; --series-4: #9d5ad1;
}
@media (prefers-color-scheme: dark) {
  :root { color-scheme: dark;
    --surface-1: #1a1a19; --text-primary: #ffffff;
    --text-secondary: #c3c2b7; --grid: #3a3936;
    --series-1: #3987e5; --series-2: #c98000;
    --series-3: #18a383; --series-4: #a368d6; }
}
body { background: var(--surface-1); color: var(--text-primary);
       font: 14px system-ui, sans-serif; margin: 24px; }
h1 { font-size: 18px; } a { color: var(--series-1); }
table { border-collapse: collapse; }
th, td { border: 1px solid var(--grid); padding: 4px 10px;
         text-align: left; }
th { color: var(--text-secondary); font-weight: 600; }
.num { font-variant-numeric: tabular-nums; }
svg.spark polyline { fill: none; stroke: var(--series-1);
                     stroke-width: 2; }
svg.spark text { fill: var(--text-secondary); font-size: 10px; }
svg.chart { display: block; margin: 8px 0; }
svg.chart line.grid { stroke: var(--grid); stroke-width: 1; }
svg.chart line.cross { stroke: var(--text-secondary);
                       stroke-width: 1; stroke-dasharray: 3 3; }
svg.chart text.axis { fill: var(--text-secondary); font-size: 10px; }
.legend { color: var(--text-secondary); font-size: 12px; }
.legend span { margin-right: 14px; }
.swatch { display: inline-block; width: 10px; height: 10px;
          border-radius: 2px; margin-right: 4px; }
#tip { visibility: hidden; border: 1px solid var(--grid);
       background: var(--surface-1); padding: 6px 10px;
       font-size: 12px; max-width: 420px; }
"""

_INDEX = """<!DOCTYPE html>
<html><head><title>veles-tpu status</title><style>%s</style></head>
<body><h1>veles-tpu sessions</h1>
<div id="tbl">%s</div>
<script>
setInterval(function () {
  fetch("/table").then(function (r) { return r.text(); })
    .then(function (t) { document.getElementById("tbl").innerHTML = t; });
}, 5000);
</script></body></html>
"""

_DETAIL = """<!DOCTYPE html>
<html><head><title>%(sid)s — veles-tpu</title><style>%(style)s</style>
</head><body data-sid="%(sid)s"><h1>session %(sid)s</h1>
<p><a href="/">&larr; all sessions</a></p>
<div id="chart">%(spark)s</div>
<div id="tip"></div>
<table id="posts"><tr><th>time</th><th>epoch</th><th>metrics</th>
<th>slaves</th></tr>%(rows)s</table>
<h1>events</h1>
<table id="events"><tr><th>time</th><th>event</th></tr>%(events)s
</table>
<script src="/static/live.js"></script>
</body></html>
"""


def _metric_history(history):
    """Extract a numeric series for ONE metric key — the first numeric
    key of the earliest post, tracked by name thereafter so a metrics
    dict that gains keys mid-run can't splice two different series."""
    def numeric(value):
        # bool is an int subclass; a {"converged": false} key must not
        # hijack the series
        return isinstance(value, (int, float)) \
            and not isinstance(value, bool)

    def items(metrics):
        # dict posts keep their keys; list posts (StatusReporter ships
        # decision.epoch_metrics = [test, validation, train]) key by
        # index
        if isinstance(metrics, dict):
            return list(metrics.items())
        if isinstance(metrics, (list, tuple)):
            return list(enumerate(metrics))
        return []

    key = None
    for post in history:
        for k, value in items(post.get("metrics")):
            if numeric(value):
                key = k
                break
        if key is not None:
            break
    if key is None:
        return []
    points = []
    for post in history:
        for k, value in items(post.get("metrics")):
            if k == key and numeric(value):
                points.append(float(value))
    return points


def _sparkline(points, width=220, height=48, label=True):
    """Inline-SVG sparkline: 2px line, last-value direct label, hover
    title with the range (single series — no legend)."""
    if len(points) < 2:
        return ""
    lo, hi = min(points), max(points)
    span = (hi - lo) or 1.0
    pad = 4
    w, h = width - 2 * pad - (46 if label else 0), height - 2 * pad
    coords = " ".join(
        "%.1f,%.1f" % (pad + w * i / (len(points) - 1),
                       pad + h * (1.0 - (p - lo) / span))
        for i, p in enumerate(points))
    tail = ("<text x='%d' y='%d'>%.4g</text>"
            % (width - 44, height // 2 + 4, points[-1]) if label else "")
    return ("<svg class='spark' width='%d' height='%d' role='img'>"
            "<title>%d points, min %.4g, max %.4g</title>"
            "<polyline points='%s'/>%s</svg>"
            % (width, height, len(points), lo, hi, coords, tail))


class _Store(object):
    """Session status + event retention: in-memory ring backed by an
    optional sqlite file (reference kept these in MongoDB)."""

    def __init__(self, db_path=None, max_sessions=100, max_history=500):
        self.sessions = OrderedDict()   # sid -> latest post, LRU order
        self.history = {}               # sid -> [posts]
        self.events = {}                # sid -> [(ts, text)]
        self.max_sessions = max_sessions
        self.max_history = max_history
        self._lock = threading.Lock()
        self._conn = None
        if db_path:
            self._conn = sqlite3.connect(
                db_path, check_same_thread=False)
            db = self._conn
            with db:
                db.execute("CREATE TABLE IF NOT EXISTS status ("
                           "sid TEXT, ts REAL, body TEXT)")
                db.execute("CREATE TABLE IF NOT EXISTS events ("
                           "sid TEXT, ts REAL, body TEXT)")
            # reload the most recently active sessions only, in recency
            # order so the LRU ring evicts the genuinely oldest first,
            # bounded per session by max_history
            recent = list(db.execute(
                "SELECT sid, MAX(ts) m FROM status GROUP BY sid "
                "ORDER BY m DESC LIMIT ?", (max_sessions,)))
            for sid, _ in reversed(recent):
                posts = [json.loads(body) for (body,) in db.execute(
                    "SELECT body FROM status WHERE sid = ? "
                    "ORDER BY ts DESC LIMIT ?", (sid, max_history))]
                posts.reverse()
                self.history[sid] = posts
                self.sessions[sid] = posts[-1]
                self.events[sid] = self._load_events(db, sid)
            # sessions that only posted events so far (a reporter may
            # post_event before its first status) keep their events too
            for (sid,) in db.execute(
                    "SELECT DISTINCT sid FROM events"):
                if sid not in self.events:
                    self.events[sid] = self._load_events(db, sid)

    def _load_events(self, db, sid):
        return [
            (time.strftime("%H:%M:%S", time.localtime(ts)), body)
            for ts, body in reversed(list(db.execute(
                "SELECT ts, body FROM events WHERE sid = ? "
                "ORDER BY ts DESC LIMIT ?", (sid, self.max_history))))]

    def list_sessions(self):
        with self._lock:
            return list(self.sessions.values())

    def get_history(self, sid):
        with self._lock:
            return list(self.history.get(sid, []))

    def get_events(self, sid):
        with self._lock:
            return list(self.events.get(sid, []))

    def close(self):
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None

    def _prune(self, db, table, sid):
        db.execute(
            "DELETE FROM %s WHERE sid = ? AND ts NOT IN (SELECT ts "
            "FROM %s WHERE sid = ? ORDER BY ts DESC LIMIT ?)"
            % (table, table), (sid, sid, self.max_history))

    def record(self, data):
        data = dict(data)
        data["updated"] = time.strftime("%H:%M:%S")
        sid = str(data.get("id", "?"))
        with self._lock:
            self.sessions[sid] = data
            self.sessions.move_to_end(sid)
            hist = self.history.setdefault(sid, [])
            hist.append(data)
            del hist[:-self.max_history]
            while len(self.sessions) > self.max_sessions:
                old, _ = self.sessions.popitem(last=False)
                self.history.pop(old, None)
                self.events.pop(old, None)
                if self._conn is not None:
                    with self._conn as db:
                        db.execute("DELETE FROM status WHERE sid = ?",
                                   (old,))
                        db.execute("DELETE FROM events WHERE sid = ?",
                                   (old,))
            if self._conn is not None:
                with self._conn as db:
                    db.execute("INSERT INTO status VALUES (?, ?, ?)",
                               (sid, time.time(), json.dumps(data)))
                    self._prune(db, "status", sid)
        return data

    def record_event(self, sid, text):
        sid = str(sid)
        with self._lock:
            if sid not in self.events and \
                    len(self.events) >= 2 * self.max_sessions:
                # event-only ids (no status post yet) are bounded too:
                # evict the first sid outside the session ring
                for old in list(self.events):
                    if old not in self.sessions:
                        del self.events[old]
                        if self._conn is not None:
                            with self._conn as db:
                                db.execute(
                                    "DELETE FROM events WHERE sid = ?",
                                    (old,))
                        break
            events = self.events.setdefault(sid, [])
            events.append((time.strftime("%H:%M:%S"), text))
            del events[:-self.max_history]
            if self._conn is not None:
                with self._conn as db:
                    db.execute("INSERT INTO events VALUES (?, ?, ?)",
                               (sid, time.time(), text))
                    self._prune(db, "events", sid)


class WebStatusServer(Logger):
    def __init__(self, port=0, persist_path=None, max_sessions=100,
                 db_path=None):
        super(WebStatusServer, self).__init__()
        import tornado.web

        # persist_path kept for backward compatibility: JSONL append
        self.store = _Store(db_path=db_path, max_sessions=max_sessions)
        self.persist_path = persist_path
        server_self = self

        class UpdateHandler(tornado.web.RequestHandler):
            def post(self):
                data = json.loads(self.request.body or b"{}")
                server_self.record(data)
                self.write({"result": "ok"})

        class EventHandler(tornado.web.RequestHandler):
            def post(self):
                data = json.loads(self.request.body or b"{}")
                server_self.store.record_event(
                    data.get("id", "?"), str(data.get("event", "")))
                self.write({"result": "ok"})

        class StatusHandler(tornado.web.RequestHandler):
            def get(self):
                self.set_header("Content-Type", "application/json")
                self.write(json.dumps(
                    server_self.store.list_sessions()))

        class HistoryHandler(tornado.web.RequestHandler):
            def get(self, sid):
                self.set_header("Content-Type", "application/json")
                self.write(json.dumps(
                    server_self.store.get_history(sid)))

        class EventsHandler(tornado.web.RequestHandler):
            def get(self, sid):
                self.set_header("Content-Type", "application/json")
                self.write(json.dumps(
                    server_self.store.get_events(sid)))

        class TableHandler(tornado.web.RequestHandler):
            def get(self):
                self.write(server_self._table_html())

        class PageHandler(tornado.web.RequestHandler):
            def get(self):
                self.write(_INDEX % (_STYLE, server_self._table_html()))

        class DetailHandler(tornado.web.RequestHandler):
            def get(self, sid):
                import html
                store = server_self.store
                history = store.get_history(sid)
                if not history:
                    raise tornado.web.HTTPError(404)
                rows = "".join(
                    "<tr><td>%s</td><td class='num'>%s</td>"
                    "<td>%s</td><td class='num'>%s</td></tr>"
                    % tuple(html.escape(str(v)) for v in (
                        p.get("updated", ""), p.get("epoch", ""),
                        json.dumps(p.get("metrics")),
                        p.get("slaves", "")))
                    for p in history[-100:])
                events = "".join(
                    "<tr><td>%s</td><td>%s</td></tr>"
                    % (html.escape(str(ts)), html.escape(str(text)))
                    for ts, text in store.get_events(sid)[-100:])
                self.write(_DETAIL % {
                    "sid": tornado.escape.xhtml_escape(sid),
                    "style": _STYLE,
                    "spark": _sparkline(
                        _metric_history(history), width=420, height=64),
                    "rows": rows, "events": events})

        import os
        self.app = tornado.web.Application([
            (r"/update", UpdateHandler),
            (r"/event", EventHandler),
            (r"/status.json", StatusHandler),
            (r"/session/([^/]+)\.json", HistoryHandler),
            (r"/events/([^/]+)\.json", EventsHandler),
            (r"/session/([^/]+)", DetailHandler),
            (r"/table", TableHandler),
            (r"/static/(.*)", tornado.web.StaticFileHandler,
             {"path": os.path.join(os.path.dirname(
                 os.path.abspath(__file__)), "web")}),
            (r"/", PageHandler),
        ])
        from veles_tpu.http_util import BackgroundHTTPServer
        self._server = BackgroundHTTPServer(self.app, port=port)

    @property
    def port(self):
        return self._server.port

    @property
    def sessions(self):
        return self.store.sessions

    def _table_html(self):
        import html
        from urllib.parse import quote
        rows = []
        for s in self.store.list_sessions():
            sid = str(s.get("id", "?"))
            spark = _sparkline(
                _metric_history(self.store.get_history(sid)),
                label=False)
            def cell(k):
                value = s.get(k)
                if value is None:
                    return ""
                if k == "serve" and isinstance(value, dict) and \
                        isinstance(value.get("segments"), dict):
                    # per-request-segment breakdown (docs/
                    # observability.md "Request tracing"): fold the
                    # histogram block into one p99-per-segment line so
                    # the cell answers "where does the time go" at a
                    # glance
                    value = dict(value)
                    segments = value.pop("segments")
                    value["segments_p99_ms"] = {
                        name: row.get("p99_ms")
                        for name, row in segments.items()}
                    return json.dumps(value)
                if k == "alerts" and isinstance(value, dict):
                    # the alerts column answers "is anything burning"
                    # at a glance: active alert names, or the firing
                    # total when everything has resolved
                    active = value.get("active") or []
                    if active:
                        return "FIRING: " + ", ".join(active)
                    fired = value.get("fired_total") or 0
                    return "ok (%d fired)" % fired if fired else "ok"
                if k in ("metrics", "health", "serve", "fleet"):
                    return json.dumps(value)
                return str(value)
            cells = "".join(
                "<td>%s</td>" % html.escape(cell(k))
                for k in ("workflow", "mode", "epoch", "metrics",
                          "health", "serve", "fleet", "alerts",
                          "slaves", "updated"))
            rows.append(
                "<tr><td><a href='/session/%s'>%s</a></td>%s<td>%s</td>"
                "</tr>" % (quote(sid, safe=""),
                           html.escape(sid), cells, spark))
        return ("<table><tr><th>id</th><th>workflow</th><th>mode</th>"
                "<th>epoch</th><th>metrics</th><th>health</th>"
                "<th>serve</th><th>fleet</th><th>alerts</th>"
                "<th>slaves</th><th>updated</th><th>trend</th></tr>"
                "%s</table>"
                % "\n".join(rows))

    def record(self, data):
        stamped = self.store.record(data)
        if self.persist_path:
            with open(self.persist_path, "a") as fout:
                fout.write(json.dumps(stamped) + "\n")

    def start_background(self):
        thread = self._server.start()
        self.info("web status on http://127.0.0.1:%d/", self.port)
        return thread

    def stop(self):
        # stop() joins the loop thread, draining in-flight handlers
        # before the DB closes
        self._server.stop()
        self.store.close()


class StatusReporter(object):
    """Posts periodic session status to a WebStatusServer (the
    launcher-side half, reference launcher.py:852-885)."""

    def __init__(self, url, session_id, workflow):
        self.url = url.rstrip("/")
        self.session_id = session_id
        self.workflow = workflow

    def snapshot(self):
        from veles_tpu.elastic import fleet_snapshot
        from veles_tpu.observe.metrics import health_snapshot
        from veles_tpu.observe.metrics import registry as _registry
        from veles_tpu.parallel.mesh import mesh_snapshot
        from veles_tpu.serve.batcher import serve_snapshot
        decision = getattr(self.workflow, "decision", None)
        launcher = self.workflow.launcher
        if _registry.peek("xla.step_flops") is not None:
            # refresh the live MFU gauge from the recent step-time
            # window so the health block carries it (reporter thread:
            # off the step path by construction)
            try:
                from veles_tpu.observe import xla_introspect
                xla_introspect.mfu_snapshot()
            except Exception:
                pass
        return {
            "id": self.session_id,
            "workflow": type(self.workflow).__name__,
            "mode": getattr(launcher, "workflow_mode", "standalone"),
            "epoch": getattr(decision, "epoch_number", None),
            "metrics": getattr(decision, "epoch_metrics", None),
            "slaves": len(getattr(
                getattr(launcher, "_agent", None), "slaves", {}) or {}),
            # numerics-health counters (docs/health.md) published at
            # the existing lazy-metric sync points: skip counts from
            # the decision unit, rollback budget from the snapshotter,
            # blacklist/quarantine from the server — reading them here
            # never forces a device sync
            "health": health_snapshot(),
            # serving health (docs/serving.md): queue depth, SLO
            # violations, latency percentiles — populated only on
            # processes that run the serve subsystem.  Multi-replica
            # servers (serve/router.py) add the replica count, the
            # per-replica queue depths and the hot-reload count; the
            # counters/percentiles are process-shared across replicas,
            # so this one block is already the fleet aggregate
            "serve": serve_snapshot() or None,
            # elastic-fleet state (docs/distributed.md, "Elasticity
            # contract"): membership epoch, live/blacklisted/
            # quarantined counts, speculative jobs in flight — only on
            # masters (the server publishes the elastic.* gauges)
            "fleet": fleet_snapshot() or None,
            # elastic device-mesh state (docs/distributed.md, "Elastic
            # mesh contract"): mesh size/epoch, reshard count, bytes of
            # train state moved, and the reshard-latency histogram —
            # only on masters training through a MeshManager
            "mesh": mesh_snapshot() or None,
            # the alert plane (docs/observability.md "Fleet
            # telemetry"): active + recently-fired alerts from the
            # process-global manager — the dashboard's alerts column
            "alerts": self._alerts_block(),
        }

    @staticmethod
    def _alerts_block():
        try:
            from veles_tpu.observe.alerts import alerts
            if not alerts.rules and not alerts.history(last=1):
                return None  # nothing configured, nothing ever fired
            return alerts.snapshot(history=4)
        except Exception:
            return None

    def _post_json(self, path, payload):
        import urllib.request
        req = urllib.request.Request(
            self.url + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())

    def post(self):
        return self._post_json("/update", self.snapshot())

    def post_event(self, event):
        """Forward one structured log event (reference streamed these
        into MongoDB for the dashboard's event browser)."""
        return self._post_json(
            "/event", {"id": self.session_id, "event": event})
