"""Plotter framework: units that ship themselves to the graphics
service for rendering.

TPU-native counterpart of reference veles/plotter.py:48 +
veles/graphics_server.py:65.  A Plotter unit's run() captures its
linked data and publishes a stripped pickle of itself on the
GraphicsServer's ZMQ PUB socket; a separate GraphicsClient process
renders with matplotlib (reference kept the same split so training
never blocks on rendering).  Payloads are gzip-pickled (the reference
used snappy, absent from this image; the codec byte is explicit so
more codecs can register).
"""

import gzip
import pickle

from veles_tpu.units import Unit

__all__ = ["Plotter"]


class Plotter(Unit):
    """Base plotter; subclasses implement render(axes)."""

    hide_from_registry = False
    SERVER_ATTR = "graphics_server"

    def __init__(self, workflow, **kwargs):
        super(Plotter, self).__init__(workflow, **kwargs)
        self.clear_plot = kwargs.get("clear_plot", False)
        self.redraw_plot = kwargs.get("redraw_plot", True)

    @property
    def graphics_server(self):
        launcher = self.launcher
        return getattr(launcher, "graphics_server", None)

    def run(self):
        if self.workflow is not None and \
                self.workflow.workflow_mode == "slave":
            return  # plotting happens on master/standalone only
        self.capture()
        server = self.graphics_server
        if server is not None:
            server.publish(self)

    def capture(self):
        """Snapshot linked data into plain attributes before pickling."""

    def render(self, axes):  # pragma: no cover - abstract
        """Draw onto a matplotlib axes."""
        raise NotImplementedError

    def __getstate__(self):
        state = super(Plotter, self).__getstate__()
        state["_links_from"] = {}
        state["_links_to"] = {}
        state["_workflow"] = None
        return state


def dumps(plotter):
    return b"g" + gzip.compress(
        pickle.dumps(plotter, protocol=pickle.HIGHEST_PROTOCOL), 1)


def loads(blob):
    codec, payload = blob[:1], blob[1:]
    if codec == b"g":
        return pickle.loads(gzip.decompress(payload))
    if codec == b"r":
        return pickle.loads(payload)
    raise ValueError("unknown plot codec %r" % codec)
