"""Command-line argument registry.

TPU-native counterpart of the reference's metaclass-based argparse
aggregation (reference: veles/cmdline.py:61,86).  Any class whose metaclass
is :class:`CommandLineArgumentsRegistry` (or that subclasses
:class:`CommandLineBase`) may define a classmethod ``init_parser(parser)``
adding its own flags; :func:`build_parser` folds every registered class's
flags into one parser for the CLI.
"""

import argparse

__all__ = ["CommandLineArgumentsRegistry", "CommandLineBase", "build_parser"]


class CommandLineArgumentsRegistry(type):
    """Metaclass collecting classes that contribute CLI arguments."""

    classes = []

    def __init__(cls, name, bases, namespace):
        super(CommandLineArgumentsRegistry, cls).__init__(
            name, bases, namespace)
        if "init_parser" in namespace:
            CommandLineArgumentsRegistry.classes.append(cls)


class CommandLineBase(object, metaclass=CommandLineArgumentsRegistry):
    """Convenience base for classes contributing CLI arguments."""

    @classmethod
    def init_parser(cls, parser):
        return parser


def build_parser(**kwargs):
    """Build one parser from every registered contributor."""
    parser = argparse.ArgumentParser(
        prog="veles_tpu",
        description="VELES-TPU: a TPU-native distributed deep learning "
                    "platform", **kwargs)
    seen = set()
    for cls in CommandLineArgumentsRegistry.classes:
        init = cls.__dict__.get("init_parser")
        if init is None or init in seen:
            continue
        seen.add(init)
        init.__get__(None, cls)(parser)
    return parser
