"""Command-line argument registry.

TPU-native counterpart of the reference's metaclass-based argparse
aggregation (reference: veles/cmdline.py:61,86).  Any class whose metaclass
is :class:`CommandLineArgumentsRegistry` (or that subclasses
:class:`CommandLineBase`) may define a classmethod ``init_parser(parser)``
adding its own flags; :func:`build_parser` folds every registered class's
flags into one parser for the CLI.  A contributor may also define a
classmethod ``apply_args(args)`` — :func:`apply_parsed_args` fans the
parsed namespace back out so each class can install its settings
(usually into the ``root`` config tree its constructor consults).
"""

import argparse

__all__ = ["CommandLineArgumentsRegistry", "CommandLineBase",
           "build_parser", "apply_parsed_args"]


class CommandLineArgumentsRegistry(type):
    """Metaclass collecting classes that contribute CLI arguments."""

    classes = []

    def __init__(cls, name, bases, namespace):
        super(CommandLineArgumentsRegistry, cls).__init__(
            name, bases, namespace)
        if "init_parser" in namespace or "apply_args" in namespace:
            CommandLineArgumentsRegistry.classes.append(cls)


class CommandLineBase(object, metaclass=CommandLineArgumentsRegistry):
    """Convenience base for classes contributing CLI arguments."""

    @classmethod
    def init_parser(cls, parser):
        return parser


def _import_standard_contributors():
    """Registration happens at class creation; pull in the framework
    modules that contribute flags so the CLI is complete regardless of
    what the workflow file imports."""
    import veles_tpu.client  # noqa: F401
    import veles_tpu.launcher  # noqa: F401
    import veles_tpu.server  # noqa: F401
    import veles_tpu.snapshotter  # noqa: F401


def build_parser(**kwargs):
    """Build one parser from every registered contributor."""
    _import_standard_contributors()
    parser = argparse.ArgumentParser(
        prog="veles_tpu",
        description="VELES-TPU: a TPU-native distributed deep learning "
                    "platform", **kwargs)
    seen = set()
    for cls in CommandLineArgumentsRegistry.classes:
        init = cls.__dict__.get("init_parser")
        if init is None or init in seen:
            continue
        seen.add(init)
        init.__get__(None, cls)(parser)
    return parser


def apply_parsed_args(args):
    """Fan the parsed namespace back out to every contributor that
    defines ``apply_args`` (constructors then read the settings from
    the config tree)."""
    seen = set()
    for cls in CommandLineArgumentsRegistry.classes:
        apply = cls.__dict__.get("apply_args")
        if apply is None or apply in seen:
            continue
        seen.add(apply)
        apply.__get__(None, cls)(args)
