"""FullBatchLoader — whole dataset resident on device (HBM).

TPU-native counterpart of reference veles/loader/fullbatch.py:79,467.
Preserved semantics: `create_originals` host allocation, validation
re-split by ratio, normalization applied ONCE to the original dataset at
initialize (reference fullbatch.py:336-347), minibatch gather by shuffled
index window, zero-padding of short minibatches, labels mapped to ints up
front.

TPU redesign (reference's GPU path was a per-step __global gather kernel,
ocl/fullbatch_loader.cl:5-50): the dataset is `device_put` once into HBM;
each serve step runs ops.gather.gather_minibatch — a Pallas kernel whose
scalar-prefetched index window routes per-sample DMAs — and adopts the
result as the device-side minibatch with NO host round-trip
(Array.set_device_array).  On the numpy backend the same contract runs
through the host path, which is what the test base uses for parity
checks.
"""

import numpy

from veles_tpu.backends import NumpyDevice
from veles_tpu.loader.base import (
    Loader, LoaderError, LoaderMSEMixin, TRAIN, VALID)
from veles_tpu.memory import Array
from veles_tpu import ops

__all__ = ["FullBatchLoader", "FullBatchLoaderMSE"]


class FullBatchLoader(Loader):
    """Dataset in one Array; minibatches gathered on device."""

    def __init__(self, workflow, **kwargs):
        super(FullBatchLoader, self).__init__(workflow, **kwargs)
        self.validation_ratio = kwargs.get("validation_ratio", None)
        self.on_device = kwargs.get("on_device", True)
        self.original_data = Array()
        self.original_labels = []
        self.device = None
        self.dtype = numpy.dtype(kwargs.get("dtype", numpy.float32))

    @staticmethod
    def _coerce_array(value):
        """Accept `loader.original_data = ndarray` (the natural user
        assignment) as well as a prepared Array."""
        if isinstance(value, Array):
            return value
        arr = Array()
        if value is not None:
            arr.mem = numpy.ascontiguousarray(value)
        return arr

    @property
    def original_data(self):
        return self._original_data

    @original_data.setter
    def original_data(self, value):
        self._original_data = self._coerce_array(value)

    @property
    def original_labels(self):
        return self._original_labels

    @original_labels.setter
    def original_labels(self, value):
        # ndarray assignment is the natural user move; the mapping pass
        # below needs a plain list (labels may be any hashable)
        if isinstance(value, numpy.ndarray):
            value = value.tolist()
        self._original_labels = [] if value is None else value

    def init_unpickled(self):
        super(FullBatchLoader, self).init_unpickled()
        # trailing-underscore attrs are not pickled; the mapped labels
        # are rebuilt from original_labels by _map_original_labels()
        self._mapped_original_labels_ = Array()

    @property
    def shape(self):
        if not self.original_data:
            raise LoaderError("load_data() has not created original_data")
        return self.original_data.shape[1:]

    def create_originals(self, dshape, labels=True):
        """Allocate original_data (+labels) for load_data() to fill."""
        self.original_data.mem = numpy.zeros(
            (self.total_samples,) + tuple(dshape), self.dtype)
        if labels:
            self._mapped_original_labels_.mem = numpy.zeros(
                self.total_samples, Loader.LABEL_DTYPE)
            self.original_labels[:] = [None] * self.total_samples

    def initialize(self, device=None, **kwargs):
        self.device = device
        result = super(FullBatchLoader, self).initialize(**kwargs)
        self.analyze_original_dataset()
        self._map_original_labels()
        if self._use_device_path():
            # one-time HBM residency; per-step gathers read from here
            self.original_data.initialize(self.device)
            self.original_data.unmap()
            if self.has_labels:
                self._mapped_original_labels_.initialize(self.device)
                self._mapped_original_labels_.unmap()
            self.shuffled_indices.initialize(self.device)
        return result

    def _use_device_path(self):
        return (self.on_device and self.device is not None and
                not isinstance(self.device, NumpyDevice) and
                self.device.exists)

    def create_minibatch_data(self):
        self.minibatch_data.mem = numpy.zeros(
            (self.max_minibatch_size,) + self.shape, self.dtype)

    # -- analysis (once, on originals) --------------------------------------

    def analyze_dataset(self):
        pass  # replaced by analyze_original_dataset after super().initialize

    def normalize_minibatch(self):
        pass  # originals are already normalized

    def analyze_original_dataset(self):
        if self.class_lengths[TRAIN] > 0:
            self.normalizer.analyze(
                self.original_data.mem[self.class_end_offsets[VALID]:])
        elif not self.normalizer.initialized:
            raise LoaderError(
                "no train samples and the normalizer is uninitialized")
        self.normalizer.normalize(self.original_data.mem)

    def _map_original_labels(self):
        if not self.original_labels or all(
                l is None for l in self.original_labels):
            self.original_labels = []
            return
        if not self.labels_mapping:
            uniques = sorted(set(self.original_labels))
            self.labels_mapping.update(
                (lbl, i) for i, lbl in enumerate(uniques))
        if self._mapped_original_labels_.mem is None:
            # labels assigned directly (no create_originals call)
            self._mapped_original_labels_.mem = numpy.zeros(
                len(self.original_labels), Loader.LABEL_DTYPE)
        self._mapped_original_labels_.map_write()
        for i, raw in enumerate(self.original_labels):
            self._mapped_original_labels_[i] = self.labels_mapping[raw]
        self.minibatch_labels.mem = numpy.zeros(
            self.max_minibatch_size, Loader.LABEL_DTYPE)

    def _build_labels_mapping_if_needed(self):
        self._map_original_labels()

    # -- validation re-split (reference fullbatch.py:349) --------------------

    def resize_validation(self, ratio=None):
        """Move a random train slice into validation (index rearrange)."""
        ratio = self.validation_ratio if ratio is None else ratio
        if ratio is None:
            return
        if ratio <= 0:
            self.class_lengths[TRAIN] += self.class_lengths[VALID]
            self.class_lengths[VALID] = 0
            self._calc_class_end_offsets()
            return
        total = self.class_lengths[VALID] + self.class_lengths[TRAIN]
        want_valid = int(numpy.round(ratio * total))
        offset = self.class_end_offsets[VALID] - self.class_lengths[VALID]
        window = numpy.arange(offset, offset + total)
        self.prng.shuffle(window)
        order = numpy.concatenate([
            numpy.sort(window[:want_valid]),
            numpy.sort(window[want_valid:])])
        self.original_data.map_write()
        self.original_data.mem[offset:offset + total] = \
            self.original_data.mem[order]
        if self.original_labels:
            self.original_labels[offset:offset + total] = [
                self.original_labels[i] for i in order]
        self.class_lengths[VALID] = want_valid
        self.class_lengths[TRAIN] = total - want_valid
        self._calc_class_end_offsets()

    # -- serving -------------------------------------------------------------

    def fill_indices(self, start_offset, count):
        if not self._use_device_path():
            return super(FullBatchLoader, self).fill_indices(
                start_offset, count)
        self.shuffled_indices.map_read()
        window = numpy.full(
            self.max_minibatch_size, 0, Loader.INDEX_DTYPE)
        window[:count] = \
            self.shuffled_indices.mem[start_offset:start_offset + count]
        self.minibatch_indices.mem[:count] = window[:count]
        self.minibatch_indices.mem[count:] = -1
        idx_dev = self.device.put(window)
        data = ops.gather_minibatch(
            self.original_data.devmem, idx_dev, out_dtype=self.dtype)
        if count < self.max_minibatch_size:
            data = self._zero_tail(data, count)
        self.minibatch_data.set_device_array(data, self.device)
        if self.has_labels:
            labels = ops.gather_labels(
                self._mapped_original_labels_.devmem, idx_dev)
            if count < self.max_minibatch_size:
                labels = self._mask_tail_labels(labels, count)
            self.minibatch_labels.set_device_array(labels, self.device)
        return True

    @staticmethod
    def _zero_tail(data, count):
        import jax.numpy as jnp
        mask = (jnp.arange(data.shape[0]) < count)
        return data * mask.astype(data.dtype).reshape(
            (-1,) + (1,) * (data.ndim - 1))

    @staticmethod
    def _mask_tail_labels(labels, count):
        import jax.numpy as jnp
        return jnp.where(jnp.arange(labels.shape[0]) < count, labels, -1)

    def fill_minibatch(self):
        idx = self.minibatch_indices.mem[:self.minibatch_size]
        self.minibatch_data.map_write()
        self.original_data.map_read()
        self.minibatch_data.mem[:self.minibatch_size] = \
            self.original_data.mem[idx]
        if self.has_labels:
            self._mapped_original_labels_.map_read()
            self.minibatch_labels.map_write()
            self.minibatch_labels.mem[:self.minibatch_size] = \
                self._mapped_original_labels_.mem[idx]

    def map_minibatch_labels(self):
        pass  # labels were mapped once in _map_original_labels


class FullBatchLoaderMSE(LoaderMSEMixin, FullBatchLoader):
    """FullBatch variant serving (data, target) pairs
    (reference: fullbatch.py:467-566)."""

    def __init__(self, workflow, **kwargs):
        super(FullBatchLoaderMSE, self).__init__(workflow, **kwargs)
        self.original_targets = Array()

    @property
    def original_targets(self):
        return self._original_targets

    @original_targets.setter
    def original_targets(self, value):
        self._original_targets = self._coerce_array(value)

    def create_minibatch_data(self):
        super(FullBatchLoaderMSE, self).create_minibatch_data()
        self.minibatch_targets.mem = numpy.zeros(
            (self.max_minibatch_size,) + self.original_targets.shape[1:],
            self.dtype)

    def initialize(self, device=None, **kwargs):
        result = super(FullBatchLoaderMSE, self).initialize(
            device=device, **kwargs)
        if self.class_lengths[TRAIN] > 0:
            self.target_normalizer.analyze(self.original_targets.mem)
        self.target_normalizer.normalize(self.original_targets.mem)
        if self._use_device_path():
            self.original_targets.initialize(self.device)
            self.original_targets.unmap()
        return result

    def fill_indices(self, start_offset, count):
        filled = super(FullBatchLoaderMSE, self).fill_indices(
            start_offset, count)
        if not filled:
            return False
        window = numpy.zeros(self.max_minibatch_size, Loader.INDEX_DTYPE)
        window[:count] = self.minibatch_indices.mem[:count]
        idx_dev = self.device.put(window)
        targets = ops.gather_minibatch(
            self.original_targets.devmem, idx_dev, out_dtype=self.dtype)
        if count < self.max_minibatch_size:
            targets = self._zero_tail(targets, count)
        self.minibatch_targets.set_device_array(targets, self.device)
        return True

    def fill_minibatch(self):
        super(FullBatchLoaderMSE, self).fill_minibatch()
        idx = self.minibatch_indices.mem[:self.minibatch_size]
        self.original_targets.map_read()
        self.minibatch_targets.map_write()
        self.minibatch_targets.mem[:self.minibatch_size] = \
            self.original_targets.mem[idx]
