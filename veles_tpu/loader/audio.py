"""Audio file loader.

Counterpart of reference veles/loader/libsndfile_loader.py (libsndfile
through ctypes).  This build decodes WAV through scipy.io.wavfile
(falling back to the stdlib ``wave`` module), normalizes to float32
[-1, 1], and serves fixed-length windows as samples — the
reference's snd-file-to-minibatch role without a native dependency.
"""

import os
import wave

import numpy

from veles_tpu.loader.base import LoaderError
from veles_tpu.loader.fullbatch import FullBatchLoader

__all__ = ["read_audio", "AudioFileLoader"]

AUDIO_EXTENSIONS = (".wav", ".wave")


def read_audio(path):
    """-> (float32 samples in [-1, 1] shaped (frames, channels), rate)."""
    try:
        from scipy.io import wavfile
        rate, data = wavfile.read(path)
    except ImportError:  # pragma: no cover - scipy is baked in
        with wave.open(path, "rb") as wav:
            rate = wav.getframerate()
            frames = wav.readframes(wav.getnframes())
            width = wav.getsampwidth()
            dtype = {1: numpy.uint8, 2: numpy.int16,
                     4: numpy.int32}[width]
            data = numpy.frombuffer(frames, dtype).reshape(
                -1, wav.getnchannels())
    if data.ndim == 1:
        data = data[:, None]
    if data.dtype == numpy.uint8:
        out = (data.astype(numpy.float32) - 128.0) / 128.0
    elif numpy.issubdtype(data.dtype, numpy.integer):
        out = data.astype(numpy.float32) / float(
            numpy.iinfo(data.dtype).max)
    else:
        out = data.astype(numpy.float32)
    return out, rate


class AudioFileLoader(FullBatchLoader):
    """Scans a directory-per-class tree of audio files; each sample is
    one ``window_frames``-long mono window (files are averaged across
    channels and chopped; short files are zero-padded).

    kwargs: train_dir / validation_dir / test_dir, window_frames
    (default 1024), stride_frames (default = window).
    """

    def __init__(self, workflow, **kwargs):
        super(AudioFileLoader, self).__init__(workflow, **kwargs)
        self.dirs = (kwargs.get("test_dir"),
                     kwargs.get("validation_dir"),
                     kwargs.get("train_dir"))
        self.window_frames = int(kwargs.get("window_frames", 1024))
        self.stride_frames = int(
            kwargs.get("stride_frames", self.window_frames))
        self.sampling_rate = None

    def _scan(self, base):
        out = []
        if not base:
            return out
        for label in sorted(os.listdir(base)):
            cdir = os.path.join(base, label)
            if not os.path.isdir(cdir):
                continue
            for fname in sorted(os.listdir(cdir)):
                if fname.lower().endswith(AUDIO_EXTENSIONS):
                    out.append((os.path.join(cdir, fname), label))
        return out

    def _windows(self, path):
        data, rate = read_audio(path)
        if self.sampling_rate is None:
            self.sampling_rate = rate
        elif rate != self.sampling_rate:
            raise LoaderError(
                "%s sampling rate %d != %d" %
                (path, rate, self.sampling_rate))
        mono = data.mean(axis=1)
        if len(mono) < self.window_frames:
            mono = numpy.pad(mono,
                             (0, self.window_frames - len(mono)))
        wins = []
        for start in range(
                0, len(mono) - self.window_frames + 1,
                self.stride_frames):
            wins.append(mono[start:start + self.window_frames])
        return wins

    def load_data(self):
        splits = [self._scan(d) for d in self.dirs]
        data, labels, lengths = [], [], []
        for files in splits:
            count = 0
            for path, label in files:
                for win in self._windows(path):
                    data.append(win)
                    labels.append(label)
                    count += 1
            lengths.append(count)
        if not data:
            raise LoaderError("no audio samples found")
        self.original_data = numpy.stack(data).astype(self.dtype)
        self.original_labels = labels
        self.class_lengths[:] = lengths
