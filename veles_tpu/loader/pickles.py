"""Pickled-array dataset loader (reference veles/loader/pickles.py:
55-215): each split is a pickle file containing either an array, an
(data, labels) tuple, or a {"data": ..., "labels": ...} dict."""

import pickle

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader

__all__ = ["PicklesLoader"]


def _unpack(obj):
    if isinstance(obj, dict):
        return numpy.asarray(obj["data"]), obj.get("labels")
    if isinstance(obj, tuple) and len(obj) == 2:
        return numpy.asarray(obj[0]), obj[1]
    return numpy.asarray(obj), None


class PicklesLoader(FullBatchLoader):
    def __init__(self, workflow, **kwargs):
        super(PicklesLoader, self).__init__(workflow, **kwargs)
        self.paths = (kwargs.get("test_path"),
                      kwargs.get("validation_path"),
                      kwargs.get("train_path"))

    def load_data(self):
        datas, labels = [], []
        for i, path in enumerate(self.paths):
            if not path:
                self.class_lengths[i] = 0
                datas.append(None)
                labels.append(None)
                continue
            with open(path, "rb") as fin:
                data, lbl = _unpack(pickle.load(fin))
            self.class_lengths[i] = len(data)
            datas.append(data)
            labels.append(lbl)
        self._calc_class_end_offsets()
        shape = next(d for d in datas if d is not None).shape[1:]
        has_labels = any(l is not None for l in labels)
        self.create_originals(shape, labels=has_labels)
        offset = 0
        for data, lbl in zip(datas, labels):
            if data is None:
                continue
            self.original_data.mem[offset:offset + len(data)] = data
            if has_labels:
                for j in range(len(data)):
                    self.original_labels[offset + j] = (
                        lbl[j] if lbl is not None else -1)
            offset += len(data)
