"""Data layer: minibatch loaders.

TPU-native counterpart of reference veles/loader/ (18 files).  The
minibatch *contract* — TEST/VALID/TRAIN class triple, per-epoch shuffling,
epoch/last-minibatch flags, the master–slave index-window protocol with
failed-minibatch requeue — is preserved verbatim so the distributed
semantics carry over; the device path is redesigned: the dataset lives in
HBM as a jax.Array and every minibatch is one Pallas gather
(ops.gather), not a host-side copy loop.
"""

from veles_tpu.loader.base import (  # noqa: F401
    Loader, LoaderMSEMixin, LoaderError, TEST, VALID, TRAIN, CLASS_NAME)
from veles_tpu.loader.fullbatch import (  # noqa: F401
    FullBatchLoader, FullBatchLoaderMSE)
from veles_tpu.loader.audio import AudioFileLoader  # noqa: F401
from veles_tpu.loader.hdfs import (  # noqa: F401
    HdfsTextLoader, WebHdfsClient)
