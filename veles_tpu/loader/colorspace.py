"""Numpy color-space conversions for the image loaders.

Counterpart of the reference ImageLoader's color handling
(reference: veles/loader/image.py:106,416-428 — any source space is
routed to the target via cv2.cvtColor, with BGR as the fallback hub).
Implemented in pure numpy so the capability does not depend on an
OpenCV build, but following cv2's numeric conventions exactly, so a
cv2-produced and a numpy-produced tensor are interchangeable:

- uint8 images: channel values in [0, 255]; HSV hue is degrees/2 in
  [0, 180); YCR_CB uses the BT.601 matrix with delta 128.
- float images (expected in [0, 1]): HSV hue is degrees in [0, 360);
  YCR_CB delta is 0.5.
- GRAY uses the BT.601 luma weights (0.299 R + 0.587 G + 0.114 B) and
  comes back as a 2-D array, like cv2.

Conversions route through an RGB hub, so every (src, dst) pair in
SPACES works — including e.g. GRAY -> HSV, which cv2 has no direct
code for (the reference bounced such pairs through BGR the same way).
"""

import numpy

__all__ = ["convert", "channels", "SPACES"]

SPACES = ("GRAY", "RGB", "BGR", "HSV", "YCR_CB")
_CHANNELS = {"GRAY": 1, "RGB": 3, "BGR": 3, "HSV": 3, "YCR_CB": 3}
_ALIASES = {"YCRCB": "YCR_CB", "GREY": "GRAY"}

# BT.601 (the cv2 forward constants); the inverse is DERIVED from the
# forward matrix rather than copied from cv2's rounded 1.403/1.773
# table, so a convert round-trip is lossless to float precision
_LUMA = numpy.array([0.299, 0.587, 0.114], numpy.float32)
_CR_SCALE, _CB_SCALE = 0.713, 0.564
_CR_TO_R = 1.0 / _CR_SCALE
_CB_TO_B = 1.0 / _CB_SCALE
_CR_TO_G = -_LUMA[0] / (_CR_SCALE * _LUMA[1])
_CB_TO_G = -_LUMA[2] / (_CB_SCALE * _LUMA[1])


def _norm_space(space):
    s = str(space).upper()
    s = _ALIASES.get(s, s)
    if s not in _CHANNELS:
        raise ValueError("unknown color space %r (choose from %s)" %
                         (space, ", ".join(SPACES)))
    return s


def channels(space):
    """Channel count of a color space (reference COLOR_CHANNELS_MAP,
    veles/loader/image.py:70)."""
    return _CHANNELS[_norm_space(space)]


def convert(img, src, dst):
    """Convert ``img`` from color space ``src`` to ``dst``.

    uint8 in -> uint8 out; any float in -> float32 out.  GRAY output
    is 2-D; GRAY input may be (H, W) or (H, W, 1).
    """
    src, dst = _norm_space(src), _norm_space(dst)
    img = numpy.asarray(img)
    if src == dst:
        return img
    is_u8 = img.dtype == numpy.uint8
    rgb = _to_rgb(_canonical(img, src, is_u8), src)
    return _emit(_from_rgb(rgb, dst), dst, is_u8)


def _canonical(img, src, is_u8):
    """To float canonical form: channels in [0, 1], HSV hue in
    degrees."""
    x = img.astype(numpy.float32)
    if src == "GRAY" and x.ndim == 3:
        x = x[..., 0]
    if is_u8:
        if src == "HSV":
            x = numpy.stack([x[..., 0] * 2.0, x[..., 1] / 255.0,
                             x[..., 2] / 255.0], axis=-1)
        else:
            x = x / 255.0
    return x


def _to_rgb(x, src):
    if src == "RGB":
        return x
    if src == "BGR":
        return x[..., ::-1]
    if src == "GRAY":
        return numpy.repeat(x[..., None], 3, axis=-1)
    if src == "HSV":
        return _hsv_to_rgb(x)
    # YCR_CB
    y = x[..., 0]
    cr = x[..., 1] - 0.5
    cb = x[..., 2] - 0.5
    return numpy.stack([y + _CR_TO_R * cr,
                        y + _CR_TO_G * cr + _CB_TO_G * cb,
                        y + _CB_TO_B * cb], axis=-1)


def _from_rgb(rgb, dst):
    if dst == "RGB":
        return rgb
    if dst == "BGR":
        return rgb[..., ::-1]
    if dst == "GRAY":
        return rgb @ _LUMA
    if dst == "HSV":
        return _rgb_to_hsv(rgb)
    # YCR_CB
    y = rgb @ _LUMA
    cr = (rgb[..., 0] - y) * _CR_SCALE + 0.5
    cb = (rgb[..., 2] - y) * _CB_SCALE + 0.5
    return numpy.stack([y, cr, cb], axis=-1)


def _emit(x, dst, is_u8):
    """From float canonical form back to the output encoding."""
    if not is_u8:
        if dst != "HSV":
            x = numpy.clip(x, 0.0, 1.0)
        return numpy.ascontiguousarray(x.astype(numpy.float32))
    if dst == "HSV":
        x = numpy.stack([x[..., 0] / 2.0, x[..., 1] * 255.0,
                         x[..., 2] * 255.0], axis=-1)
    else:
        x = x * 255.0
    return numpy.ascontiguousarray(
        numpy.clip(numpy.round(x), 0, 255).astype(numpy.uint8))


def _rgb_to_hsv(rgb):
    """RGB [0,1] -> (H degrees [0,360), S [0,1], V [0,1])."""
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    v = numpy.max(rgb, axis=-1)
    c = v - numpy.min(rgb, axis=-1)
    safe = numpy.where(c > 0, c, 1.0)
    h = numpy.where(
        v == r, ((g - b) / safe) % 6.0,
        numpy.where(v == g, (b - r) / safe + 2.0,
                    (r - g) / safe + 4.0))
    h = numpy.where(c > 0, h * 60.0, 0.0)
    s = numpy.where(v > 0, c / numpy.where(v > 0, v, 1.0), 0.0)
    return numpy.stack([h, s, v], axis=-1)


def _hsv_to_rgb(hsv):
    """(H degrees, S [0,1], V [0,1]) -> RGB [0,1]."""
    h6 = (hsv[..., 0] / 60.0) % 6.0
    s, v = hsv[..., 1], hsv[..., 2]
    i = numpy.floor(h6)
    f = h6 - i
    p = v * (1.0 - s)
    q = v * (1.0 - f * s)
    t = v * (1.0 - (1.0 - f) * s)
    i = i.astype(numpy.int32)
    r = numpy.choose(i, [v, q, p, p, t, v])
    g = numpy.choose(i, [t, v, v, q, p, p])
    b = numpy.choose(i, [p, p, t, v, v, q])
    return numpy.stack([r, g, b], axis=-1)
