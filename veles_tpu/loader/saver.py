"""Minibatch stream saver/replayer.

Reference veles/loader/saver.py:69,182: MinibatchesSaver dumps every
served minibatch into a compressed stream so expensive preprocessing
runs once; MinibatchesLoader replays the stream as a drop-in loader.
Stream format here: gzip-framed pickles, one record per minibatch, with
a header record carrying shapes/class_lengths.
"""

import gzip
import pickle

import numpy

from veles_tpu.loader.base import Loader
from veles_tpu.units import Unit

__all__ = ["MinibatchesSaver", "MinibatchesLoader"]


class MinibatchesSaver(Unit):
    """Link after a loader; writes each served minibatch."""

    def __init__(self, workflow, **kwargs):
        super(MinibatchesSaver, self).__init__(workflow, **kwargs)
        self.path = kwargs.get("path", "minibatches.dat.gz")
        self.loader = None  # linked
        self._file = None
        self.records = 0
        self.demand("loader")

    def initialize(self, **kwargs):
        super(MinibatchesSaver, self).initialize(**kwargs)
        self._file = gzip.open(self.path, "wb", compresslevel=1)
        header = {
            "class_lengths": list(self.loader.class_lengths),
            "max_minibatch_size": self.loader.max_minibatch_size,
            "labels_mapping": dict(self.loader.labels_mapping),
        }
        pickle.dump(header, self._file, protocol=pickle.HIGHEST_PROTOCOL)
        return True

    def run(self):
        loader = self.loader
        loader.minibatch_data.map_read()
        record = {
            "data": numpy.array(
                loader.minibatch_data.mem[:loader.minibatch_size]),
            "class": loader.minibatch_class,
            "size": loader.minibatch_size,
            "indices": numpy.array(
                loader.minibatch_indices.mem[:loader.minibatch_size]),
        }
        if loader.has_labels:
            loader.minibatch_labels.map_read()
            record["labels"] = numpy.array(
                loader.minibatch_labels.mem[:loader.minibatch_size])
        pickle.dump(record, self._file, protocol=pickle.HIGHEST_PROTOCOL)
        self.records += 1

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


class MinibatchesLoader(Loader):
    """Replays a saved stream; epochs loop over the recorded sequence."""

    def __init__(self, workflow, **kwargs):
        super(MinibatchesLoader, self).__init__(workflow, **kwargs)
        self.path = kwargs.get("path", "minibatches.dat.gz")
        self.records = []
        self._cursor = 0

    def load_data(self):
        with gzip.open(self.path, "rb") as fin:
            header = pickle.load(fin)
            while True:
                try:
                    self.records.append(pickle.load(fin))
                except EOFError:
                    break
        self.class_lengths[:] = header["class_lengths"]
        self._max_minibatch_size = header["max_minibatch_size"]
        self.labels_mapping.update(header["labels_mapping"])
        self._calc_class_end_offsets()

    def create_minibatch_data(self):
        first = self.records[0]
        self.minibatch_data.mem = numpy.zeros(
            (self.max_minibatch_size,) + first["data"].shape[1:],
            first["data"].dtype)

    def analyze_dataset(self):
        self.normalizer.analyze(self.records[0]["data"])

    def fill_indices(self, start_offset, count):
        record = self.records[self._cursor % len(self.records)]
        self._cursor += 1
        size = record["size"]
        self.minibatch_size = size
        self.minibatch_data.map_invalidate()
        self.minibatch_data.mem[:size] = record["data"]
        self.minibatch_indices.map_invalidate()
        self.minibatch_indices.mem[:size] = record["indices"]
        if "labels" in record:
            if not self.minibatch_labels:
                self.minibatch_labels.mem = numpy.zeros(
                    self.max_minibatch_size, Loader.LABEL_DTYPE)
            self.minibatch_labels.map_invalidate()
            self.minibatch_labels.mem[:size] = record["labels"]
        return True

    def fill_minibatch(self):
        pass
