"""HDF5 dataset loaders (reference veles/loader/loader_hdf5.py:48-151).

Schema: each split file holds datasets ``data`` (N, ...) and ``labels``
(N,); pass any of test_path / validation_path / train_path.
"""

import numpy

from veles_tpu.loader.fullbatch import FullBatchLoader

__all__ = ["FullBatchHDF5Loader"]


class FullBatchHDF5Loader(FullBatchLoader):
    def __init__(self, workflow, **kwargs):
        super(FullBatchHDF5Loader, self).__init__(workflow, **kwargs)
        self.paths = (kwargs.get("test_path"),
                      kwargs.get("validation_path"),
                      kwargs.get("train_path"))

    def load_data(self):
        import h5py
        datas, labels = [], []
        for i, path in enumerate(self.paths):
            if not path:
                self.class_lengths[i] = 0
                datas.append(None)
                labels.append(None)
                continue
            with h5py.File(path, "r") as fin:
                data = numpy.asarray(fin["data"])
                lbl = (numpy.asarray(fin["labels"])
                       if "labels" in fin else None)
            self.class_lengths[i] = len(data)
            datas.append(data)
            labels.append(lbl)
        self._calc_class_end_offsets()
        shape = next(d for d in datas if d is not None).shape[1:]
        has_labels = any(l is not None for l in labels)
        self.create_originals(shape, labels=has_labels)
        offset = 0
        for data, lbl in zip(datas, labels):
            if data is None:
                continue
            self.original_data.mem[offset:offset + len(data)] = data
            if has_labels:
                for j in range(len(data)):
                    raw = lbl[j] if lbl is not None else -1
                    self.original_labels[offset + j] = (
                        raw.item() if hasattr(raw, "item") else raw)
            offset += len(data)
