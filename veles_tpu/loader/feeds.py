"""Queue-fed loaders for interactive and serving pipelines.

Reference counterparts: InteractiveLoader (loader/interactive.py:57,
feed from IPython), RestfulLoader (loader/restful.py:52, feed from the
HTTP unit), ZeroMQLoader (zmq_loader.py:74, ROUTER socket feed), and
EnsembleLoader (loader/ensemble.py:53, reads the trained-models result
JSON for ensemble testing).
"""

import json
import queue

import numpy

from veles_tpu.loader.base import Loader, TEST

__all__ = ["QueueLoader", "InteractiveLoader", "RestfulLoader",
           "ZeroMQLoader", "EnsembleLoader"]


class QueueLoader(Loader):
    """Serves whatever feed() provides; TEST-class only (serving)."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("testing", True)
        super(QueueLoader, self).__init__(workflow, **kwargs)
        self.sample_shape = kwargs.get("sample_shape")
        self.queue = queue.Queue()

    def feed(self, sample):
        self.queue.put(numpy.asarray(sample, numpy.float32))

    def load_data(self):
        if self.sample_shape is None:
            raise ValueError("sample_shape is required")
        self.class_lengths[:] = [1, 0, 0]  # a rolling TEST stream
        self._calc_class_end_offsets()

    def create_minibatch_data(self):
        self.minibatch_data.mem = numpy.zeros(
            (self.max_minibatch_size,) + tuple(self.sample_shape),
            numpy.float32)

    def analyze_dataset(self):
        self.normalizer.analyze(self.minibatch_data.mem)

    def fill_indices(self, start_offset, count):
        sample = self.queue.get()  # blocks for work
        self.minibatch_size = 1
        self.minibatch_class = TEST
        self.minibatch_data.map_invalidate()
        self.minibatch_data.mem[0] = sample
        self.minibatch_indices.map_invalidate()
        self.minibatch_indices.mem[0] = 0
        return True

    def fill_minibatch(self):
        pass

    def _advance_global_offset(self):
        self.minibatch_class = TEST
        return 1, 1


class InteractiveLoader(QueueLoader):
    """feed() from a notebook/REPL (reference interactive.py:57)."""


class RestfulLoader(QueueLoader):
    """Fed by veles_tpu.restful_api for serving pipelines
    (reference restful.py:52)."""


class ZeroMQLoader(QueueLoader):
    """Receives work items over a ZMQ ROUTER socket
    (reference zmq_loader.py:74)."""

    def __init__(self, workflow, **kwargs):
        super(ZeroMQLoader, self).__init__(workflow, **kwargs)
        self.endpoint = None
        self._socket = None
        self._thread = None
        self.restartable = False  # stop() closes the socket for good

    def initialize(self, **kwargs):
        import pickle
        import threading

        import zmq

        result = super(ZeroMQLoader, self).initialize(**kwargs)
        context = zmq.Context.instance()
        self._socket = context.socket(zmq.ROUTER)
        port = self._socket.bind_to_random_port("tcp://127.0.0.1")
        self.endpoint = "tcp://127.0.0.1:%d" % port
        self._pump_stop_ = threading.Event()

        def pump():
            # the socket is owned by THIS thread: zmq sockets are not
            # thread-safe, so stop() only raises the flag and the pump
            # closes the socket itself
            while not self._pump_stop_.is_set():
                if not self._socket.poll(100):
                    continue
                identity, payload = self._socket.recv_multipart()
                self.feed(pickle.loads(payload))
                self._socket.send_multipart([identity, b"ok"])
            self._socket.close(0)

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()
        self.info("ZeroMQLoader on %s", self.endpoint)
        return result

    def stop(self):
        super(ZeroMQLoader, self).stop()
        if getattr(self, "_pump_stop_", None) is not None:
            self._pump_stop_.set()


class EnsembleLoader(Loader):
    """Reads the ensemble results JSON (reference loader/ensemble.py):
    serves one TEST 'sample' per trained model entry so an ensemble-test
    workflow can iterate members."""

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("testing", True)
        super(EnsembleLoader, self).__init__(workflow, **kwargs)
        self.results_path = kwargs.get("results_path")
        self.models = []
        self.current_model = None

    def load_data(self):
        with open(self.results_path) as fin:
            self.models = json.load(fin)["models"]
        self.class_lengths[:] = [len(self.models), 0, 0]
        self._calc_class_end_offsets()

    def create_minibatch_data(self):
        self.minibatch_data.mem = numpy.zeros(
            (self.max_minibatch_size, 1), numpy.float32)

    def analyze_dataset(self):
        self.normalizer.analyze(self.minibatch_data.mem)

    def fill_minibatch(self):
        index = int(self.minibatch_indices.mem[0])
        self.current_model = self.models[index]
