"""HDFS loader over the WebHDFS REST gateway.

Counterpart of reference veles/loader/hdfs_loader.py:48 (which spoke
the native protocol through a Twisted client).  This build uses the
WebHDFS HTTP API — stdlib urllib only, no hadoop client dependency —
which every HDFS namenode exposes; the loader semantics (pull files
into a full batch, samples = one file or one line each) are preserved.
"""

import json
import posixpath
import urllib.parse
import urllib.request

import numpy

from veles_tpu.loader.base import LoaderError
from veles_tpu.loader.fullbatch import FullBatchLoader

__all__ = ["WebHdfsClient", "HdfsTextLoader"]


class WebHdfsClient(object):
    """Minimal WebHDFS v1 client: LISTSTATUS + OPEN."""

    def __init__(self, base_url, user=None, timeout=30):
        # base_url like http://namenode:9870
        self.base_url = base_url.rstrip("/")
        self.user = user
        self.timeout = timeout

    def _url(self, path, op, **params):
        params = dict(params, op=op)
        if self.user:
            params["user.name"] = self.user
        return "%s/webhdfs/v1%s?%s" % (
            self.base_url, urllib.parse.quote(path),
            urllib.parse.urlencode(params))

    def list_status(self, path):
        """-> [{pathSuffix, type, length, ...}, ...]"""
        with urllib.request.urlopen(self._url(path, "LISTSTATUS"),
                                    timeout=self.timeout) as resp:
            payload = json.load(resp)
        return payload["FileStatuses"]["FileStatus"]

    def open(self, path):
        """-> file bytes (follows the datanode redirect)."""
        with urllib.request.urlopen(self._url(path, "OPEN"),
                                    timeout=self.timeout) as resp:
            return resp.read()

    def list_files(self, path, suffix=None):
        out = []
        for status in self.list_status(path):
            if status.get("type") != "FILE":
                continue
            name = status["pathSuffix"]
            if suffix and not name.endswith(suffix):
                continue
            out.append(posixpath.join(path, name))
        return sorted(out)


class HdfsTextLoader(FullBatchLoader):
    """Each LINE of each file under ``hdfs_path`` is one sample of
    whitespace-separated floats; the last column is the int label
    (set ``labeled=False`` for unlabeled data).

    kwargs: hdfs_url, hdfs_path, user, suffix (e.g. ".txt"),
    validation_ratio (split off the tail).
    """

    def __init__(self, workflow, **kwargs):
        super(HdfsTextLoader, self).__init__(workflow, **kwargs)
        self.hdfs_url = kwargs["hdfs_url"]
        self.hdfs_path = kwargs["hdfs_path"]
        self.user = kwargs.get("user")
        self.suffix = kwargs.get("suffix")
        self.labeled = kwargs.get("labeled", True)
        self.split_ratio = kwargs.get("validation_ratio") or 0.0

    def load_data(self):
        client = WebHdfsClient(self.hdfs_url, user=self.user)
        rows, labels = [], []
        for path in client.list_files(self.hdfs_path, self.suffix):
            for line in client.open(path).decode().splitlines():
                line = line.strip()
                if not line:
                    continue
                cols = line.split()
                if self.labeled:
                    labels.append(int(cols[-1]))
                    cols = cols[:-1]
                rows.append([float(c) for c in cols])
        if not rows:
            raise LoaderError("no samples under %s%s" %
                              (self.hdfs_url, self.hdfs_path))
        data = numpy.array(rows, self.dtype)
        n_valid = int(len(rows) * self.split_ratio)
        self.original_data = data
        if self.labeled:
            self.original_labels = labels
        self.class_lengths[0] = 0
        self.class_lengths[1] = n_valid
        self.class_lengths[2] = len(rows) - n_valid
        if n_valid:
            # validation window first (loader layout [test|valid|train])
            self.original_data = numpy.concatenate(
                [data[len(rows) - n_valid:], data[:len(rows) - n_valid]])
            if self.labeled:
                self.original_labels = (labels[len(rows) - n_valid:] +
                                        labels[:len(rows) - n_valid])
