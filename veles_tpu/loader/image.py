"""Image loaders with augmentation.

Counterpart of reference veles/loader/image.py:106 + file_image.py +
fullbatch_image.py: scale / crop / rotate / mirror augmentation, color
space conversion through OpenCV, directory-scanning file loaders, and a
fullbatch composition that lands the whole image set in HBM.

Augmentation happens at load/refresh time on host (CPU, numpy/cv2);
the per-step path stays the device gather.  (A Pallas-side augmentation
pipeline is a possible follow-up; the reference also augmented on CPU.)
"""

import os

import numpy

from veles_tpu.loader.base import Loader, LoaderError, TEST, VALID, TRAIN
from veles_tpu.loader.fullbatch import FullBatchLoader

__all__ = ["ImageAugmentation", "FullBatchImageLoader",
           "FileImageLoader", "scan_image_tree"]

IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".pgm",
                    ".tif", ".tiff", ".webp")


class ImageAugmentation(object):
    """scale: output (w, h); crop: (w, h) random window; mirror:
    False | True (random) | "always"; rotations: list of degrees to
    sample from; color_space: target cv2 space name (e.g. "GRAY",
    "HSV") from BGR source."""

    def __init__(self, scale=None, crop=None, mirror=False,
                 rotations=(0,), color_space=None, prng=None):
        from veles_tpu import prng as prng_module
        self.scale = scale
        self.crop = crop
        self.mirror = mirror
        self.rotations = tuple(rotations)
        self.color_space = color_space
        self.prng = prng or prng_module.get("image_augmentation")

    def apply(self, img):
        import cv2
        if self.color_space:
            code = getattr(cv2, "COLOR_BGR2%s" % self.color_space)
            img = cv2.cvtColor(img, code)
        if self.scale:
            img = cv2.resize(img, tuple(self.scale),
                             interpolation=cv2.INTER_AREA)
        if len(self.rotations) > 1 or self.rotations[0]:
            angle = self.rotations[int(
                self.prng.random_sample() * len(self.rotations))]
            if angle:
                h, w = img.shape[:2]
                mat = cv2.getRotationMatrix2D((w / 2, h / 2), angle, 1.0)
                img = cv2.warpAffine(img, mat, (w, h))
        if self.crop:
            cw, ch = self.crop
            h, w = img.shape[:2]
            if h < ch or w < cw:
                raise LoaderError("crop %s larger than image %s" %
                                  ((cw, ch), (w, h)))
            x0 = int(self.prng.random_sample() * (w - cw + 1))
            y0 = int(self.prng.random_sample() * (h - ch + 1))
            img = img[y0:y0 + ch, x0:x0 + cw]
        if self.mirror == "always" or (
                self.mirror is True and self.prng.random_sample() < 0.5):
            img = img[:, ::-1]
        return numpy.ascontiguousarray(img)


def scan_image_tree(root_dir):
    """directory-per-class tree -> sorted [(path, label), ...]
    (reference file_loader.py:48-277 scanning behavior)."""
    samples = []
    for label in sorted(os.listdir(root_dir)):
        class_dir = os.path.join(root_dir, label)
        if not os.path.isdir(class_dir):
            continue
        for fname in sorted(os.listdir(class_dir)):
            if fname.lower().endswith(IMAGE_EXTENSIONS):
                samples.append((os.path.join(class_dir, fname), label))
    return samples


class FullBatchImageLoader(FullBatchLoader):
    """Loads explicit (path, label) lists per split into one device
    batch (reference fullbatch_image.py:56-266).

    kwargs: test_paths / validation_paths / train_paths: lists of
    (path, label); augmentation: ImageAugmentation; grayscale: bool.
    """

    def __init__(self, workflow, **kwargs):
        super(FullBatchImageLoader, self).__init__(workflow, **kwargs)
        self.split_paths = (kwargs.get("test_paths", ()),
                            kwargs.get("validation_paths", ()),
                            kwargs.get("train_paths", ()))
        self.augmentation = kwargs.get("augmentation")
        self.grayscale = kwargs.get("grayscale", False)

    def _read_image(self, path):
        import cv2
        flag = cv2.IMREAD_GRAYSCALE if self.grayscale \
            else cv2.IMREAD_COLOR
        img = cv2.imread(path, flag)
        if img is None:
            raise LoaderError("cannot read image %s" % path)
        if self.augmentation is not None:
            img = self.augmentation.apply(img)
        if img.ndim == 2:
            img = img[..., None]
        return img

    def load_data(self):
        for i, split in enumerate(self.split_paths):
            self.class_lengths[i] = len(split)
        self._calc_class_end_offsets()
        flat = [pair for split in self.split_paths for pair in split]
        first = self._read_image(flat[0][0])
        self.create_originals(first.shape)
        for i, (path, label) in enumerate(flat):
            img = self._read_image(path)
            if img.shape != first.shape:
                raise LoaderError(
                    "image %s shape %s != %s (use augmentation.scale)" %
                    (path, img.shape, first.shape))
            self.original_data.mem[i] = img.astype(self.dtype) / 255.0
            self.original_labels[i] = label


class FileImageLoader(FullBatchImageLoader):
    """Scans directory trees: test_dir / validation_dir / train_dir
    each holding class subdirectories (reference file_image.py:53)."""

    def __init__(self, workflow, **kwargs):
        dirs = [kwargs.get("test_dir"), kwargs.get("validation_dir"),
                kwargs.get("train_dir")]
        paths = tuple(scan_image_tree(d) if d else () for d in dirs)
        kwargs["test_paths"], kwargs["validation_paths"], \
            kwargs["train_paths"] = paths
        super(FileImageLoader, self).__init__(workflow, **kwargs)
