"""Image loaders with augmentation.

Counterpart of reference veles/loader/image.py:106 + file_image.py +
fullbatch_image.py: scale / crop / rotate / mirror augmentation, color
space conversion (numpy, cv2-convention compatible — see
veles_tpu.loader.colorspace), directory-scanning file loaders, and a
fullbatch composition that lands the whole image set in HBM.

Augmentation happens at load/refresh time on host (CPU, numpy/cv2);
the per-step path stays the device gather.  (A Pallas-side augmentation
pipeline is a possible follow-up; the reference also augmented on CPU.)
"""

import os

import numpy

from veles_tpu.loader.base import Loader, LoaderError, TEST, VALID, TRAIN
from veles_tpu.loader.fullbatch import FullBatchLoader, FullBatchLoaderMSE

__all__ = ["ImageAugmentation", "FullBatchImageLoader",
           "FileImageLoader", "FullBatchImageLoaderMSE",
           "FileImageLoaderMSE", "scan_image_tree"]

IMAGE_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".pgm",
                    ".tif", ".tiff", ".webp")


class ImageAugmentation(object):
    """scale: output (w, h); crop: (w, h) random window; mirror:
    False | True (random) | "always"; rotations: list of degrees to
    sample from; color_space: target space name from
    :data:`veles_tpu.loader.colorspace.SPACES` (e.g. "GRAY", "HSV",
    "YCR_CB"); the source is what the reader produced (BGR for color
    cv2.imread, override with ``apply(img, source_space=...)``)."""

    def __init__(self, scale=None, crop=None, mirror=False,
                 rotations=(0,), color_space=None, prng=None):
        from veles_tpu import prng as prng_module
        self.scale = scale
        self.crop = crop
        self.mirror = mirror
        self.rotations = tuple(rotations)
        self.color_space = color_space
        self.prng = prng or prng_module.get("image_augmentation")

    def apply(self, img, source_space="BGR"):
        import cv2

        from veles_tpu.loader import colorspace
        if self.color_space:
            img = colorspace.convert(img, source_space,
                                     self.color_space)
        if self.scale:
            img = cv2.resize(img, tuple(self.scale),
                             interpolation=cv2.INTER_AREA)
        if len(self.rotations) > 1 or self.rotations[0]:
            angle = self.rotations[int(
                self.prng.random_sample() * len(self.rotations))]
            if angle:
                h, w = img.shape[:2]
                mat = cv2.getRotationMatrix2D((w / 2, h / 2), angle, 1.0)
                img = cv2.warpAffine(img, mat, (w, h))
        if self.crop:
            cw, ch = self.crop
            h, w = img.shape[:2]
            if h < ch or w < cw:
                raise LoaderError("crop %s larger than image %s" %
                                  ((cw, ch), (w, h)))
            x0 = int(self.prng.random_sample() * (w - cw + 1))
            y0 = int(self.prng.random_sample() * (h - ch + 1))
            img = img[y0:y0 + ch, x0:x0 + cw]
        if self.mirror == "always" or (
                self.mirror is True and self.prng.random_sample() < 0.5):
            img = img[:, ::-1]
        return numpy.ascontiguousarray(img)


def scan_image_tree(root_dir):
    """directory-per-class tree -> sorted [(path, label), ...]
    (reference file_loader.py:48-277 scanning behavior)."""
    samples = []
    for label in sorted(os.listdir(root_dir)):
        class_dir = os.path.join(root_dir, label)
        if not os.path.isdir(class_dir):
            continue
        for fname in sorted(os.listdir(class_dir)):
            if fname.lower().endswith(IMAGE_EXTENSIONS):
                samples.append((os.path.join(class_dir, fname), label))
    return samples


def distortion_stages(mirror, rotations):
    """The reference's deterministic distortion composition
    (fullbatch_image.py:63-80 DistortionIterator): every (mirror,
    rotation) combination, materialized."""
    stages = []
    for rot in rotations:
        stages.append((False, rot))
        if mirror is True or mirror == "always":
            stages.append((True, rot))
    return stages


def distort(img, mirror_state, rotation):
    """Apply one deterministic distortion stage."""
    import cv2
    if rotation:
        h, w = img.shape[:2]
        mat = cv2.getRotationMatrix2D((w / 2, h / 2), rotation, 1.0)
        img = cv2.warpAffine(img, mat, (w, h))
        if img.ndim == 2:
            img = img[..., None]
    if mirror_state:
        img = img[:, ::-1]
    return numpy.ascontiguousarray(img)


class FullBatchImageLoader(FullBatchLoader):
    """Loads explicit (path, label) lists per split into one device
    batch (reference fullbatch_image.py:56-266).

    kwargs: test_paths / validation_paths / train_paths: lists of
    (path, label); augmentation: ImageAugmentation; grayscale: bool;
    color_space: target space from colorspace.SPACES (reference
    loader/image.py:111-125 ``color_space`` kwarg; None keeps the
    reader's space — BGR for color files, GRAY with grayscale=True);
    distortion composition via mirror=True + rotations=(0, 15, -15):
    every TRAIN sample is materialized once per (mirror, rotation)
    combination (samples_inflation, reference DistortionIterator).
    """

    def __init__(self, workflow, **kwargs):
        super(FullBatchImageLoader, self).__init__(workflow, **kwargs)
        self.split_paths = (kwargs.get("test_paths", ()),
                            kwargs.get("validation_paths", ()),
                            kwargs.get("train_paths", ()))
        self.augmentation = kwargs.get("augmentation")
        self.grayscale = kwargs.get("grayscale", False)
        self.color_space = kwargs.get("color_space")
        self.mirror = kwargs.get("mirror", False)
        self.rotations = tuple(kwargs.get("rotations", (0,)))

    @property
    def samples_inflation(self):
        """How many distorted copies each TRAIN sample becomes."""
        return len(distortion_stages(self.mirror, self.rotations))

    def _read_image(self, path):
        import cv2
        flag = cv2.IMREAD_GRAYSCALE if self.grayscale \
            else cv2.IMREAD_COLOR
        img = cv2.imread(path, flag)
        if img is None:
            raise LoaderError("cannot read image %s" % path)
        space = "GRAY" if self.grayscale else "BGR"
        if self.augmentation is not None:
            img = self.augmentation.apply(img, source_space=space)
            space = self.augmentation.color_space or space
        if self.color_space and self.color_space != space:
            from veles_tpu.loader import colorspace
            img = colorspace.convert(img, space, self.color_space)
        if img.ndim == 2:
            img = img[..., None]
        return img

    def _expanded_splits(self):
        """(path, label, mirror_state, rotation) rows per split;
        TRAIN inflated by the distortion composition."""
        stages = distortion_stages(self.mirror, self.rotations)
        out = []
        for cls, split in enumerate(self.split_paths):
            rows = []
            for path, label in split:
                if cls == TRAIN and len(stages) > 1:
                    for mirror_state, rot in stages:
                        rows.append((path, label, mirror_state, rot))
                else:
                    rows.append((path, label, False, 0))
            out.append(rows)
        return out

    def load_data(self):
        splits = self._expanded_splits()
        for i, rows in enumerate(splits):
            self.class_lengths[i] = len(rows)
        self._calc_class_end_offsets()
        flat = [row for rows in splits for row in rows]
        first = self._read_image(flat[0][0])
        self.create_originals(first.shape)
        for i, (path, label, mirror_state, rot) in enumerate(flat):
            img = self._read_image(path)
            if img.shape != first.shape:
                raise LoaderError(
                    "image %s shape %s != %s (use augmentation.scale)" %
                    (path, img.shape, first.shape))
            if mirror_state or rot:
                img = distort(img, mirror_state, rot)
            self.original_data.mem[i] = img.astype(self.dtype) / 255.0
            self.original_labels[i] = label


class FileImageLoader(FullBatchImageLoader):
    """Scans directory trees: test_dir / validation_dir / train_dir
    each holding class subdirectories (reference file_image.py:53)."""

    def __init__(self, workflow, **kwargs):
        dirs = [kwargs.get("test_dir"), kwargs.get("validation_dir"),
                kwargs.get("train_dir")]
        paths = tuple(scan_image_tree(d) if d else () for d in dirs)
        kwargs["test_paths"], kwargs["validation_paths"], \
            kwargs["train_paths"] = paths
        super(FileImageLoader, self).__init__(workflow, **kwargs)


class FullBatchImageLoaderMSE(FullBatchImageLoader, FullBatchLoaderMSE):
    """(input image, target image) pairs for MSE workflows (reference
    image_mse.py:47-158 + fullbatch_image.py:200-222 class_targets).

    Target sources, either of:
    - ``target_paths``: one target image path per sample, ordered like
      test_paths + validation_paths + train_paths (label-less MSE);
    - ``class_target_paths``: {label: path} — one target image per
      class; each sample's target is its class's image (the
      reference's ``class_targets`` mapping).
    """

    def __init__(self, workflow, **kwargs):
        super(FullBatchImageLoaderMSE, self).__init__(workflow, **kwargs)
        self.target_paths = list(kwargs.get("target_paths", ()))
        self.class_target_paths = dict(
            kwargs.get("class_target_paths", {}))
        if bool(self.target_paths) == bool(self.class_target_paths):
            raise LoaderError(
                "provide exactly one of target_paths / "
                "class_target_paths")

    def load_data(self):
        super(FullBatchImageLoaderMSE, self).load_data()
        if self.class_target_paths:
            targets_by_label = {
                label: self._read_image(path).astype(self.dtype) / 255.0
                for label, path in self.class_target_paths.items()}
            self.original_targets = numpy.stack(
                [targets_by_label[label]
                 for label in self.original_labels])
            return
        # per-sample targets follow the same distortion composition as
        # the inputs so pairs stay aligned
        splits = self._expanded_splits()
        flat_inputs = [row for rows in splits for row in rows]
        if len(self.target_paths) != sum(
                len(s) for s in self.split_paths):
            raise LoaderError(
                "%d target_paths for %d source images" %
                (len(self.target_paths),
                 sum(len(s) for s in self.split_paths)))
        target_by_source = {}
        flat_sources = [pair[0] for split in self.split_paths
                        for pair in split]
        for src, tgt in zip(flat_sources, self.target_paths):
            target_by_source[src] = tgt
        targets = []
        for path, _label, mirror_state, rot in flat_inputs:
            img = self._read_image(target_by_source[path])
            if mirror_state or rot:
                img = distort(img, mirror_state, rot)
            targets.append(img.astype(self.dtype) / 255.0)
        self.original_targets = numpy.stack(targets)


class FileImageLoaderMSE(FullBatchImageLoaderMSE):
    """Directory-scanning MSE variant (reference image_mse.py:129-158):
    target_dir holds one image per source basename."""

    def __init__(self, workflow, **kwargs):
        dirs = [kwargs.get("test_dir"), kwargs.get("validation_dir"),
                kwargs.get("train_dir")]
        paths = tuple(scan_image_tree(d) if d else () for d in dirs)
        kwargs["test_paths"], kwargs["validation_paths"], \
            kwargs["train_paths"] = paths
        target_dir = kwargs.get("target_dir")
        if target_dir and "target_paths" not in kwargs:
            kwargs["target_paths"] = [
                os.path.join(target_dir, os.path.basename(p))
                for split in paths for (p, _label) in split]
        super(FileImageLoaderMSE, self).__init__(workflow, **kwargs)
