"""Loader — the minibatch server contract.

TPU-native counterpart of reference veles/loader/base.py:100,120.
Preserved semantics:

- the TEST(0) / VALIDATION(1) / TRAIN(2) class triple with
  ``class_lengths`` / ``class_end_offsets`` and per-epoch iteration
  test → validation → train;
- per-epoch TRAIN shuffling bounded by ``shuffle_limit``, driven by the
  keyed reproducible PRNG;
- ``Bool`` flags ``last_minibatch`` / ``epoch_ended`` / ``train_ended`` /
  ``test_ended`` that downstream decision units gate on;
- label → int mapping built during dataset analysis;
- normalizer hookup through ``normalization_type`` /
  ``normalization_parameters``;
- the distributed contract (reference loader/base.py:631-687): the master
  serves ``(indices, class, size, offset, epoch)`` per job, the slave
  patches its ``shuffled_indices`` window and fills data locally; pending
  minibatches are tracked per slave and requeued into
  ``failed_minibatches`` on ``drop_slave``; pickling moves pending →
  failed so snapshots stay consistent.

Subclasses implement ``load_data`` / ``create_minibatch_data`` /
``fill_minibatch`` exactly as in the reference's ILoader.
"""

import threading
import time
from collections import defaultdict

import numpy

from veles_tpu import prng
from veles_tpu.memory import Array
from veles_tpu.mutable import Bool
from veles_tpu.normalization import NormalizerRegistry, StatelessNormalizer
from veles_tpu.units import Unit

__all__ = ["Loader", "LoaderMSEMixin", "LoaderError",
           "TEST", "VALID", "TRAIN", "CLASS_NAME"]

TEST, VALID, TRAIN = 0, 1, 2
CLASS_NAME = ["test", "validation", "train"]


class LoaderError(Exception):
    pass


class ServeShadow(object):
    """Thread-private view of a loader's public serving fields.

    While an input pipeline worker serves minibatches AHEAD of the unit
    graph (veles_tpu/pipeline_input.py), the fields downstream units
    gate on — minibatch class/size/offset, epoch_number, and the four
    end-of-class Bools — must keep describing the minibatch currently
    being CONSUMED.  The worker therefore reads and writes this shadow
    instead (keyed on its thread identity), and the graph thread
    applies the shadow snapshot captured with each minibatch when that
    minibatch is popped.  See docs/pipeline_input.md.
    """

    __slots__ = ("thread", "values")

    #: the public flags routed through the shadow
    FLAGS = ("last_minibatch", "epoch_ended", "train_ended", "test_ended")

    def __init__(self, loader, thread):
        self.thread = thread
        self.values = {
            "minibatch_class": loader.minibatch_class,
            "minibatch_size": loader.minibatch_size,
            "minibatch_offset": loader.minibatch_offset,
            "epoch_number": loader.epoch_number,
        }
        for name in self.FLAGS:
            self.values[name] = bool(getattr(loader, name))


class Loader(Unit):
    """Serves minibatches; see module docstring for the contract."""

    LABEL_DTYPE = numpy.int32
    INDEX_DTYPE = numpy.int32

    def __init__(self, workflow, **kwargs):
        super(Loader, self).__init__(workflow, **kwargs)
        self.last_minibatch = Bool(False)
        self.epoch_ended = Bool(False)
        self.train_ended = Bool(False)
        self.test_ended = Bool(False)
        self.testing = kwargs.get("testing", False)
        self.shuffle_limit = kwargs.get(
            "shuffle_limit", numpy.iinfo(numpy.uint32).max)
        if self.testing:
            self.shuffle_limit = 0
        self._max_minibatch_size = int(kwargs.get("minibatch_size", 100))
        if self._max_minibatch_size < 1:
            raise ValueError("minibatch_size must be positive")
        self.class_lengths = [0, 0, 0]
        self.class_end_offsets = [0, 0, 0]
        self.train_ratio = kwargs.get("train_ratio", 1.0)
        self.epoch_number = 0
        self.samples_served = 0
        self.global_offset = 0
        self.minibatch_class = 0
        self.minibatch_data = Array(shallow_pickle=True)
        self.minibatch_indices = Array(shallow_pickle=True)
        self.minibatch_labels = Array(shallow_pickle=True)
        self.raw_minibatch_labels = []
        self.shuffled_indices = Array()
        self.labels_mapping = {}
        self.failed_minibatches = []
        self._total_failed = 0
        self.has_data_for_slave = True
        #: advisory elastic-fleet window hint from the last reshard
        #: push (apply_reshard); None until a master ever pushed one
        self.fleet_share = None
        self.fleet_epoch = None
        self._normalization_type = kwargs.get("normalization_type", "none")
        self._normalization_parameters = kwargs.get(
            "normalization_parameters", {})
        self._normalizer = None
        self.prng = kwargs.get("prng", prng.get())

    def init_unpickled(self):
        super(Loader, self).init_unpickled()
        self._minibatch_offset_ = 0
        self._minibatch_size_ = 0
        self.pending_minibatches_ = defaultdict(list)
        self._serve_log_time_ = time.time()
        # When applying a slave's update, flags must be computed against
        # the global offset AS OF that job's serve (the loader may have
        # served ahead under async pipelining); None -> live offset.
        self._flags_global_offset_ = None
        # async input pipeline hookup (veles_tpu/pipeline_input.py):
        # both transient — a restored loader serves synchronously until
        # a FusedTrainer re-attaches its Prefetcher at initialize
        self._serve_shadow_ = None
        self._pipeline_ = None

    # -- pickling: pending -> failed (reference loader/base.py:216-232) ----

    def __getstate__(self):
        pipeline = self._pipeline_
        if pipeline is not None:
            # a mid-run snapshot must not observe a half-applied serve
            # (the worker mutates pending/failed between these reads)
            with pipeline.quiescent():
                return self._getstate_quiesced()
        return self._getstate_quiesced()

    def _getstate_quiesced(self):
        state = super(Loader, self).__getstate__()
        if not self.stopped:
            failed = list(state.get("failed_minibatches", []))
            for key, pmb in self.pending_minibatches_.items():
                if key is None and self._pipeline_ is None:
                    # Standalone SYNC serving retires its single None-
                    # keyed record only lazily, at the start of the
                    # NEXT serve — but a snapshot is taken post-
                    # decision, after the graph has fully consumed the
                    # minibatch.  Requeueing it would REPLAY a consumed
                    # minibatch on resume (double-counted samples, a
                    # spurious epoch-end), so exact resume forbids it.
                    # The pipeline's None-keyed records are different:
                    # those are served-ahead and genuinely unconsumed.
                    continue
                # reversed: serve_next_minibatch replays failed jobs
                # LIFO, so requeueing newest-first preserves the
                # original serve order on restore (the pipeline can
                # hold several served-ahead records here)
                failed.extend(reversed(pmb))
            state["failed_minibatches"] = failed
        if self._pipeline_ is not None:
            # pickle serializes the state dict AFTER the quiescent lock
            # is released, while the pipeline worker keeps serving — an
            # epoch-wrap shuffle would tear shuffled_indices/prng mid-
            # serialization, so snapshot the worker-owned mutables NOW
            import copy
            state["shuffled_indices"] = copy.deepcopy(
                self.shuffled_indices)
            state["prng"] = copy.deepcopy(self.prng)
        return state

    def __setstate__(self, state):
        # minibatch_class / epoch_number became properties (shadow-aware
        # serving fields); migrate snapshots written when they were
        # plain attributes, which would otherwise be shadowed by the
        # class-level descriptors
        for legacy, backing in (("minibatch_class", "_minibatch_class"),
                                ("epoch_number", "_epoch_number")):
            if legacy in state and backing not in state:
                state[backing] = state.pop(legacy)
        super(Loader, self).__setstate__(state)

    # -- the ILoader contract ---------------------------------------------

    def load_data(self):
        """Populate class_lengths (and any backing storage)."""
        raise NotImplementedError

    def create_minibatch_data(self):
        """Allocate minibatch_data for max_minibatch_size samples."""
        raise NotImplementedError

    def fill_minibatch(self):
        """Fill minibatch_data[:minibatch_size] (and raw labels) according
        to minibatch_indices."""
        raise NotImplementedError

    # -- derived quantities -------------------------------------------------

    @property
    def has_labels(self):
        return len(self.labels_mapping) > 0

    @property
    def reversed_labels_mapping(self):
        return {v: k for k, v in self.labels_mapping.items()}

    @property
    def unique_labels_count(self):
        return len(self.labels_mapping)

    @property
    def total_samples(self):
        return sum(self.class_lengths)

    @property
    def effective_total_samples(self):
        return self.total_samples - int(
            (1.0 - self.train_ratio) * self.class_lengths[TRAIN])

    @property
    def effective_class_end_offsets(self):
        offsets = list(self.class_end_offsets)
        offsets[TRAIN] -= int(
            (1.0 - self.train_ratio) * self.class_lengths[TRAIN])
        return offsets

    @property
    def max_minibatch_size(self):
        return self._max_minibatch_size

    # -- serving fields, shadow-aware under async pipelining ----------------
    #
    # A pipeline worker thread (pipeline_input.Prefetcher) serves ahead
    # of the unit graph; its reads/writes of the PUBLIC serving fields
    # go to its thread-private ServeShadow so the graph thread keeps
    # seeing the values of the minibatch currently being consumed.

    def _shadow_for_current_thread(self):
        shadow = self._serve_shadow_
        if shadow is not None and \
                threading.current_thread() is shadow.thread:
            return shadow
        return None

    def _set_flag(self, name, value):
        """Write a public Bool flag; a pipeline worker's write lands in
        its shadow and is applied when its minibatch is consumed."""
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            shadow.values[name] = bool(value)
        else:
            flag = getattr(self, name)
            flag <<= value

    @property
    def minibatch_offset(self):
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            return shadow.values["minibatch_offset"]
        return self._minibatch_offset_

    @minibatch_offset.setter
    def minibatch_offset(self, value):
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            shadow.values["minibatch_offset"] = value
        else:
            self._minibatch_offset_ = value
        self._update_flags()

    @property
    def minibatch_size(self):
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            return shadow.values["minibatch_size"]
        return self._minibatch_size_

    @minibatch_size.setter
    def minibatch_size(self, value):
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            shadow.values["minibatch_size"] = value
        else:
            self._minibatch_size_ = value

    @property
    def minibatch_class(self):
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            return shadow.values["minibatch_class"]
        return self._minibatch_class

    @minibatch_class.setter
    def minibatch_class(self, value):
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            shadow.values["minibatch_class"] = value
        else:
            self._minibatch_class = value

    @property
    def epoch_number(self):
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            return shadow.values["epoch_number"]
        return self._epoch_number

    @epoch_number.setter
    def epoch_number(self, value):
        shadow = self._shadow_for_current_thread()
        if shadow is not None:
            shadow.values["epoch_number"] = value
        else:
            self._epoch_number = value

    @property
    def pending_minibatches_count(self):
        return sum(len(v) for v in self.pending_minibatches_.values())

    @property
    def total_failed(self):
        return self._total_failed

    @property
    def shape(self):
        return self.minibatch_data.shape[1:]

    @property
    def normalizer(self):
        if self._normalizer is None:
            self._normalizer = NormalizerRegistry.get(
                self._normalization_type, **self._normalization_parameters)
        return self._normalizer

    @property
    def normalization_type(self):
        return self._normalization_type

    @normalization_type.setter
    def normalization_type(self, value):
        self._normalization_type = value
        self._normalizer = None

    # -- lifecycle ----------------------------------------------------------

    def initialize(self, **kwargs):
        super(Loader, self).initialize(**kwargs)
        if self.testing:
            self.global_offset = 0
            del self.failed_minibatches[:]
        self.load_data()
        self._calc_class_end_offsets()
        self._max_minibatch_size = min(
            self._max_minibatch_size, max(self.class_lengths))
        self.info(
            "Samples: test %d, validation %d, train %d; minibatch %d",
            self.class_lengths[TEST], self.class_lengths[VALID],
            self.class_lengths[TRAIN], self.max_minibatch_size)
        self.minibatch_indices.mem = numpy.zeros(
            self.max_minibatch_size, self.INDEX_DTYPE)
        self.minibatch_labels.reset()
        self.raw_minibatch_labels = [None] * self.max_minibatch_size
        self.create_minibatch_data()
        if not self.minibatch_data:
            raise LoaderError(
                "create_minibatch_data() must set minibatch_data")
        self.analyze_dataset()
        if self.has_labels:
            self.minibatch_labels.mem = numpy.zeros(
                self.max_minibatch_size, self.LABEL_DTYPE)
        if self.testing:
            self.shuffled_indices.reset()
        if not getattr(self, "restored_from_snapshot", False) or self.testing:
            self.shuffle()
        return True

    def run(self):
        pipeline = self._pipeline_
        if pipeline is not None:
            pipeline.step()
            return
        self.pending_minibatches_.pop(None, None)
        self.serve_next_minibatch(None)
        self._on_successful_serve()

    def stop(self):
        pipeline = self._pipeline_
        if pipeline is not None:
            pipeline.shutdown()
        super(Loader, self).stop()

    def on_workflow_finish(self):
        """End of a run: wind the pipeline worker down (a later run
        lazily restarts it)."""
        pipeline = self._pipeline_
        if pipeline is not None:
            pipeline.shutdown()

    # -- distributed contract (reference loader/base.py:631-687) ------------

    # -- IResultProvider (reference loader/base.py:689-701) ------------------

    def get_metric_names(self):
        if not self.testing:
            return {"Total epochs"}
        return {"Labels"} if self.has_labels else set()

    def get_metric_values(self):
        if not self.testing:
            return {"Total epochs": self.epoch_number}
        if self.has_labels:
            return {"Labels": self.reversed_labels_mapping}
        return {}

    def generate_data_for_master(self):
        return True

    def generate_data_for_slave(self, slave):
        self.serve_next_minibatch(slave.id)
        data = {
            "indices": numpy.array(
                self.minibatch_indices.mem[:self.minibatch_size]),
            "minibatch_class": self.minibatch_class,
            "minibatch_size": self.minibatch_size,
            "minibatch_offset": self.minibatch_offset,
            "epoch_number": self.epoch_number,
        }
        self.has_data_for_slave = (
            not self._class_ended() or len(self.failed_minibatches) > 0)
        return data

    def apply_data_from_master(self, data):
        for attr in ("minibatch_class", "minibatch_size",
                     "minibatch_offset", "epoch_number"):
            setattr(self, attr, data[attr])
        self.last_minibatch <<= False
        self.epoch_ended <<= False
        self.train_ended <<= False
        indices = data["indices"]
        if indices.size != self.minibatch_size:
            raise LoaderError("minibatch size mismatch from master")
        start = self.minibatch_offset - self.minibatch_size
        if start < 0 or self.minibatch_offset > len(self.shuffled_indices):
            raise LoaderError("minibatch offset out of range from master")
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=self.INDEX_DTYPE)
        self.shuffled_indices.map_write()
        self.shuffled_indices.mem[start:self.minibatch_offset] = indices

    def apply_data_from_slave(self, data, slave):
        if slave is None:
            return
        try:
            job = self.pending_minibatches_[slave.id].pop()
        except (KeyError, IndexError):
            raise LoaderError(
                "no pending minibatch for slave %s" % slave.id)
        offset, size, mb_class, global_snapshot = job
        self.minibatch_class = mb_class
        self._flags_global_offset_ = global_snapshot
        try:
            self.minibatch_offset, self.minibatch_size = offset, size
            self._on_successful_serve()
        finally:
            self._flags_global_offset_ = None
        if not self.has_data_for_slave:
            self.has_data_for_slave = bool(self.last_minibatch)

    def drop_slave(self, slave):
        if slave.id in self.pending_minibatches_:
            self._total_failed += 1
            self.failed_minibatches.extend(
                self.pending_minibatches_.pop(slave.id))
            self.has_data_for_slave = True
            self.info("Jobs failed: %d, pending: %d",
                      len(self.failed_minibatches),
                      self.pending_minibatches_count)

    def unserved_remainder(self):
        """Elastic resharding input (docs/distributed.md): samples of
        the current epoch not yet APPLIED — the class-window total
        minus this epoch's applied progress.  Reserved-but-unapplied
        minibatches count as unserved: a reshard after a drop must
        repartition exactly the work the requeue put back."""
        total = self.effective_total_samples
        if not total:
            return None
        return total - self.samples_served % total

    def apply_reshard(self, info):
        """Slave-side window hint from a master reshard push: this
        loader's power-weighted share of the epoch's unserved
        remainder and the membership epoch it was computed at.
        Advisory next to the authoritative per-job
        ``apply_data_from_master`` window — the hint lets dashboards
        (and future prefetch sizing) see the fair split without
        touching the sample accounting."""
        self.fleet_share = info.get("share")
        self.fleet_epoch = info.get("epoch")
        self.debug("reshard hint: share %s of %s at membership "
                   "epoch %s", self.fleet_share, info.get("remaining"),
                   self.fleet_epoch)

    # -- serving ------------------------------------------------------------

    def shuffle(self):
        """Shuffle the TRAIN window of shuffled_indices
        (reference loader/base.py:711)."""
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=self.INDEX_DTYPE)
        if self.shuffle_limit <= 0 or self.class_lengths[TRAIN] == 0:
            return
        self.shuffle_limit -= 1
        self.shuffled_indices.map_write()
        self.prng.shuffle(
            self.shuffled_indices.mem[self.class_end_offsets[VALID]:])

    def serve_next_minibatch(self, slave_id):
        try:
            minibatch_def = self.failed_minibatches.pop()
            offset, size = minibatch_def[0], minibatch_def[1]
            self.minibatch_class = minibatch_def[2]
        except IndexError:
            offset, size = self._advance_global_offset()
            minibatch_def = (offset, size, self.minibatch_class,
                             self.global_offset)
        self.pending_minibatches_[slave_id].append(minibatch_def)
        self.minibatch_offset, self.minibatch_size = offset, size

        if self.fill_indices(offset - size, size):
            return  # device path filled everything already
        if self.is_master:
            return
        self.fill_minibatch()
        self.normalize_minibatch()
        self.map_minibatch_labels()
        if size < self.max_minibatch_size:
            self.minibatch_data[size:] = 0.0
            if self.has_labels:
                self.minibatch_labels[size:] = -1
            self.minibatch_indices[size:] = -1

    def fill_indices(self, start_offset, count):
        """Default host path: copy the indices window.  Returns True when
        a device path already produced the whole minibatch."""
        for arr in (self.minibatch_data, self.minibatch_labels,
                    self.minibatch_indices):
            arr.map_invalidate()
        self.shuffled_indices.map_read()
        self.minibatch_indices.mem[:count] = \
            self.shuffled_indices.mem[start_offset:start_offset + count]
        return False

    def normalize_minibatch(self):
        self.normalizer.normalize(
            self.minibatch_data.mem[:self.minibatch_size])

    def map_minibatch_labels(self):
        if not self.has_labels:
            return
        self.minibatch_labels.map_write()
        for i, raw in enumerate(
                self.raw_minibatch_labels[:self.minibatch_size]):
            self.minibatch_labels[i] = self.labels_mapping[raw]

    def analyze_dataset(self):
        """One pass over TRAIN building normalizer stats + labels mapping
        (reference loader/base.py:755)."""
        if self.class_lengths[TRAIN] == 0:
            if not self.normalizer.initialized:
                raise LoaderError(
                    "no train samples and the normalizer is uninitialized")
            return
        if isinstance(self.normalizer, StatelessNormalizer):
            self.normalizer.analyze(self.minibatch_data.mem)
            self._build_labels_mapping_if_needed()
            return
        raw_labels = set()

        def callback():
            self.normalizer.analyze(
                self.minibatch_data.mem[:self.minibatch_size])
            raw_labels.update(
                l for l in self.raw_minibatch_labels[:self.minibatch_size]
                if l is not None)

        self._iterate_class(TRAIN, callback)
        if raw_labels and not self.labels_mapping:
            for i, lbl in enumerate(sorted(raw_labels)):
                self.labels_mapping[lbl] = i

    def _build_labels_mapping_if_needed(self):
        """Hook for subclasses that can derive labels without iteration."""

    def _iterate_class(self, class_index, callback):
        """Serve every minibatch of one class through fill_minibatch."""
        size = self.class_lengths[class_index]
        start = self.class_end_offsets[class_index] - size
        if not self.shuffled_indices:
            self.shuffled_indices.mem = numpy.arange(
                self.total_samples, dtype=self.INDEX_DTYPE)
        for offset in range(start, start + size, self.max_minibatch_size):
            count = min(self.max_minibatch_size, start + size - offset)
            self.minibatch_size = count
            self.minibatch_indices.mem[:count] = \
                self.shuffled_indices.mem[offset:offset + count]
            self.fill_minibatch()
            callback()

    def _class_ended(self):
        current = (self._flags_global_offset_
                   if self._flags_global_offset_ is not None
                   else self.global_offset)
        for offset in self.effective_class_end_offsets:
            if current == offset:
                return True
            if current < offset:
                return False
        raise LoaderError("global_offset out of bounds")

    def class_index_by_sample_index(self, index):
        for class_index, class_offset in enumerate(
                self.effective_class_end_offsets):
            if index < class_offset:
                return class_index, class_offset - index
        raise LoaderError("sample index %d out of bounds" % index)

    def _calc_class_end_offsets(self):
        total = 0
        for i, n in enumerate(self.class_lengths):
            total += int(n)
            self.class_end_offsets[i] = total
        if total == 0:
            raise LoaderError("there is no data to serve")

    def _update_flags(self):
        if self.is_slave:
            return  # set explicitly by apply_data_from_master
        if self._flags_global_offset_ is not None:
            # apply time: the job's own serve-time snapshot decides
            # whether it closed its class (exact under async pipelining)
            last_mb = self._class_ended() and not self.failed_minibatches
        else:
            last_mb = (self._class_ended() and
                       (not self.pending_minibatches_count or
                        not self.is_master) and
                       not self.failed_minibatches)
        self._set_flag("last_minibatch", last_mb)
        self._set_flag("epoch_ended", last_mb and (
            self.minibatch_class == VALID or
            (self.minibatch_class == TEST and
             self.class_lengths[TRAIN] == self.class_lengths[VALID] == 0) or
            (self.minibatch_class == TEST and self.testing) or
            (self.minibatch_class == TRAIN and
             self.class_lengths[VALID] == 0)))

    def _advance_global_offset(self):
        if self.is_slave:
            return self.minibatch_offset, self.minibatch_size
        if self.global_offset >= self.effective_total_samples:
            self.global_offset = 0
            self.shuffle()
        self.minibatch_class, remainder = self.class_index_by_sample_index(
            self.global_offset)
        size = min(remainder, self.max_minibatch_size)
        self.global_offset += size
        self._set_flag("train_ended",
                       self.global_offset >= self.effective_total_samples)
        self._set_flag("test_ended",
                       self.global_offset >= self.class_end_offsets[TEST])
        return self.global_offset, size

    def _on_successful_serve(self):
        self.samples_served += self.minibatch_size
        if not self.is_slave and self.samples_served > 0:
            num, den = divmod(self.samples_served,
                              self.effective_total_samples)
            self.epoch_number = num
            now = time.time()
            if now - self._serve_log_time_ >= 10:
                self._serve_log_time_ = now
                self.info(
                    "Served %d samples (%d epochs, %.1f%%); failed %d, "
                    "pending %d", self.samples_served, num,
                    100.0 * den / self.effective_total_samples,
                    len(self.failed_minibatches),
                    self.pending_minibatches_count)


class LoaderMSEMixin(object):
    """Adds regression targets to the contract
    (reference: veles/loader/base.py LoaderMSEMixin)."""

    def __init__(self, workflow, **kwargs):
        super(LoaderMSEMixin, self).__init__(workflow, **kwargs)
        self.minibatch_targets = Array(shallow_pickle=True)
        self.targets_shape = None
        self.target_normalization_type = kwargs.get(
            "target_normalization_type", "none")
        self.target_normalization_parameters = kwargs.get(
            "target_normalization_parameters", {})
        self._target_normalizer = None

    @property
    def target_normalizer(self):
        if self._target_normalizer is None:
            self._target_normalizer = NormalizerRegistry.get(
                self.target_normalization_type,
                **self.target_normalization_parameters)
        return self._target_normalizer
