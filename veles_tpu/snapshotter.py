"""Workflow snapshots: periodic whole-workflow pickles with codecs.

TPU-native counterpart of reference veles/snapshotter.py:84,360,522.
Preserved capabilities: interval + time-interval gating with a ``skip``
Bool, compression codecs (none/gz/bz2/xz + snappy when available), the
``_current`` symlink, restore via :meth:`SnapshotterBase.import_file`,
size warning with a per-unit pickle-size top-5, and destruction of
pending state so restored runs are consistent.

Crash consistency (docs/checkpointing.md): every snapshot is written to
``<dest>.tmp``, fsynced, ``os.replace``d into place, and the directory
fsynced, so a ``kill -9`` at any instant leaves either the complete new
file or no new file — never a torn one at the final path.  A sidecar
manifest (``<dest>.manifest``, JSON: sha256, nbytes, codec, epoch,
workflow checksum/metric) makes every snapshot verifiable;
:meth:`import_file` checks it before unpickling and falls back to the
newest previous-good snapshot when the preferred one is truncated or
corrupt.  ``keep=N`` bounds the on-disk history (the best-by-metric and
the ``_current`` target always survive); the default keeps everything,
reference parity.

TPU note: device arrays snapshot through ``Array.__getstate__`` which
performs ``map_read`` (device->host) first, so a snapshot taken mid-run
is a complete host-side image; restore re-uploads lazily at first unmap,
resharding onto whatever mesh the restoring process has.
"""

import bz2
import glob
import gzip
import hashlib
import json
import logging
import lzma
import os
import pickle
import time

from veles_tpu import chaos
from veles_tpu.config import root
from veles_tpu.health import RollbackExhausted
from veles_tpu.mutable import Bool
from veles_tpu.observe.flight import flight as _flight
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.units import Unit

__all__ = ["SnapshotterBase", "Snapshotter", "SnapshotError",
           "RollbackExhausted", "MANIFEST_SUFFIX", "LATEST_NAME",
           "publish_snapshot", "publish_schedule_bank", "read_latest",
           "write_state_snapshot", "load_state_snapshot",
           "latest_state_snapshot"]

#: sidecar manifest filename suffix (next to the snapshot it describes)
MANIFEST_SUFFIX = ".manifest"

#: the publish directory's atomic pointer file (freshness loop)
LATEST_NAME = "LATEST"

#: module-level logger for the static restore/verify paths
_log = logging.getLogger("Snapshotter")


class SnapshotError(Exception):
    """No usable snapshot could be restored."""


CODECS = {
    "": (lambda path: open(path, "wb"), lambda path: open(path, "rb")),
    "gz": (lambda path: gzip.open(path, "wb", 6),
           lambda path: gzip.open(path, "rb")),
    "bz2": (lambda path: bz2.open(path, "wb", 6),
            lambda path: bz2.open(path, "rb")),
    "xz": (lambda path: lzma.open(path, "wb", preset=1),
           lambda path: lzma.open(path, "rb")),
}

try:  # snappy framing, reference parity (snapshotter.py:249-356)
    import snappy  # noqa: F401

    class _SnappyWriter(object):
        def __init__(self, path):
            self._file = open(path, "wb")
            self._compressor = snappy.StreamCompressor()

        def write(self, data):
            self._file.write(self._compressor.compress(data))

        def flush(self):
            self._file.flush()

        def close(self):
            self._file.close()

        def __enter__(self):
            return self

        def __exit__(self, *args):
            self.close()

    class _SnappyReader(object):
        def __init__(self, path):
            with open(path, "rb") as fin:
                self._data = snappy.StreamDecompressor().decompress(
                    fin.read())
            self._pos = 0

        def read(self, size=-1):
            if size < 0:
                size = len(self._data) - self._pos
            chunk = self._data[self._pos:self._pos + size]
            self._pos += len(chunk)
            return chunk

        def readline(self):
            idx = self._data.find(b"\n", self._pos)
            end = len(self._data) if idx < 0 else idx + 1
            chunk = self._data[self._pos:end]
            self._pos = end
            return chunk

        def close(self):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *args):
            self.close()

    CODECS["snappy"] = (_SnappyWriter, _SnappyReader)
except ImportError:
    pass

#: warn when a snapshot exceeds this many bytes (reference: 1 GB warning)
SIZE_WARNING = 1 << 30


def _write_bytes_atomic(path, data):
    """The ONE tmp -> fsync -> ``os.replace`` -> dir-fsync sequence for
    small metadata files (manifests, the publish LATEST pointer)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as fout:
        fout.write(data)
        fout.flush()
        os.fsync(fout.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def _fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path):
    """Durably record a rename/creation in its directory; best-effort
    (some filesystems refuse O_RDONLY directory fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _file_sha256(path):
    digest = hashlib.sha256()
    with open(path, "rb") as fin:
        for block in iter(lambda: fin.read(1 << 20), b""):
            digest.update(block)
    return digest.hexdigest()


def _manifest_path(path):
    """Manifest sidecar for a snapshot; symlinks (``_current``) resolve
    to their target first, since the sidecar sits next to the data."""
    return os.path.realpath(path) + MANIFEST_SUFFIX


def read_latest(publish_dir):
    """The publish directory's ``LATEST`` pointer as a dict, or None
    when absent/unparseable/mid-replace — the watcher treats every
    failure mode as "nothing new yet"."""
    try:
        with open(os.path.join(publish_dir, LATEST_NAME), "rb") as fin:
            latest = json.loads(fin.read().decode())
    except (OSError, ValueError):
        return None
    if not isinstance(latest, dict) or "snapshot" not in latest:
        return None
    return latest


def _next_publish_ordinal(publish_dir):
    """Next export ordinal: one past the largest already published
    (scanned from filenames AND the LATEST pointer, so a crashed
    publish that never flipped LATEST still cannot reuse its
    ordinal)."""
    best = 0
    latest = read_latest(publish_dir)
    if latest is not None:
        try:
            best = int(latest.get("ordinal", 0))
        except (TypeError, ValueError):
            best = 0
    try:
        names = os.listdir(publish_dir)
    except OSError:
        names = []
    for name in names:
        head = name.split("_", 1)[0]
        if head.isdigit():
            best = max(best, int(head))
    return best + 1


def _copy_atomic(src, dest):
    """Stream-copy ``src`` to ``dest`` through tmp -> fsync ->
    os.replace, so the final path is never torn."""
    tmp = dest + ".tmp"
    with open(src, "rb") as fin, open(tmp, "wb") as fout:
        for block in iter(lambda: fin.read(1 << 20), b""):
            fout.write(block)
        fout.flush()
        os.fsync(fout.fileno())
    os.replace(tmp, dest)


def publish_snapshot(path, publish_dir, keep=8):
    """Publish a manifest-verified snapshot into the watched publish
    directory — the trainer half of the train-to-serve freshness loop
    (docs/serving.md "Freshness loop").

    The publish contract the serve-side ``SnapshotWatcher`` relies on:

    - the snapshot bytes and the sidecar manifest are copied (manifest
      FIRST, both atomically) under an export-ordinal-ordered name
      ``NNNNNN_<basename>``, so publication order survives clock skew
      and same-second exports;
    - only after both are in place does the ``LATEST`` pointer flip
      (atomic tmp -> ``os.replace``), so a watcher that sees an ordinal
      can always find its files — a crash at any instant leaves LATEST
      pointing at a complete previous publish;
    - the publish dir keeps its own bounded history (``keep`` newest
      ordinals; the LATEST target always survives) and is EXEMPT from
      the train directory's ``keep=N`` retention — it is a *view* for
      the serve fleet, not the training run's crash-recovery history
      (docs/checkpointing.md).

    An unverifiable snapshot is refused here — the publish side is the
    first line of the "a poisoned snapshot never reaches the fleet"
    defense.  Returns ``{"ordinal", "snapshot", "sha256"}``.

    Chaos point ``freshness.publish`` (docs/health.md table):
    ``truncate`` writes only half the snapshot bytes at the FINAL path
    (a non-atomic publisher / torn copy — the watcher must
    skip-and-retry), ``crash`` dies after the copy but before the
    LATEST flip (stale pointer; the ordinal is burned)."""
    real = os.path.realpath(path)
    ok, detail = SnapshotterBase.verify_snapshot(real)
    if ok is False:
        raise SnapshotError(
            "refusing to publish %s: %s" % (path, detail))
    if ok is None:
        raise SnapshotError(
            "refusing to publish %s without a manifest: the watcher "
            "verifies BEFORE unpickling, an unverifiable snapshot "
            "could never be accepted (%s)" % (path, detail))
    os.makedirs(publish_dir, exist_ok=True)
    ordinal = _next_publish_ordinal(publish_dir)
    name = "%06d_%s" % (ordinal, os.path.basename(real))
    dest = os.path.join(publish_dir, name)
    # manifest first: from the instant the data file exists the watcher
    # can verify it — there is no window where a complete-looking
    # snapshot sits beside no manifest
    _copy_atomic(real + MANIFEST_SUFFIX, dest + MANIFEST_SUFFIX)
    fault = chaos.plan.fire("freshness.publish") \
        if chaos.plan is not None else None
    if fault is not None and fault.action == "truncate":
        # a torn, NON-atomic copy at the final path: manifest present,
        # bytes short — exactly the half-written case the watcher's
        # skip-and-retry discipline exists for
        with open(real, "rb") as fin:
            payload = fin.read()
        with open(dest, "wb") as fout:
            fout.write(payload[:max(1, len(payload) // 2)])
    else:
        _copy_atomic(real, dest)
    _fsync_dir(publish_dir)
    if fault is not None and fault.action == "crash":
        raise chaos.ChaosCrash("simulated crash mid-publish (LATEST "
                               "not flipped)")
    manifest = SnapshotterBase.read_manifest(dest) or {}
    latest = {
        "version": 1,
        "ordinal": ordinal,
        "snapshot": name,
        "sha256": manifest.get("sha256"),
        "published": time.strftime("%Y-%m-%d %H:%M:%S"),
    }
    _write_bytes_atomic(
        os.path.join(publish_dir, LATEST_NAME),
        json.dumps(latest, indent=1, sort_keys=True).encode())
    # bounded history: this is a serve-side view, not the training
    # run's recovery history — prune ordinals past `keep` (the LATEST
    # target is by construction among the newest)
    if keep and keep > 0:
        published = []
        for entry in os.listdir(publish_dir):
            head = entry.split("_", 1)[0]
            if head.isdigit() and not entry.endswith(".tmp") and \
                    not entry.endswith(MANIFEST_SUFFIX):
                published.append((int(head), entry))
        for _, entry in sorted(published)[:-keep]:
            for victim in (entry, entry + MANIFEST_SUFFIX):
                try:
                    os.remove(os.path.join(publish_dir, victim))
                except OSError:
                    pass
    _registry.counter("serve.freshness.published").inc()
    _tracer.instant("freshness.publish", cat="freshness",
                    ordinal=ordinal, snapshot=name)
    return {"ordinal": ordinal, "snapshot": dest,
            "sha256": latest["sha256"]}


def publish_schedule_bank(publish_dir, cache=None):
    """Publish the local schedule cache as a manifest-verified fleet
    bank beside the snapshots (``schedule_bank.json`` — docs/
    kernels.md "Autotuning"): one host's tuning pays for the fleet.

    Same channel discipline as :func:`publish_snapshot`: the manifest
    lands FIRST, so from the instant the bank bytes flip a watcher can
    verify them; during the (manifest-new, bank-old) replace window
    verification fails and the watcher just retries next poll.
    Returns ``{"bank", "entries"}``, or None when the cache is empty
    (nothing to share is not an error)."""
    from veles_tpu.tune.cache import cache_for
    from veles_tpu.tune.cache import BANK_FILE_NAME
    cache = cache_for() if cache is None else cache
    count = len(cache)
    if count == 0:
        return None
    os.makedirs(publish_dir, exist_ok=True)
    dest = os.path.join(publish_dir, BANK_FILE_NAME)
    tmp = dest + ".export"
    count = cache.export_bank(tmp)
    SnapshotterBase.write_manifest(tmp, workflow_name="schedule_bank")
    os.replace(tmp + MANIFEST_SUFFIX, dest + MANIFEST_SUFFIX)
    os.replace(tmp, dest)
    _fsync_dir(publish_dir)
    _registry.counter("tune.bank_published").inc()
    _tracer.instant("tune.bank_publish", cat="tune", entries=count)
    return {"bank": dest, "entries": count}


class SnapshotterBase(Unit):
    """Common logic: gating, naming, codec selection, restore."""

    hide_from_registry = True

    @classmethod
    def init_parser(cls, parser):
        parser.add_argument(
            "--snapshot-dir", default=None,
            help="snapshot output directory")
        parser.add_argument(
            "--snapshot-interval", type=int, default=None,
            help="snapshot every N improvements")
        parser.add_argument(
            "--snapshot-time-interval", type=float, default=None,
            help="minimum seconds between snapshots")
        parser.add_argument(
            "--snapshot-compress", default=None,
            choices=("", "gz", "bz2", "xz"),
            help="snapshot compression codec")
        parser.add_argument(
            "--disable-snapshotting", action="store_true")
        parser.add_argument(
            "--snapshot-db", default=None,
            help="sqlite file recording snapshot history (the "
                 "reference's ODBC sink analog)")
        parser.add_argument(
            "--snapshot-keep", type=int, default=None, metavar="N",
            help="retain only the newest N snapshots (plus the "
                 "best-by-metric and the _current target); 0 keeps "
                 "everything")
        parser.add_argument(
            "--rollback-budget", type=int, default=None, metavar="N",
            help="in-process divergence rollbacks allowed before the "
                 "run hard-fails (docs/health.md)")
        parser.add_argument(
            "--publish-dir", default=None, metavar="DIR",
            help="also publish every manifest-verified snapshot into "
                 "this watched directory for the serve fleet's "
                 "freshness loop (docs/serving.md)")
        parser.add_argument(
            "--publish-keep", type=int, default=None, metavar="N",
            help="published snapshots retained in the publish dir "
                 "(its own bounded view; the train dir's "
                 "--snapshot-keep is separate)")
        return parser

    @classmethod
    def apply_args(cls, args):
        cfg = {}
        if getattr(args, "snapshot_dir", None):
            cfg["dir"] = args.snapshot_dir
        if getattr(args, "snapshot_interval", None) is not None:
            cfg["interval"] = args.snapshot_interval
        if getattr(args, "snapshot_time_interval", None) is not None:
            cfg["time_interval"] = args.snapshot_time_interval
        if getattr(args, "snapshot_compress", None) is not None:
            cfg["compression"] = args.snapshot_compress
        if getattr(args, "snapshot_db", None):
            cfg["db"] = args.snapshot_db
        if getattr(args, "snapshot_keep", None) is not None:
            cfg["keep"] = args.snapshot_keep
        if getattr(args, "rollback_budget", None) is not None:
            cfg["rollback_budget"] = args.rollback_budget
        root.common.snapshot.update(cfg)
        fresh = {}
        if getattr(args, "publish_dir", None):
            fresh["publish_dir"] = args.publish_dir
        if getattr(args, "publish_keep", None) is not None:
            fresh["keep"] = args.publish_keep
        if fresh:
            root.common.freshness.update(fresh)
        if getattr(args, "disable_snapshotting", False):
            root.common.disable.update({"snapshotting": True})

    def __init__(self, workflow, **kwargs):
        cfg = root.common.snapshot
        self.prefix = kwargs.pop("prefix", "wf")
        self.directory = kwargs.pop(
            "directory", cfg.get("dir") or
            root.common.dirs.get("snapshots", "/tmp"))
        self.compression = kwargs.pop(
            "compression", cfg.get("compression", "gz"))
        self.interval = kwargs.pop("interval", cfg.get("interval", 1))
        self.time_interval = kwargs.pop(
            "time_interval", cfg.get("time_interval", 15))
        self._db_path = kwargs.pop("db_path", cfg.get("db"))
        # retention: 0/None = unlimited (reference parity); the
        # best-by-metric snapshot and the _current target always survive
        self.keep = kwargs.pop("keep", cfg.get("keep", 0))
        self.keep_best = kwargs.pop("keep_best", True)
        # divergence recovery (docs/health.md): in-process rollbacks
        # allowed before the run hard-fails with RollbackExhausted
        self.rollback_budget = kwargs.pop(
            "rollback_budget", cfg.get("rollback_budget", 3))
        # freshness-loop publishing (docs/serving.md): None = off
        fresh = root.common.freshness
        self.publish_dir = kwargs.pop(
            "publish_dir", fresh.get("publish_dir"))
        self.publish_keep = kwargs.pop(
            "publish_keep", fresh.get("keep", 8))
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.skip = Bool(False)
        self.suffix = None
        self.destination = None
        self.rollbacks = 0
        self._counter = 0
        self._exports = 0
        self._last_time = 0.0

    def initialize(self, **kwargs):
        os.makedirs(self.directory, exist_ok=True)
        self._last_time = time.time()
        _registry.gauge("health.rollbacks_remaining").set(
            max(0, self.rollback_budget - self.rollbacks))
        return super(SnapshotterBase, self).initialize(**kwargs)

    def run(self):
        if root.common.disable.get("snapshotting", False):
            return
        if self.workflow is not None and self.workflow.workflow_mode == \
                "slave":
            return  # only master/standalone snapshot (reference :160)
        self._counter += 1
        if bool(self.skip):
            return
        if self._counter % self.interval:
            return
        # time_interval throttles REPEAT snapshots; the first one is
        # exempt, else a short run (or a crash before time_interval
        # elapses) leaves nothing on disk to resume from
        if self.destination is not None and \
                time.time() - self._last_time < self.time_interval:
            return
        self._last_time = time.time()
        self.export()

    def export(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def _workflow_epoch_metric(self):
        decision = getattr(self.workflow, "decision", None)
        metric = getattr(decision, "best_metric", None)
        epoch = getattr(decision, "epoch_number", None)
        return (epoch, float(metric) if metric is not None else None)

    def _record_in_db(self, destination, nbytes):
        """Append a row to the snapshot database (the reference's ODBC
        sink, snapshotter.py:428-518; sqlite here).  Enabled via
        ``db_path=`` kwarg or root.common.snapshot.db.  A DB failure
        (locked/readonly sqlite) only warns: the snapshot itself is
        already safe on disk and must not abort the training step."""
        db_path = self._db_path
        if not db_path:
            return
        try:
            self._record_in_db_unchecked(destination, nbytes)
        except Exception as exc:
            self.warning(
                "snapshot db record failed (%s: %s); continuing — the "
                "snapshot itself is safe at %s",
                type(exc).__name__, exc, destination)

    def _record_in_db_unchecked(self, destination, nbytes):
        import sqlite3
        epoch, metric = self._workflow_epoch_metric()
        with sqlite3.connect(self._db_path) as conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS snapshots ("
                "  id INTEGER PRIMARY KEY AUTOINCREMENT,"
                "  timestamp TEXT NOT NULL,"
                "  prefix TEXT, workflow TEXT, checksum TEXT,"
                "  destination TEXT, bytes INTEGER,"
                "  epoch INTEGER, best_metric REAL)")
            conn.execute(
                "INSERT INTO snapshots (timestamp, prefix, workflow, "
                "checksum, destination, bytes, epoch, best_metric) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
                (time.strftime("%Y-%m-%d %H:%M:%S"), self.prefix,
                 type(self.workflow).__name__,
                 getattr(self.workflow, "checksum", None),
                 destination, nbytes, epoch, metric))

    def _destination(self):
        # the export ordinal disambiguates same-second exports: a
        # second-resolution timestamp alone silently OVERWRITES the
        # previous snapshot (destroying the previous-good fallback)
        self._exports += 1
        suffix = self.suffix or "%s.%03d" % (
            time.strftime("%Y%m%d_%H%M%S"), self._exports)
        ext = (".%s" % self.compression) if self.compression else ""
        return os.path.join(
            self.directory,
            "%s_%s.%d.pickle%s" % (self.prefix, suffix,
                                   pickle.HIGHEST_PROTOCOL, ext))

    def _update_current_link(self):
        # atomic replace: _current is the canonical crash-resume
        # target, so there must never be a window without it
        link = os.path.join(self.directory, "%s_current" % self.prefix)
        temp = link + ".tmp"
        try:
            try:
                os.remove(temp)
            except FileNotFoundError:
                pass
            os.symlink(os.path.basename(self.destination), temp)
            os.replace(temp, link)
            _fsync_dir(self.directory)
        except OSError as exc:
            # a failed flip means _current (the canonical resume
            # target) silently stops tracking the newest snapshot —
            # that must never be invisible
            self.warning(
                "failed to update snapshot link %s -> %s (%s); resume "
                "will use an OLDER snapshot", link,
                os.path.basename(self.destination), exc)

    # -- verification / restore --------------------------------------------

    @staticmethod
    def write_manifest(destination, workflow_name=None, checksum=None,
                       codec=None, epoch=None, best_metric=None):
        """Write the sidecar manifest for a finished snapshot file,
        atomically (tmp -> fsync -> replace -> dir fsync)."""
        manifest = {
            "version": 1,
            "sha256": _file_sha256(destination),
            "nbytes": os.path.getsize(destination),
            "codec": codec or "",
            "workflow": workflow_name,
            "checksum": checksum,
            "epoch": epoch,
            "best_metric": best_metric,
            "timestamp": time.strftime("%Y-%m-%d %H:%M:%S"),
        }
        _write_bytes_atomic(
            destination + MANIFEST_SUFFIX,
            json.dumps(manifest, indent=1, sort_keys=True).encode())
        return manifest

    @staticmethod
    def read_manifest(path):
        """The manifest dict for a snapshot path, or None when absent
        or unparseable."""
        try:
            with open(_manifest_path(path), "rb") as fin:
                manifest = json.loads(fin.read().decode())
        except (OSError, ValueError):
            return None
        return manifest if isinstance(manifest, dict) else None

    @staticmethod
    def verify_snapshot(path):
        """Check a snapshot against its manifest.

        Returns ``(True, manifest)`` when it verifies, ``(None,
        reason)`` when there is no manifest to check against (legacy
        snapshot — restorable but unverifiable), and ``(False,
        reason)`` on truncation or checksum mismatch."""
        real = os.path.realpath(path)
        if not os.path.isfile(real):
            return False, "missing file %s" % real
        manifest = SnapshotterBase.read_manifest(real)
        if manifest is None:
            return None, "no manifest"
        nbytes = os.path.getsize(real)
        if nbytes != manifest.get("nbytes"):
            return False, "size mismatch (%d on disk, %s in manifest)" \
                % (nbytes, manifest.get("nbytes"))
        digest = _file_sha256(real)
        if digest != manifest.get("sha256"):
            return False, "sha256 mismatch"
        return True, manifest

    @staticmethod
    def _iter_verified_snapshots(directory, exclude=()):
        """Manifest-verified snapshots in ``directory``, newest first.

        Candidates are ordered by a cheap mtime stat and HASHED LAZILY,
        so a fallback restore only pays sha256 for the snapshots it
        actually tries, not the whole retained history."""
        exclude = {os.path.realpath(p) for p in exclude}
        found = []
        for mpath in glob.glob(os.path.join(directory,
                                            "*" + MANIFEST_SUFFIX)):
            snap = mpath[:-len(MANIFEST_SUFFIX)]
            if os.path.realpath(snap) in exclude:
                continue
            try:
                found.append((os.path.getmtime(snap), snap))
            except OSError:
                continue
        for _, snap in sorted(found, reverse=True):
            if SnapshotterBase.verify_snapshot(snap)[0]:
                yield snap

    @staticmethod
    def _verified_snapshots(directory, exclude=()):
        return list(SnapshotterBase._iter_verified_snapshots(
            directory, exclude=exclude))

    @staticmethod
    def _load_pickle(path):
        """Unpickle one snapshot file.  The codec is sniffed from the
        file's magic bytes, not the extension — the ``_current``
        symlink (the natural -w target) carries no extension."""
        with open(path, "rb") as probe:
            magic = probe.read(10)
        if magic[:2] == b"\x1f\x8b":
            codec = "gz"
        elif magic[:3] == b"BZh":
            codec = "bz2"
        elif magic[:6] == b"\xfd7zXZ\x00":
            codec = "xz"
        elif magic.startswith(b"\xff\x06\x00\x00sNaPpY") and \
                "snappy" in CODECS:
            codec = "snappy"
        else:
            # unknown magic: fall back to the extension (covers plain
            # pickles and any codec the sniff list lags behind)
            ext = os.path.splitext(path)[1].lstrip(".")
            codec = ext if ext in CODECS else ""
        _, opener = CODECS[codec]
        with opener(path) as fin:
            return pickle.load(fin)

    @staticmethod
    def import_file(path, fallback=True):
        """Restore a workflow object from a snapshot file.

        The sidecar manifest, when present, is verified (size + sha256)
        BEFORE unpickling.  A snapshot that fails verification or fails
        to load falls back to the newest previous-good (manifest-
        verified) snapshot in the same directory, so a torn write or a
        corrupted ``_current`` target never strands a resume; pass
        ``fallback=False`` to fail fast instead."""
        real = os.path.realpath(path)
        want = SnapshotterBase.read_manifest(real)

        def same_workflow(candidate):
            # NEVER fall back across workflows: a shared snapshot
            # directory (the out-of-the-box default) may hold several
            # models' histories.  Prefer the manifest identity; with no
            # primary manifest, require a shared filename prefix.
            if want is not None:
                manifest = SnapshotterBase.read_manifest(candidate)
                if manifest is None or \
                        manifest.get("workflow") != want.get("workflow"):
                    return False
                if manifest.get("checksum") != want.get("checksum"):
                    _log.warning(
                        "fallback snapshot %s was written by a "
                        "different source revision of %s", candidate,
                        want.get("workflow"))
                return True
            return os.path.basename(candidate).split("_")[0] == \
                os.path.basename(real).split("_")[0]

        def candidates():
            yield real, False
            if fallback:  # evaluated only once the primary has failed
                for prev in SnapshotterBase._iter_verified_snapshots(
                        os.path.dirname(real) or ".", exclude=(real,)):
                    if same_workflow(prev):
                        yield prev, True  # just verified — don't re-hash

        tried = 0
        errors = []
        for candidate, verified in candidates():
            tried += 1
            if not verified:
                ok, detail = SnapshotterBase.verify_snapshot(candidate)
                if ok is False:
                    _log.warning("snapshot %s failed verification: %s",
                                 candidate, detail)
                    errors.append("%s: %s" % (candidate, detail))
                    continue
                if ok is None:
                    _log.debug("snapshot %s has no manifest; restoring "
                               "unverified (legacy)", candidate)
            try:
                restored = SnapshotterBase._load_pickle(candidate)
            except Exception as exc:
                _log.warning("snapshot %s failed to load (%s: %s)",
                             candidate, type(exc).__name__, exc)
                errors.append("%s: %s" % (candidate, exc))
                continue
            if candidate != real:
                _log.warning(
                    "restored previous-good snapshot %s (%s was "
                    "invalid)", candidate, path)
            return restored
        raise SnapshotError(
            "no usable snapshot for %s (tried %d candidate(s): %s)" %
            (path, tried, "; ".join(errors) or "none found"))

    @staticmethod
    def resolve_resume(spec, directory=None):
        """Resolve a ``--resume`` spec to a snapshot path, or None.

        ``auto`` picks the newest ``*_current`` target under the
        snapshot directory (``root.common.snapshot.dir`` falling back
        to ``root.common.dirs.snapshots``), then the newest manifest-
        verified snapshot; None means "nothing to resume — start
        fresh".  Any other spec is an explicit path (which must
        exist).  Validation and previous-good fallback happen at
        :meth:`import_file` time."""
        if not spec:
            return None
        if spec != "auto":
            if not os.path.exists(spec):
                raise SnapshotError("--resume %s: no such snapshot" %
                                    spec)
            return spec
        if directory is None:
            cfg = root.common.snapshot
            directory = cfg.get("dir") or root.common.dirs.get(
                "snapshots", "/tmp")
        if not os.path.isdir(directory):
            return None
        targets = []
        for link in glob.glob(os.path.join(directory, "*_current")):
            target = os.path.realpath(link)
            if os.path.isfile(target):
                targets.append((os.path.getmtime(target), target))
            else:
                _log.warning("broken snapshot link %s -> %s", link,
                             target)
        if targets:
            return sorted(targets, reverse=True)[0][1]
        verified = SnapshotterBase._verified_snapshots(directory)
        return verified[0] if verified else None

    # -- in-process divergence rollback (docs/health.md) --------------------

    def rollback(self, reason=""):
        """Restore the newest manifest-VERIFIED snapshot's model state
        into the LIVE workflow, in process — the decision watchdog's
        recovery path when training diverges (sustained non-finite
        steps, loss spike).

        Unlike ``--resume`` this does not replace the workflow object:
        the run keeps its loader position and epoch bookkeeping and
        only the model state (params + solver accumulators) rolls back,
        via the workflow's ``adopt_model_state`` hook; the caller then
        applies LR backoff and reseeds stochastic streams so the retry
        is not a bit-exact replay of the divergence.  Bounded by
        ``rollback_budget``: when the budget is spent the run
        HARD-FAILS with :class:`RollbackExhausted` — looping rollback
        -> divergence forever is worse than dying loudly."""
        self.rollbacks += 1
        _registry.counter("health.rollbacks").inc()
        _registry.gauge("health.rollbacks_remaining").set(
            max(0, self.rollback_budget - self.rollbacks))
        if self.rollbacks > self.rollback_budget:
            raise RollbackExhausted(
                "rollback budget exhausted (%d allowed) and training "
                "still diverges: %s" % (self.rollback_budget, reason))
        adopt = getattr(self.workflow, "adopt_model_state", None)
        if adopt is None:
            raise SnapshotError(
                "cannot roll back: workflow %s has no "
                "adopt_model_state hook" % type(self.workflow).__name__)
        errors = []
        for path in self._iter_verified_snapshots(self.directory):
            if not os.path.basename(path).startswith(self.prefix + "_"):
                continue
            try:
                # verified just above by the iterator: no fallback
                # cascade — each candidate stands or falls alone
                restored = self.import_file(path, fallback=False)
                adopt(restored)
            except Exception as exc:
                self.warning("rollback candidate %s unusable (%s: %s)",
                             path, type(exc).__name__, exc)
                errors.append("%s: %s" % (path, exc))
                continue
            self.warning(
                "rolled back model state to verified snapshot %s "
                "[%d/%d, reason: %s]", path, self.rollbacks,
                self.rollback_budget, reason or "unspecified")
            _tracer.instant("snapshot.rollback", cat="snapshot",
                            path=path, reason=reason)
            # the pre-rollback timeline is about to be overwritten by
            # the restored state's — preserve it in a black-box dump
            _flight.dump(reason="rollback")
            return path
        raise SnapshotError(
            "no verified snapshot to roll back to in %s (%s)" %
            (self.directory, "; ".join(errors) or "none found"))


class Snapshotter(SnapshotterBase):
    """Pickles the whole workflow through the selected codec."""

    def export(self):
        destination = self._destination()
        start = time.perf_counter()
        self._prefetch_device_arrays()
        payload = pickle.dumps(self.workflow,
                               protocol=pickle.HIGHEST_PROTOCOL)
        if len(payload) > SIZE_WARNING:
            self.check_snapshot_size()
        try:
            self._write_atomic(destination, payload)
        except OSError as exc:
            # Disk trouble (ENOSPC and friends) must not kill a
            # training run: the previous snapshot and _current are
            # untouched, so recovery capability degrades but survives.
            self.error(
                "snapshot write to %s failed (%s); previous snapshot "
                "kept, training continues", destination, exc)
            self._remove_quiet(destination + ".tmp")
            return
        self.destination = destination
        epoch, metric = self._workflow_epoch_metric()
        try:
            self.write_manifest(
                destination, workflow_name=type(self.workflow).__name__,
                checksum=getattr(self.workflow, "checksum", None),
                codec=self.compression, epoch=epoch, best_metric=metric)
        except OSError as exc:
            self.warning("manifest write for %s failed (%s); snapshot "
                         "restorable but unverifiable", destination, exc)
        self._update_current_link()
        self._record_in_db(destination, len(payload))
        self._apply_retention()
        # elapsed stamped BEFORE the publish copy: snapshot.write_s is
        # the checkpoint write cost (docs/checkpointing.md), and the
        # train-dir snapshot is already durable whether or not the
        # freshness view gets its copy
        elapsed = time.perf_counter() - start
        self._publish(destination)
        _registry.counter("snapshot.exports").inc()
        _registry.histogram("snapshot.write_s").observe(elapsed)
        if _tracer.enabled:
            _tracer.complete("snapshot.export", start, elapsed,
                             cat="snapshot",
                             args={"bytes": len(payload),
                                   "destination": destination})
        self.info("snapshot -> %s (%.1f MB, %.2f s)", destination,
                  len(payload) / 1e6, elapsed)

    def _publish(self, destination):
        """Trainer-side freshness hook: push the finished (verified,
        manifested) snapshot into the publish directory.  A publish
        failure degrades freshness, not training — warn and continue;
        the train-dir snapshot is already safe."""
        if not self.publish_dir:
            return
        try:
            receipt = publish_snapshot(destination, self.publish_dir,
                                       keep=self.publish_keep)
        except Exception as exc:
            self.warning(
                "snapshot publish to %s failed (%s: %s); training "
                "continues, the serve fleet keeps its current model",
                self.publish_dir, type(exc).__name__, exc)
            return
        self.info("published snapshot #%d -> %s", receipt["ordinal"],
                  receipt["snapshot"])
        try:
            bank = publish_schedule_bank(self.publish_dir)
        except Exception as exc:
            self.warning("schedule bank publish to %s failed (%s: "
                         "%s); the fleet keeps its current schedules",
                         self.publish_dir, type(exc).__name__, exc)
            return
        if bank is not None:
            self.info("published schedule bank (%d entries) -> %s",
                      bank["entries"], bank["bank"])

    def _write_atomic(self, destination, payload):
        """tmp -> fsync -> os.replace -> directory fsync.  A crash at
        any instant leaves either the complete new snapshot or only a
        ``.tmp`` residue — the final path is never torn, so ``_current``
        can never point at a half-written file."""
        tmp = destination + ".tmp"
        writer, _ = CODECS.get(self.compression, CODECS[""])
        with writer(tmp) as fout:
            if chaos.plan is not None:
                self._chaos_write(fout, payload)
            fout.write(payload)
        _fsync_file(tmp)
        os.replace(tmp, destination)
        _fsync_dir(self.directory)

    def _chaos_write(self, fout, payload):
        fault = chaos.plan.fire("snapshot.write")
        if fault is None:
            return
        if fault.action == "crash":
            # half the payload lands in the .tmp file, then the
            # "process dies": os.replace never runs
            fout.write(payload[:max(1, len(payload) // 2)])
            flush = getattr(fout, "flush", None)
            if flush is not None:
                flush()
            raise chaos.ChaosCrash("simulated crash mid-snapshot-write")
        if fault.action == "enospc":
            raise chaos.enospc()

    @staticmethod
    def _remove_quiet(path):
        try:
            os.remove(path)
        except OSError:
            pass

    def _apply_retention(self):
        """Prune old snapshots beyond ``keep``; the best-by-metric
        (lower is better, the decision's convention) and the _current
        target always survive."""
        keep = int(self.keep or 0)
        if keep <= 0:
            return
        snaps = []
        for path in glob.glob(os.path.join(self.directory,
                                           self.prefix + "_*")):
            name = os.path.basename(path)
            if os.path.islink(path) or name.endswith(MANIFEST_SUFFIX) \
                    or name.endswith(".tmp"):
                continue
            if ".pickle" not in name:
                continue
            snaps.append((os.path.getmtime(path), path))
        snaps.sort(reverse=True)
        survivors = {os.path.realpath(p) for _, p in snaps[:keep]}
        link = os.path.join(self.directory, "%s_current" % self.prefix)
        if os.path.exists(link):
            survivors.add(os.path.realpath(link))
        if self.keep_best:
            best = None
            for _, path in snaps:
                manifest = self.read_manifest(path)
                metric = manifest.get("best_metric") if manifest else None
                if metric is not None and (best is None or
                                           metric < best[0]):
                    best = (metric, path)
            if best is not None:
                survivors.add(os.path.realpath(best[1]))
        for _, path in snaps:
            if os.path.realpath(path) in survivors:
                continue
            self.debug("retention (keep=%d): pruning %s", keep, path)
            self._remove_quiet(path)
            self._remove_quiet(path + MANIFEST_SUFFIX)

    def _prefetch_device_arrays(self):
        """Overlap the device->host reads the pickle is about to do:
        start async copies for every device-resident Array in one
        sweep so N arrays cost ~one tunnel round trip, not N
        (measured ~1.9 s/snapshot serialized on a tunneled TPU)."""
        from veles_tpu.memory import Array
        # fused workflows stage params back into unit Arrays first
        trainer = getattr(self.workflow, "fused_trainer", None)
        if trainer is not None:
            try:
                trainer.sync()
            except Exception:
                pass
        seen = set()
        for unit in getattr(self.workflow, "units", ()):
            for value in vars(unit).values():
                if isinstance(value, Array) and id(value) not in seen:
                    seen.add(id(value))
                    value.prefetch_host()

    def check_snapshot_size(self):
        """Log the top-5 units by pickle size (reference :203-225)."""
        sizes = []
        for unit in self.workflow.units:
            try:
                sizes.append((len(pickle.dumps(
                    unit, protocol=pickle.HIGHEST_PROTOCOL)), unit.name))
            except Exception:
                pass
        sizes.sort(reverse=True)
        self.warning("snapshot is large; top units by pickle size:")
        for nbytes, name in sizes[:5]:
            self.warning("  %8.1f MB  %s", nbytes / 1e6, name)


# -- raw state snapshots (parallel/mesh.py MeshManager) -------------------
#
# The elastic mesh's pre-reshard safety snapshots are plain pickled
# state pytrees, not whole workflows, but they ride the SAME atomics
# and manifest contract as every other snapshot in this module: tmp ->
# fsync -> os.replace -> dir-fsync, sha256+size sidecar written after
# the data is durable, verify-before-unpickle on restore.  That is
# what lets a crash mid-reshard recover through the existing
# ``--resume auto`` machinery instead of a parallel bespoke path.

def write_state_snapshot(path, obj, workflow_name=None, epoch=None):
    """Atomically pickle ``obj`` to ``path`` and write its manifest
    sidecar; returns the manifest.  Honors the ``snapshot.write``
    chaos point (crash leaves only a ``.tmp`` residue — the final
    path is never torn)."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fout:
        if chaos.plan is not None:
            fault = chaos.plan.fire("snapshot.write")
            if fault is not None:
                if fault.action == "crash":
                    fout.write(payload[:max(1, len(payload) // 2)])
                    fout.flush()
                    raise chaos.ChaosCrash(
                        "simulated crash mid-snapshot-write")
                if fault.action == "enospc":
                    raise chaos.enospc()
        fout.write(payload)
    _fsync_file(tmp)
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")
    return SnapshotterBase.write_manifest(
        path, workflow_name=workflow_name, epoch=epoch)


def load_state_snapshot(path):
    """Verify ``path`` against its manifest, then unpickle it.  Raises
    :class:`SnapshotError` on a failed or impossible verification —
    a torn or tampered state snapshot must never be resumed from."""
    ok, detail = SnapshotterBase.verify_snapshot(path)
    if not ok:
        raise SnapshotError("state snapshot %s failed verification: %s"
                            % (path, detail))
    return SnapshotterBase._load_pickle(os.path.realpath(path))


def latest_state_snapshot(directory):
    """The newest manifest-verified snapshot in ``directory`` (or None)
    — the ``--resume auto`` semantics for raw state snapshots."""
    for snap in SnapshotterBase._iter_verified_snapshots(directory):
        return snap
    return None
