"""Workflow snapshots: periodic whole-workflow pickles with codecs.

TPU-native counterpart of reference veles/snapshotter.py:84,360,522.
Preserved capabilities: interval + time-interval gating with a ``skip``
Bool, compression codecs (none/gz/bz2/xz + snappy when available), the
``_current`` symlink, restore via :meth:`SnapshotterBase.import_file`,
size warning with a per-unit pickle-size top-5, and destruction of
pending state so restored runs are consistent.

TPU note: device arrays snapshot through ``Array.__getstate__`` which
performs ``map_read`` (device->host) first, so a snapshot taken mid-run
is a complete host-side image; restore re-uploads lazily at first unmap,
resharding onto whatever mesh the restoring process has.
"""

import bz2
import gzip
import lzma
import os
import pickle
import time

from veles_tpu.config import root
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit

__all__ = ["SnapshotterBase", "Snapshotter"]

CODECS = {
    "": (lambda path: open(path, "wb"), lambda path: open(path, "rb")),
    "gz": (lambda path: gzip.open(path, "wb", 6),
           lambda path: gzip.open(path, "rb")),
    "bz2": (lambda path: bz2.open(path, "wb", 6),
            lambda path: bz2.open(path, "rb")),
    "xz": (lambda path: lzma.open(path, "wb", preset=1),
           lambda path: lzma.open(path, "rb")),
}

try:  # snappy framing, reference parity (snapshotter.py:249-356)
    import snappy  # noqa: F401

    class _SnappyWriter(object):
        def __init__(self, path):
            self._file = open(path, "wb")
            self._compressor = snappy.StreamCompressor()

        def write(self, data):
            self._file.write(self._compressor.compress(data))

        def close(self):
            self._file.close()

        def __enter__(self):
            return self

        def __exit__(self, *args):
            self.close()

    class _SnappyReader(object):
        def __init__(self, path):
            with open(path, "rb") as fin:
                self._data = snappy.StreamDecompressor().decompress(
                    fin.read())
            self._pos = 0

        def read(self, size=-1):
            if size < 0:
                size = len(self._data) - self._pos
            chunk = self._data[self._pos:self._pos + size]
            self._pos += len(chunk)
            return chunk

        def readline(self):
            idx = self._data.find(b"\n", self._pos)
            end = len(self._data) if idx < 0 else idx + 1
            chunk = self._data[self._pos:end]
            self._pos = end
            return chunk

        def close(self):
            pass

        def __enter__(self):
            return self

        def __exit__(self, *args):
            self.close()

    CODECS["snappy"] = (_SnappyWriter, _SnappyReader)
except ImportError:
    pass

#: warn when a snapshot exceeds this many bytes (reference: 1 GB warning)
SIZE_WARNING = 1 << 30


class SnapshotterBase(Unit):
    """Common logic: gating, naming, codec selection, restore."""

    hide_from_registry = True

    @classmethod
    def init_parser(cls, parser):
        parser.add_argument(
            "--snapshot-dir", default=None,
            help="snapshot output directory")
        parser.add_argument(
            "--snapshot-interval", type=int, default=None,
            help="snapshot every N improvements")
        parser.add_argument(
            "--snapshot-time-interval", type=float, default=None,
            help="minimum seconds between snapshots")
        parser.add_argument(
            "--snapshot-compress", default=None,
            choices=("", "gz", "bz2", "xz"),
            help="snapshot compression codec")
        parser.add_argument(
            "--disable-snapshotting", action="store_true")
        parser.add_argument(
            "--snapshot-db", default=None,
            help="sqlite file recording snapshot history (the "
                 "reference's ODBC sink analog)")
        return parser

    @classmethod
    def apply_args(cls, args):
        cfg = {}
        if getattr(args, "snapshot_dir", None):
            cfg["dir"] = args.snapshot_dir
        if getattr(args, "snapshot_interval", None) is not None:
            cfg["interval"] = args.snapshot_interval
        if getattr(args, "snapshot_time_interval", None) is not None:
            cfg["time_interval"] = args.snapshot_time_interval
        if getattr(args, "snapshot_compress", None) is not None:
            cfg["compression"] = args.snapshot_compress
        if getattr(args, "snapshot_db", None):
            cfg["db"] = args.snapshot_db
        root.common.snapshot.update(cfg)
        if getattr(args, "disable_snapshotting", False):
            root.common.disable.update({"snapshotting": True})

    def __init__(self, workflow, **kwargs):
        cfg = root.common.snapshot
        self.prefix = kwargs.pop("prefix", "wf")
        self.directory = kwargs.pop(
            "directory", cfg.get("dir") or
            root.common.dirs.get("snapshots", "/tmp"))
        self.compression = kwargs.pop(
            "compression", cfg.get("compression", "gz"))
        self.interval = kwargs.pop("interval", cfg.get("interval", 1))
        self.time_interval = kwargs.pop(
            "time_interval", cfg.get("time_interval", 15))
        self._db_path = kwargs.pop("db_path", cfg.get("db"))
        super(SnapshotterBase, self).__init__(workflow, **kwargs)
        self.skip = Bool(False)
        self.suffix = None
        self.destination = None
        self._counter = 0
        self._last_time = 0.0

    def initialize(self, **kwargs):
        os.makedirs(self.directory, exist_ok=True)
        self._last_time = time.time()
        return super(SnapshotterBase, self).initialize(**kwargs)

    def run(self):
        if root.common.disable.get("snapshotting", False):
            return
        if self.workflow is not None and self.workflow.workflow_mode == \
                "slave":
            return  # only master/standalone snapshot (reference :160)
        self._counter += 1
        if bool(self.skip):
            return
        if self._counter % self.interval:
            return
        # time_interval throttles REPEAT snapshots; the first one is
        # exempt, else a short run (or a crash before time_interval
        # elapses) leaves nothing on disk to resume from
        if self.destination is not None and \
                time.time() - self._last_time < self.time_interval:
            return
        self._last_time = time.time()
        self.export()

    def export(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def _record_in_db(self, destination, nbytes):
        """Append a row to the snapshot database (the reference's ODBC
        sink, snapshotter.py:428-518; sqlite here).  Enabled via
        ``db_path=`` kwarg or root.common.snapshot.db."""
        db_path = self._db_path
        if not db_path:
            return
        import sqlite3
        decision = getattr(self.workflow, "decision", None)
        metric = getattr(decision, "best_metric", None)
        epoch = getattr(decision, "epoch_number", None)
        with sqlite3.connect(db_path) as conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS snapshots ("
                "  id INTEGER PRIMARY KEY AUTOINCREMENT,"
                "  timestamp TEXT NOT NULL,"
                "  prefix TEXT, workflow TEXT, checksum TEXT,"
                "  destination TEXT, bytes INTEGER,"
                "  epoch INTEGER, best_metric REAL)")
            conn.execute(
                "INSERT INTO snapshots (timestamp, prefix, workflow, "
                "checksum, destination, bytes, epoch, best_metric) "
                "VALUES (?, ?, ?, ?, ?, ?, ?, ?)",
                (time.strftime("%Y-%m-%d %H:%M:%S"), self.prefix,
                 type(self.workflow).__name__,
                 getattr(self.workflow, "checksum", None),
                 destination, nbytes, epoch,
                 float(metric) if metric is not None else None))

    def _destination(self):
        suffix = self.suffix or time.strftime("%Y%m%d_%H%M%S")
        ext = (".%s" % self.compression) if self.compression else ""
        return os.path.join(
            self.directory,
            "%s_%s.%d.pickle%s" % (self.prefix, suffix,
                                   pickle.HIGHEST_PROTOCOL, ext))

    def _update_current_link(self):
        # atomic replace: _current is the canonical crash-resume
        # target, so there must never be a window without it
        link = os.path.join(self.directory, "%s_current" % self.prefix)
        temp = link + ".tmp"
        try:
            try:
                os.remove(temp)
            except FileNotFoundError:
                pass
            os.symlink(os.path.basename(self.destination), temp)
            os.replace(temp, link)
        except OSError:
            pass

    @staticmethod
    def import_file(path):
        """Restore a workflow object from a snapshot file.

        The codec is sniffed from the file's magic bytes, not the
        extension — the ``_current`` symlink (the natural -w target)
        carries no extension."""
        with open(path, "rb") as probe:
            magic = probe.read(10)
        if magic[:2] == b"\x1f\x8b":
            codec = "gz"
        elif magic[:3] == b"BZh":
            codec = "bz2"
        elif magic[:6] == b"\xfd7zXZ\x00":
            codec = "xz"
        elif magic.startswith(b"\xff\x06\x00\x00sNaPpY") and \
                "snappy" in CODECS:
            codec = "snappy"
        else:
            # unknown magic: fall back to the extension (covers plain
            # pickles and any codec the sniff list lags behind)
            ext = os.path.splitext(path)[1].lstrip(".")
            codec = ext if ext in CODECS else ""
        _, opener = CODECS[codec]
        with opener(path) as fin:
            return pickle.load(fin)


class Snapshotter(SnapshotterBase):
    """Pickles the whole workflow through the selected codec."""

    def export(self):
        self.destination = self._destination()
        writer, _ = CODECS.get(self.compression, CODECS[""])
        start = time.time()
        self._prefetch_device_arrays()
        payload = pickle.dumps(self.workflow,
                               protocol=pickle.HIGHEST_PROTOCOL)
        if len(payload) > SIZE_WARNING:
            self.check_snapshot_size()
        with writer(self.destination) as fout:
            fout.write(payload)
        self._update_current_link()
        self._record_in_db(self.destination, len(payload))
        self.info("snapshot -> %s (%.1f MB, %.2f s)", self.destination,
                  len(payload) / 1e6, time.time() - start)

    def _prefetch_device_arrays(self):
        """Overlap the device->host reads the pickle is about to do:
        start async copies for every device-resident Array in one
        sweep so N arrays cost ~one tunnel round trip, not N
        (measured ~1.9 s/snapshot serialized on a tunneled TPU)."""
        from veles_tpu.memory import Array
        # fused workflows stage params back into unit Arrays first
        trainer = getattr(self.workflow, "fused_trainer", None)
        if trainer is not None:
            try:
                trainer.sync()
            except Exception:
                pass
        seen = set()
        for unit in getattr(self.workflow, "units", ()):
            for value in vars(unit).values():
                if isinstance(value, Array) and id(value) not in seen:
                    seen.add(id(value))
                    value.prefetch_host()

    def check_snapshot_size(self):
        """Log the top-5 units by pickle size (reference :203-225)."""
        sizes = []
        for unit in self.workflow.units:
            try:
                sizes.append((len(pickle.dumps(
                    unit, protocol=pickle.HIGHEST_PROTOCOL)), unit.name))
            except Exception:
                pass
        sizes.sort(reverse=True)
        self.warning("snapshot is large; top units by pickle size:")
        for nbytes, name in sizes[:5]:
            self.warning("  %8.1f MB  %s", nbytes / 1e6, name)
