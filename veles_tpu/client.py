"""Slave-side control plane client.

TPU-native counterpart of reference veles/client.py:404.  Like the
Server, this plane is DEMOTED since the SPMD split
(docs/distributed.md): per-step gradients ride ICI inside the compiled
shard_map step, so the update payloads a slave ships here are small
control records (membership, loader bookkeeping, metrics) — the
protocol's elasticity semantics matter, its bandwidth no longer does.

Preserved capabilities: checksum handshake with computing-power report,
the job -> do_job -> update cycle, ASYNC-SLAVE pipelining (request the
next job while the previous update is still in flight, reference
client.py:278-354), reconnection with an attempt budget, and
``death_probability`` fault injection for chaos testing
(client.py:303-307).
"""

import asyncio
import os
import random
import signal
import threading
import time

from veles_tpu import chaos
from veles_tpu.cmdline import CommandLineArgumentsRegistry
from veles_tpu.config import root
from veles_tpu.logger import Logger
from veles_tpu.observe.cluster import estimate_offset
from veles_tpu.observe.metrics import registry as _registry
from veles_tpu.observe.trace import tracer as _tracer
from veles_tpu.network_common import (
    ProtocolError, ShmChannel, default_secret, machine_id, pack_payload,
    parse_address, read_frame, unpack_payload, write_frame)

__all__ = ["Client"]


class Client(Logger, metaclass=CommandLineArgumentsRegistry):

    @classmethod
    def init_parser(cls, parser):
        parser.add_argument(
            "--async-slave", action="store_true", default=None,
            help="pipeline: request the next job while the previous "
                 "update is in flight")
        parser.add_argument(
            "--reconnect-limit", type=int, default=None,
            help="reconnection attempt budget")
        parser.add_argument(
            "--death-probability", type=float, default=None,
            help="chaos testing: per-job probability of simulated "
                 "sudden death")
        return parser

    @classmethod
    def apply_args(cls, args):
        cfg = {}
        for flag in ("async_slave", "reconnect_limit",
                     "death_probability"):
            value = getattr(args, flag, None)
            if value is not None:
                cfg[flag] = value
        root.common.network.update(cfg)

    def __init__(self, address, workflow, launcher=None, codec=None,
                 async_slave=None, reconnect_limit=None,
                 death_probability=None, secret=None, tracer=None,
                 trace_scope="process", trace_chunk_max=2048):
        super(Client, self).__init__()
        net = root.common.network
        self.host, self.port = parse_address(address,
                                             default_host="127.0.0.1")
        self.workflow = workflow
        self.launcher = launcher
        self.codec = codec if codec is not None else net.get(
            "codec", "none")
        self.async_slave = async_slave if async_slave is not None \
            else net.get("async_slave", False)
        self.reconnect_limit = reconnect_limit \
            if reconnect_limit is not None \
            else net.get("reconnect_limit", 5)
        self.death_probability = death_probability \
            if death_probability is not None \
            else net.get("death_probability", 0.0)
        self.secret = secret if secret is not None else default_secret()
        self.sid = None
        self.jobs_done = 0
        # distributed tracing (docs/observability.md): the master's
        # run-scoped trace id arrives in the handshake ack; bounded
        # chunks of this process's recorded spans ship back with the
        # updates (and at session end) for cluster-scope merging
        self.trace_id = None
        #: estimated master-minus-local clock offset (NTP-style join
        #: handshake; None until a session established one)
        self.clock_offset = None
        self.clock_delay = None
        self.trace_chunks_sent = 0
        self.series_chunks_sent = 0
        self._mid = "%s:%d" % (os.uname().nodename, os.getpid())
        self._trace_tracer = tracer if tracer is not None else _tracer
        # "process": ship every recorded event (one-process-per-role
        # deployments).  "threads": ship only events recorded by THIS
        # client's threads — the in-process two-node tests share one
        # tracer between master and slave and must not cross-ship
        self._trace_scope = trace_scope
        self._trace_chunk_max = int(trace_chunk_max)
        self._trace_tids = set()
        self.reject_reason = None
        self.shm_sends = 0
        #: successful handshakes over this client's lifetime
        self.sessions_established = 0
        #: elasticity state (docs/distributed.md, "Elasticity
        #: contract"): the membership epoch this slave was admitted
        #: at rides the handshake ack; reshard pushes update the
        #: fleet's current epoch, this slave's power-weighted share of
        #: the unserved remainder, and the live fleet size
        self.member_epoch = None
        self.share = None
        self.fleet_size = None
        self.reshards_seen = 0
        #: device-mesh epoch stamped into reshard frames when the
        #: master trains on an elastic mesh (parallel.mesh.MeshManager)
        self.mesh_epoch = None
        self._handshaken = False
        self._session_progress = False
        self._stopping = False
        self._paused = False
        self._pending_update = None
        self._loop = None
        self._shm_in = None         # master -> slave payload channel
        self._shm_out = None        # slave -> master payload channel

    # -- lifecycle ----------------------------------------------------------

    def run(self):
        asyncio.run(self._main())

    def start_background(self):
        thread = threading.Thread(target=self.run, daemon=True)
        thread.start()
        return thread

    def on_workflow_finished(self):
        pass  # per-job workflow completion is normal on a slave

    def stop(self):
        self._stopping = True

    @property
    def paused(self):
        """True while the master has this slave parked."""
        return self._paused

    def pause(self):
        pass  # pausing is master-driven; see Server.pause()

    def resume(self):
        pass

    @property
    def computing_power(self):
        """Reference: 1000/avg-matmul-time (accelerated_units.py:768).
        Estimated once from the benchmark op when available.

        A failed rating falls back to the neutral 1.0 so the handshake
        still completes, but LOUDLY: a silent fallback would skew the
        master's load balancing invisibly (the rating itself already
        refuses to publish a clamped nonsense slope)."""
        try:
            from veles_tpu.ops.benchmark import estimate_computing_power
            return float(estimate_computing_power(size=256, repeats=1))
        except Exception as exc:
            self.warning(
                "computing-power rating failed (%s); reporting "
                "neutral power=1.0 — this slave will be weighted "
                "as baseline by the master's load balancer", exc)
            return 1.0

    # -- asyncio internals ---------------------------------------------------

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        attempts = 0
        while not self._stopping and attempts <= self.reconnect_limit:
            self._handshaken = False
            self._session_progress = False
            try:
                await self._session()
                return
            except ProtocolError as exc:
                if not self._handshaken:
                    # authentication/handshake failure is not
                    # transient: don't retry
                    self.reject_reason = str(exc)
                    self.error("protocol failure: %s", exc)
                    self._stopping = True
                    return
                # mid-session protocol violation (e.g. a corrupted
                # frame rejected by the HMAC check): the address and
                # secret are proven good, treat like a connection loss
                attempts = 1 if self._session_progress else attempts + 1
                self.warning("session protocol failure (%s); "
                             "reconnecting (retry %d/%d)", exc,
                             attempts, self.reconnect_limit)
            except (ConnectionError, OSError) as exc:
                # a session that made real progress (handshake + at
                # least one job) RESETS the budget: it bounds
                # consecutive unproductive attempts, so a long run
                # never exhausts a lifetime allowance on unrelated
                # blips — while a slave that dies on every job (or a
                # flapping master) still runs out
                attempts = 1 if self._session_progress else attempts + 1
                self.warning("connection lost (%s); retry %d/%d", exc,
                             attempts, self.reconnect_limit)
            if attempts > self.reconnect_limit:
                continue  # budget spent: exit now, skip a dead backoff
            # full jitter on the exponential backoff: simultaneously
            # orphaned slaves must not stampede a restarted master
            delay = min(0.2 * 2 ** attempts, 5.0)
            await asyncio.sleep(delay * (0.5 + random.random() / 2))
        if not self._stopping:
            self.error("giving up after %d reconnect attempts", attempts)

    async def _session(self):
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            self._mid = "%s:%d" % (os.uname().nodename, os.getpid())
            self._send(writer, {
                "type": "handshake",
                "checksum": self.workflow.checksum,
                "power": self.computing_power,
                "mid": self._mid,
                "machine": machine_id(),
                "pid": os.getpid()})
            msg, payload = await self._recv(reader)
            # the fleet reshards on EVERY membership change: another
            # slave joining or leaving while our handshake is still in
            # flight can push a reshard frame ahead of our ack (the
            # master registers us before generating our initial data).
            # Absorb them — dying here would turn a concurrent join
            # into a permanent loss of this slave
            while msg.get("type") == "reshard":
                self._apply_reshard(msg)
                msg, payload = await self._recv(reader)
            if msg.get("type") == "reject":
                self.reject_reason = msg.get("reason")
                retry_after = msg.get("retry_after")
                if retry_after is not None and not self._stopping:
                    # TTL quarantine, not a verdict: sleep out the FULL
                    # TTL (capped only against absurd values) so ONE
                    # attempt-budget charge outlives the blacklist —
                    # sleeping less would burn the whole budget on
                    # rejections before a long TTL ever expires
                    self.warning(
                        "master quarantined us (%s); retrying in "
                        "%.1fs", self.reject_reason, retry_after)
                    await asyncio.sleep(
                        min(max(float(retry_after), 0.0), 600.0) + 0.05)
                    raise ConnectionResetError(
                        "temporarily blacklisted by master")
                self.error("master rejected us: %s", self.reject_reason)
                self._stopping = True
                return
            if msg.get("type") == "stop":
                # a (re)join racing the master's shutdown: the
                # handshake is answered with 'stop' instead of an ack
                # — a clean end of the run, not a protocol violation
                self.info("master is stopping; ending session")
                self._stopping = True
                return
            assert msg.get("type") == "handshake_ack"
            self.sid = msg["id"]
            self._handshaken = True
            self.sessions_established += 1
            if "member_epoch" in msg:
                self.member_epoch = msg["member_epoch"]
            self._trace_tids.add(threading.get_ident())
            if msg.get("trace"):
                self.trace_id = msg["trace"]
                if self._trace_tracer.label is None:
                    self._trace_tracer.label = "slave:" + self._mid
            if "shm" in msg:
                try:
                    self._shm_in = ShmChannel.attach(msg["shm"]["m2s"])
                    self._shm_out = ShmChannel.attach(msg["shm"]["s2m"])
                    self.info("shm payload bypass engaged")
                except Exception:
                    self.exception("shm attach failed; staying on socket")
                    self._close_shm()
            initial = unpack_payload(payload, msg.get("codec", "none"))
            if initial:
                await self._in_thread(
                    self.workflow.apply_initial_data_from_master, initial)
            if "epoch" in msg:
                self.info("connected as %s (admitted at epoch %s)",
                          self.sid[:8], msg["epoch"])
            else:
                self.info("connected as %s", self.sid[:8])
            if self.trace_id is not None:
                # capability-gated: only masters that advertise
                # cluster tracing (a trace id in the ack) understand
                # clock_probe frames; older/stub masters are not sent
                # messages they would misparse as job traffic
                await self._clock_sync(reader, writer)
            await self._job_loop(reader, writer)
        finally:
            self._ship_trace_chunk(writer, final=True)
            self._ship_series_chunk(writer, final=True)
            self._close_shm()
            writer.close()

    async def _clock_sync(self, reader, writer, probes=4):
        """NTP-style offset estimate at join time (observe/cluster.py):
        probe the master's wall clock over the live connection, report
        the minimum-delay estimate so the master can offset-correct
        this slave's shipped trace chunks.  Failures only cost the
        estimate, never the session."""
        try:
            samples = []
            for _ in range(probes):
                t0 = time.time()
                self._send(writer, {"type": "clock_probe", "t0": t0})
                for _ in range(8):  # skip interleaved broadcasts
                    msg, _ = await self._recv(reader)
                    mtype = msg.get("type")
                    if mtype == "clock_probe_ack":
                        break
                    if mtype == "pause":
                        self._paused = True
                    elif mtype == "resume":
                        self._paused = False
                    elif mtype == "reshard":
                        self._apply_reshard(msg)
                    elif mtype == "stop":
                        self._stopping = True
                        return
                else:
                    return
                t3 = time.time()
                samples.append((msg.get("t0", t0), msg["t1"],
                                msg["t2"], t3))
            offset, delay = estimate_offset(samples)
            self.clock_offset, self.clock_delay = offset, delay
            self._send(writer, {"type": "clock_report",
                                "offset": offset, "delay": delay})
        except (KeyError, TypeError, ValueError) as exc:
            self.warning("clock sync failed (%s); traces from this "
                         "slave merge uncorrected", exc)

    def _ship_trace_chunk(self, writer, final=False):
        """Ship a bounded chunk of recorded trace events to the master
        (riding along with updates, or the remainder at session end).
        Never lets a telemetry failure touch the job cycle."""
        tracer = self._trace_tracer
        if not tracer.enabled or not self._handshaken:
            return
        try:
            idents = (self._trace_tids
                      if self._trace_scope == "threads" else None)
            # the label names THIS slave explicitly: an in-process
            # two-node setup shares one tracer whose label belongs to
            # the master, and the merged trace must not show two
            # tracks with the master's name
            chunk = tracer.take_chunk(
                max_events=self._trace_chunk_max, idents=idents,
                extra={"trace_id": self.trace_id, "final": final,
                       "label": "slave:" + self._mid})
            if chunk is None:
                return
            # chunks ride INLINE, never shm: the master closes its shm
            # segments at shutdown while late frames are still being
            # read (a chunk referencing a dead segment arrives empty),
            # and keeping telemetry off the two-slot channel preserves
            # its one-payload-in-flight-per-direction invariant
            self._send(writer, {"type": "trace_chunk",
                                "codec": self.codec}, payload=chunk,
                       use_shm=False)
            self.trace_chunks_sent += 1
        except Exception as exc:
            self.debug("trace chunk shipping failed: %s", exc)

    def _ship_series_chunk(self, writer, final=False):
        """Ship new telemetry buckets (observe/timeseries.py) to the
        master over the same inline path as trace chunks.  Gated on
        the same capability signal (a trace id in the ack) so stub or
        older masters never see a frame they would misparse; ticks
        the process ring first so slaves without a Heartbeat still
        bucketize at update cadence.  Never raises into the job
        cycle."""
        if not self._handshaken or self.trace_id is None:
            return
        try:
            from veles_tpu.observe.timeseries import series
            series.maybe_tick()
            if final:
                series.tick()  # flush the partial tail bucket
            chunk = series.take_chunk(label="slave:" + self._mid)
            if chunk is None:
                return
            self._send(writer, {"type": "series_chunk",
                                "codec": self.codec}, payload=chunk,
                       use_shm=False)
            self.series_chunks_sent += 1
        except Exception as exc:
            self.debug("series chunk shipping failed: %s", exc)

    async def _job_loop(self, reader, writer):
        self._send(writer, {"type": "job_request"})
        while not self._stopping:
            msg, payload = await self._recv(reader)
            mtype = msg.get("type")
            if mtype == "stop":
                self.info("master signalled stop after %d jobs",
                          self.jobs_done)
                return
            if mtype == "pause":
                # master parked our outstanding job_request server-side;
                # nothing to do but note it — the next frame wakes us
                self._paused = True
                continue
            if mtype == "resume":
                # the server releases our parked request itself;
                # re-requesting here would double-request
                self._paused = False
                continue
            if mtype == "wait":
                # parked server-side at a sync point; the master
                # releases parked requesters itself (on updates, on
                # resume, on new farm batches).  Re-requesting here
                # would DOUBLE-SERVE: the release path and the poll
                # both hand out jobs, the per-connection backlog grows
                # without bound, and queued updates overrun the
                # two-slot shm channel (measured: stale results
                # surfacing six farm batches late)
                continue
            if mtype == "update_ack":
                continue
            if mtype == "reshard":
                # membership changed somewhere in the fleet: learn the
                # new split (and our admission epoch) without breaking
                # the job cycle
                self._apply_reshard(msg)
                continue
            if mtype != "job":
                continue
            if (self.death_probability > 0 and
                    random.random() < self.death_probability):
                # chaos: simulated sudden death (reference
                # client.py:438-442)
                self.warning("fault injection: dying")
                raise ConnectionResetError("injected death")
            if chaos.plan is not None:
                # deterministic variant: die on exactly the Nth job,
                # BEFORE running it — the master must requeue it and
                # this client (re-handshaken) must replay it
                fault = chaos.plan.fire("client.job")
                if fault is not None and fault.action == "die":
                    self.warning("fault injection: dying on job %d",
                                 self.jobs_done + 1)
                    raise ConnectionResetError("injected death (chaos)")
                # the REAL preemption: SIGKILL this process, the
                # closest in-tree stand-in for a preemptible chip
                # being reclaimed (no atexit, no finally blocks, no
                # goodbye frame).  Subprocess soaks arm this; the
                # in-process variant above covers the same master-side
                # requeue path without taking the test runner with it
                fault = chaos.plan.fire("slave.preempt")
                if fault is not None and fault.action == "kill":
                    self.warning(
                        "fault injection: preempting (SIGKILL self, "
                        "pid %d) on job %d", os.getpid(),
                        self.jobs_done + 1)
                    os.kill(os.getpid(), signal.SIGKILL)
            job8 = str(msg.get("job_id") or "")[:8]
            _tracer.instant("proto.job_in", cat="proto", job=job8,
                            trace=str(self.trace_id or "")[:8])
            data = unpack_payload(payload, msg.get("codec", "none"))
            if self.async_slave:
                # pipeline: ask for the next job before running this one
                self._send(writer, {"type": "job_request"})
            # the slave-side span a merged cluster trace hangs between
            # the master's proto.job_out and proto.update_in instants
            with _tracer.span("slave.job", cat="proto", job=job8,
                              trace=str(self.trace_id or "")[:8]):
                update = await self._run_job(data)
            self.jobs_done += 1
            self._session_progress = True
            if chaos.plan is not None:
                # poisoned-update injection (docs/health.md): ship a
                # structurally-valid update whose float payloads are
                # all NaN — the master's finiteness quarantine must
                # catch it BEFORE apply_data_from_slave
                fault = chaos.plan.fire("net.update")
                if fault is not None and fault.action == "nan":
                    self.warning("fault injection: poisoning update "
                                 "payload with non-finite values")
                    update = chaos.poison_tree(
                        update, float("nan") if fault.param is None
                        else fault.param)
            self._send(writer, {
                "type": "update", "job_id": msg.get("job_id"),
                "codec": self.codec}, payload=update)
            _registry.counter("client.jobs_done").inc()
            _tracer.instant("proto.update_out", cat="proto", job=job8,
                            trace=str(self.trace_id or "")[:8])
            # trace + telemetry chunks ride back WITH the update
            # cadence: bounded, so a chatty tracer never starves the
            # data plane
            self._ship_trace_chunk(writer)
            self._ship_series_chunk(writer)
            if not self.async_slave:
                self._send(writer, {"type": "job_request"})

    def _apply_reshard(self, msg):
        """A membership change repartitioned the epoch's unserved
        remainder (docs/distributed.md, "Elasticity contract"): record
        the fleet's new membership epoch and this slave's power-
        weighted share, and forward both to the workflow's
        ``apply_reshard`` hook when it defines one (the loader records
        them as its window hint).  The share itself is advisory — the
        master still serves minibatches job by job, so a stale share
        can never corrupt the sample accounting — but a FAILED hook is
        not: a slave whose loader could not adopt the new window is
        operating on stale elasticity state, so it severs and rejoins
        at the fresh epoch instead of limping along."""
        self.member_epoch = msg.get("epoch", self.member_epoch)
        self.share = msg.get("share")
        self.fleet_size = msg.get("fleet")
        self.mesh_epoch = msg.get("mesh_epoch", self.mesh_epoch)
        self.reshards_seen += 1
        _registry.gauge("elastic.membership_epoch").set(
            self.member_epoch or 0)
        self.info("resharded: membership epoch %s, fleet of %s, our "
                  "share %s", self.member_epoch, self.fleet_size,
                  "?" if self.share is None else self.share)
        hook = getattr(self.workflow, "apply_reshard", None)
        if hook is not None:
            try:
                hook({"epoch": self.member_epoch, "share": self.share,
                      "fleet": self.fleet_size,
                      "mesh_epoch": self.mesh_epoch,
                      "remaining": msg.get("remaining")})
            except Exception:
                self.exception("apply_reshard hook failed; severing to "
                               "rejoin at membership epoch %s",
                               self.member_epoch)
                _registry.counter("elastic.reshard_failures").inc()
                raise ConnectionResetError(
                    "apply_reshard hook failed; rejoining at a fresh "
                    "epoch")

    async def _run_job(self, data):
        result = {}

        def callback(update):
            result["update"] = update

        def invoke():
            # remember which executor threads run OUR jobs: with
            # trace_scope="threads" only their spans ship in chunks
            self._trace_tids.add(threading.get_ident())
            self.workflow.do_job(data, self._pending_update, callback)

        await self._in_thread(invoke)
        self._pending_update = None
        return result.get("update")

    # -- helpers -------------------------------------------------------------

    _NO_PAYLOAD = object()

    def _send(self, writer, msg, payload=_NO_PAYLOAD, use_shm=True):
        if payload is not Client._NO_PAYLOAD:
            raw = pack_payload(payload, self.codec)
            if use_shm and self._shm_out is not None:
                desc = self._shm_out.write(raw)
                if desc is not None:
                    msg = dict(msg, shm=list(desc))
                    self.shm_sends += 1
                    raw = b""
        else:
            raw = b""
        write_frame(writer, msg, raw, self.secret, peer="slave")

    async def _recv(self, reader):
        try:
            msg, payload = await read_frame(reader, self.secret,
                                            peer="slave")
        except asyncio.IncompleteReadError:
            raise ConnectionResetError("EOF from master")
        if self._shm_in is not None and "shm" in msg:
            off, length = msg["shm"]
            payload = self._shm_in.read(off, length)
        return msg, payload

    def _close_shm(self):
        for chan in (self._shm_in, self._shm_out):
            if chan is not None:
                chan.close()
        self._shm_in = self._shm_out = None

    async def _in_thread(self, fn, *args):
        return await self._loop.run_in_executor(None, fn, *args)
