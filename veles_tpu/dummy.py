"""Test stand-ins (reference: veles/dummy.py:46,101,122)."""

from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow

__all__ = ["DummyLauncher", "DummyWorkflow", "DummyUnit"]


class DummyLauncher(object):
    """Minimal launcher substitute so any unit/workflow runs standalone."""

    workflow_mode = "standalone"

    def __init__(self, **kwargs):
        self._workflows = []
        self.stopped = False
        self.interactive = False

    def add_ref(self, workflow):
        self._workflows.append(workflow)

    def del_ref(self, workflow):
        if workflow in self._workflows:
            self._workflows.remove(workflow)

    def on_workflow_finished(self):
        self.stopped = True

    @property
    def workflow(self):
        return self._workflows[0] if self._workflows else None


class DummyWorkflow(Workflow):
    """Workflow auto-owning its own DummyLauncher."""

    def __init__(self, **kwargs):
        super(DummyWorkflow, self).__init__(DummyLauncher(), **kwargs)


class DummyUnit(Unit):
    """Unit whose attributes are set freely from kwargs."""

    def __init__(self, workflow=None, **kwargs):
        attrs = dict(kwargs)
        super(DummyUnit, self).__init__(
            workflow if workflow is not None else DummyWorkflow())
        for key, value in attrs.items():
            setattr(self, key, value)

    def initialize(self, **kwargs):
        self._is_initialized_ = True
        return True

    def run(self):
        pass
