"""Shared wire protocol for the job-farming control plane.

TPU-native counterpart of reference veles/network_common.py + the
txzmq streaming-pickle framing (txzmq/connection.py:140).  Design
difference, documented: the reference split a JSON-line TCP control
plane from a ZeroMQ pickled-tensor data plane (with posix-shm bypass)
because slave jobs carried whole minibatches and weight matrices between
GPU hosts.  On TPU pods tensor traffic rides ICI inside compiled steps
(veles_tpu.parallel), so this plane only carries job descriptors and
small deltas.

Framing: length-prefixed binary frames, ``!IIB`` (header_len,
payload_len, mac_len) + JSON header + raw pickled payload + optional
HMAC-SHA256 over header||payload.  No base64 inflation; payloads ride
as raw bytes next to a small JSON control header.

Trust boundary: payloads are pickled objects, so a peer that can speak
the protocol can execute code.  Protections, in order: (1) the default
bind address is 127.0.0.1 — reaching other hosts requires an explicit
listen address; (2) when a shared secret is set (``VELES_TPU_SECRET``
env or the ``secret=`` argument on Server/Client), every frame is
authenticated with HMAC-SHA256 and unauthenticated frames are rejected
*before* any unpickling.  Multi-host deployments must set a secret.
"""

import gzip
import hashlib
import hmac
import json
import os
import pickle
import struct
import uuid

__all__ = ["pack_payload", "unpack_payload", "read_frame", "write_frame",
           "parse_address", "new_id", "default_secret", "ProtocolError",
           "encode_payload", "decode_payload"]

_FRAME = struct.Struct("!IIB")
_MAC_LEN = hashlib.sha256().digest_size
# Job descriptors and deltas are small; a 1 GiB ceiling guards against
# hostile length prefixes without constraining real traffic.
_MAX_LEN = 1 << 30


class ProtocolError(Exception):
    pass


def default_secret():
    """Shared secret from the environment, or None (localhost trust)."""
    sec = os.environ.get("VELES_TPU_SECRET")
    return sec.encode() if sec else None


def pack_payload(obj, codec="none"):
    raw = pickle.dumps(obj, protocol=4)
    if codec == "gzip":
        raw = gzip.compress(raw, 1)
    elif codec != "none":
        raise ValueError("unknown codec %r" % codec)
    return raw


def unpack_payload(raw, codec="none"):
    if codec == "gzip":
        raw = gzip.decompress(raw)
    return pickle.loads(raw)


def write_frame(writer, msg, payload=b"", secret=None):
    """Serialize one frame onto an asyncio StreamWriter."""
    header = json.dumps(msg).encode()
    mac = (hmac.new(secret, header + payload, hashlib.sha256).digest()
           if secret else b"")
    writer.write(_FRAME.pack(len(header), len(payload), len(mac)) +
                 header + payload + mac)


async def read_frame(reader, secret=None):
    """Read one frame -> (msg dict, payload bytes).

    When ``secret`` is set the MAC is verified before the header is
    even parsed; a missing or wrong MAC raises ProtocolError.
    """
    prefix = await reader.readexactly(_FRAME.size)
    hlen, plen, mlen = _FRAME.unpack(prefix)
    if hlen > _MAX_LEN or plen > _MAX_LEN or mlen > _MAC_LEN:
        raise ProtocolError("oversized frame (%d/%d/%d)" %
                            (hlen, plen, mlen))
    header = await reader.readexactly(hlen)
    payload = await reader.readexactly(plen) if plen else b""
    mac = await reader.readexactly(mlen) if mlen else b""
    if secret is not None:
        want = hmac.new(secret, header + payload, hashlib.sha256).digest()
        if not hmac.compare_digest(want, mac):
            raise ProtocolError("frame authentication failed")
    return json.loads(header.decode()), payload


def parse_address(address, default_host="127.0.0.1"):
    host, sep, port = address.rpartition(":")
    if not sep:
        raise ValueError("address must be host:port, got %r" % address)
    return (host or default_host), int(port)


def new_id():
    return str(uuid.uuid4())


# -- legacy dict codec (kept for tooling/tests that round-trip payloads) --

def encode_payload(obj, codec="none"):
    import base64
    return {"codec": codec,
            "b64": base64.b64encode(pack_payload(obj, codec)).decode()}


def decode_payload(blob):
    import base64
    if blob is None:
        return None
    return unpack_payload(base64.b64decode(blob["b64"]), blob["codec"])
