"""Shared wire protocol for the job-farming control plane.

TPU-native counterpart of reference veles/network_common.py + the
txzmq streaming-pickle framing (txzmq/connection.py:140).  Design
difference, documented: the reference split a JSON-line TCP control
plane from a ZeroMQ pickled-tensor data plane (with posix-shm bypass)
because slave jobs carried whole minibatches and weight matrices between
GPU hosts.  On TPU pods tensor traffic rides ICI inside compiled steps
(veles_tpu.parallel), so this plane only carries job descriptors and
small deltas.

Framing: length-prefixed binary frames, ``!IIB`` (header_len,
payload_len, mac_len) + JSON header + raw pickled payload + optional
HMAC-SHA256 over header||payload.  No base64 inflation; payloads ride
as raw bytes next to a small JSON control header.  Payload codecs:
none/gzip/bz2/xz (+snappy when installed) — reference parity with
txzmq/connection.py:140-143.  Same-host peers bypass the socket for
payload bytes entirely via ``ShmChannel`` shared memory (the
reference's SharedIO, txzmq/sharedio.py:44).

Trust boundary: payloads are pickled objects, so a peer that can speak
the protocol can execute code.  Protections, in order: (1) the default
bind address is 127.0.0.1 — reaching other hosts requires an explicit
listen address; (2) when a shared secret is set (``VELES_TPU_SECRET``
env or the ``secret=`` argument on Server/Client), every frame is
authenticated with HMAC-SHA256 and unauthenticated frames are rejected
*before* any unpickling.  Multi-host deployments must set a secret.
"""

import asyncio
import bz2
import gzip
import hashlib
import hmac
import json
import lzma
import os
import pickle
import struct
import threading
import time
import uuid

from veles_tpu import chaos

try:  # optional, reference codec parity (txzmq/connection.py:140)
    import snappy as _snappy
except ImportError:
    _snappy = None

__all__ = ["pack_payload", "unpack_payload", "read_frame", "write_frame",
           "pack_frame", "read_frame_sync", "get_codec",
           "parse_address", "new_id", "default_secret", "ProtocolError",
           "encode_payload", "decode_payload", "available_codecs",
           "ShmChannel", "machine_id"]

_FRAME = struct.Struct("!IIB")
_MAC_LEN = hashlib.sha256().digest_size
# Job descriptors and deltas are small; a 1 GiB ceiling guards against
# hostile length prefixes without constraining real traffic.
_MAX_LEN = 1 << 30


class ProtocolError(Exception):
    pass


def default_secret():
    """Shared secret from the environment, or None (localhost trust)."""
    sec = os.environ.get("VELES_TPU_SECRET")
    return sec.encode() if sec else None


# Codec set mirrors the reference's streaming-pickle framing options
# none/gzip/snappy/xz (txzmq/connection.py:140-143); bz2 added for
# snapshot parity, snappy gated on availability.
_COMPRESS = {
    "none": (lambda raw: raw, lambda raw: raw),
    "gzip": (lambda raw: gzip.compress(raw, 1), gzip.decompress),
    "bz2": (lambda raw: bz2.compress(raw, 1), bz2.decompress),
    "xz": (lambda raw: lzma.compress(raw, preset=1), lzma.decompress),
}
if _snappy is not None:
    _COMPRESS["snappy"] = (_snappy.compress, _snappy.decompress)


def available_codecs():
    return tuple(_COMPRESS)


def get_codec(name):
    """``(compress, decompress)`` pair for a codec name.

    Public so payload layers that are NOT pickle — the serve binary
    transport's tensor codec (veles_tpu/serve/transport.py) — can ride
    the same compression table without touching pack/unpack_payload's
    pickling."""
    try:
        return _COMPRESS[name]
    except KeyError:
        raise ValueError("unknown codec %r" % name)


def pack_payload(obj, codec="none"):
    try:
        compress = _COMPRESS[codec][0]
    except KeyError:
        raise ValueError("unknown codec %r" % codec)
    return compress(pickle.dumps(obj, protocol=4))


def unpack_payload(raw, codec="none"):
    try:
        decompress = _COMPRESS[codec][1]
    except KeyError:
        raise ValueError("unknown codec %r" % codec)
    return pickle.loads(decompress(raw))


def _flip_byte(blob):
    """Invert one byte (chaos 'corrupt' action).  The MAC/manifest was
    computed over the clean bytes, so verification catches this."""
    buf = bytearray(blob)
    buf[len(buf) // 2] ^= 0xFF
    return bytes(buf)


def _fire_net_fault(point, peer):
    """Chaos lookup for a frame op: the generic point first, then the
    peer-scoped one (``net.recv:slave``) — peer scoping keeps the Nth-
    hit triggers deterministic when master and slave share one
    in-process plan."""
    fault = chaos.plan.fire(point)
    if fault is None and peer:
        fault = chaos.plan.fire("%s:%s" % (point, peer))
    return fault


def pack_frame(msg, payload=b"", secret=None):
    """Serialize one frame to bytes: ``!IIB`` prefix + JSON header +
    raw payload + optional HMAC-SHA256 over header||payload.  The one
    encoder behind both the asyncio writer (:func:`write_frame`) and
    synchronous socket senders (the serve binary transport)."""
    header = json.dumps(msg).encode()
    mac = (hmac.new(secret, header + payload, hashlib.sha256).digest()
           if secret else b"")
    return _FRAME.pack(len(header), len(payload), len(mac)) + \
        header + payload + mac


def _check_lengths(hlen, plen, mlen, max_len=None):
    ceiling = _MAX_LEN if max_len is None else int(max_len)
    if hlen > ceiling or plen > ceiling or mlen > _MAC_LEN:
        raise ProtocolError("oversized frame (%d/%d/%d)" %
                            (hlen, plen, mlen))


def _finish_frame(header, payload, mac, secret):
    """Shared tail of the async and sync frame readers: MAC
    verification BEFORE the header is even parsed, then the JSON
    decode with protocol-violation (not crash) semantics."""
    if secret is not None:
        want = hmac.new(secret, header + payload, hashlib.sha256).digest()
        if not hmac.compare_digest(want, mac):
            raise ProtocolError("frame authentication failed")
    try:
        return json.loads(header.decode()), payload
    except (UnicodeDecodeError, ValueError) as exc:
        # a mangled header is a protocol violation, not a crash: the
        # caller's ProtocolError handling (drop + reconnect) applies
        raise ProtocolError("malformed frame header (%s)" % exc)


def write_frame(writer, msg, payload=b"", secret=None, peer=None):
    """Serialize one frame onto an asyncio StreamWriter."""
    frame = pack_frame(msg, payload, secret)
    if chaos.plan is not None:
        fault = _fire_net_fault("net.send", peer)
        if fault is not None:
            frame = _apply_send_fault(fault, frame, writer)
            if frame is None:
                return
    writer.write(frame)


def _apply_send_fault(fault, frame, writer):
    """Wire-level faults on an outgoing frame (chaos 'net.send')."""
    if fault.action == "drop":
        return None
    if fault.action == "delay":
        # deliberately BLOCKS the sender's event loop: net.send=delay
        # models a stalled sender process (GC pause, CPU starvation),
        # which freezes everything that peer multiplexes.  Per-frame
        # network latency belongs on net.recv, whose delay awaits.
        time.sleep(fault.param or 0.05)
        return frame
    if fault.action == "truncate":
        # partial frame then close: the peer's readexactly raises
        # IncompleteReadError -> clean connection-loss recovery path
        writer.write(frame[:max(1, len(frame) * 2 // 3)])
        writer.close()
        return None
    if fault.action == "corrupt":
        return _flip_byte(frame)
    return frame


async def read_frame(reader, secret=None, peer=None, max_len=None):
    """Read one frame -> (msg dict, payload bytes).

    When ``secret`` is set the MAC is verified before the header is
    even parsed; a missing or wrong MAC raises ProtocolError.  With a
    shared secret this also rejects chaos-corrupted frames BEFORE any
    unpickling; without one, only header corruption is caught here.
    ``max_len`` tightens the default 1 GiB length ceiling — a hostile
    length prefix must fail HERE, not park the connection buffering
    bytes that never come (the serve transport bounds frames to what a
    tensor can legitimately need).
    """
    prefix = await reader.readexactly(_FRAME.size)
    hlen, plen, mlen = _FRAME.unpack(prefix)
    _check_lengths(hlen, plen, mlen, max_len)
    header = await reader.readexactly(hlen)
    payload = await reader.readexactly(plen) if plen else b""
    mac = await reader.readexactly(mlen) if mlen else b""
    if chaos.plan is not None:
        fault = _fire_net_fault("net.recv", peer)
        if fault is not None:
            if fault.action == "delay":
                await asyncio.sleep(fault.param or 0.05)
            elif fault.action == "corrupt":
                if payload:
                    payload = _flip_byte(payload)
                else:
                    header = _flip_byte(header)
    return _finish_frame(header, payload, mac, secret)


def read_frame_sync(recv_exactly, secret=None, max_len=None):
    """Synchronous :func:`read_frame` twin for blocking-socket clients
    (the serve binary transport's closed-loop client keeps one thread
    per connection, where an event loop would be pure overhead).

    ``recv_exactly(n)`` must return exactly ``n`` bytes or raise.  Same
    length bounds (``max_len`` tightening included), MAC-before-parse
    order and ProtocolError semantics as the asyncio reader; no chaos
    hooks — client-side fault injection rides the server's async half.
    """
    hlen, plen, mlen = _FRAME.unpack(recv_exactly(_FRAME.size))
    _check_lengths(hlen, plen, mlen, max_len)
    header = recv_exactly(hlen)
    payload = recv_exactly(plen) if plen else b""
    mac = recv_exactly(mlen) if mlen else b""
    return _finish_frame(header, payload, mac, secret)


def parse_address(address, default_host="127.0.0.1"):
    host, sep, port = address.rpartition(":")
    if not sep:
        raise ValueError("address must be host:port, got %r" % address)
    return (host or default_host), int(port)


def new_id():
    return str(uuid.uuid4())


def machine_id():
    """Stable per-host identifier used for same-machine detection
    (the reference's ``mid``, network_common.py)."""
    return "%x-%s" % (uuid.getnode(), os.uname().nodename)


class ShmChannel(object):
    """One-directional shared-memory payload channel.

    TPU-native counterpart of the reference's ``SharedIO`` posix-ipc
    ring (txzmq/sharedio.py:44-105; engaged for same-machine
    master<->slave at server.py:144-167, client.py:140-159): when the
    handshake detects both peers on one host, payload bytes ride a
    shared-memory segment instead of the socket, and the frame header
    carries only ``{"shm": [offset, length]}``.

    The control protocol is strict request-reply per connection, so at
    most one payload per direction is unconsumed at any time; a two-slot
    alternating layout removes even that reasoning burden (the writer
    never touches the slot the reader may still be consuming).

    Trust note: shm payloads are not covered by the frame HMAC — the
    segment is same-host, named by a random UUID, and created with
    owner-only permissions, so the OS user boundary is the protection.
    """

    #: names created by THIS process (attach must not unregister them)
    _local_creations = set()
    #: every not-yet-closed channel in this process — the test suite's
    #: leak detector fails any test that abandons a segment (an
    #: unlinked-but-open segment holds memory; an un-unlinked created
    #: one leaks a /dev/shm file past process death).  Channels open
    #: and close on daemon network threads, so the registry is locked.
    _open_channels = set()
    _open_lock = threading.Lock()

    def __init__(self, shm, created):
        self._shm = shm
        self._created = created
        self._slot = 0
        self.name = shm.name
        self.slot_size = shm.size // 2
        if created:
            ShmChannel._local_creations.add(shm.name)
        with ShmChannel._open_lock:
            ShmChannel._open_channels.add(self)

    @classmethod
    def open_channels(cls):
        """Race-free snapshot of the not-yet-closed channels."""
        with cls._open_lock:
            return set(cls._open_channels)

    @classmethod
    def create(cls, size):
        from multiprocessing import shared_memory
        shm = shared_memory.SharedMemory(
            create=True, size=max(int(size), 2), name=None)
        return cls(shm, created=True)

    @classmethod
    def attach(cls, name):
        from multiprocessing import shared_memory
        shm = shared_memory.SharedMemory(name=name)
        if name not in cls._local_creations:
            # The CREATOR owns the segment's lifetime (it unlinks in
            # close()); Python auto-registers every open with the
            # resource tracker, which then warns about the creator's
            # segment at CROSS-process attacher exit.  A same-process
            # attach (tests) shares the creator's tracker entry and
            # must leave it alone.
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:
                pass
        return cls(shm, created=False)

    def write(self, raw):
        """Write bytes into the next slot -> (offset, length), or None
        when the payload does not fit (caller falls back to inline)."""
        if len(raw) > self.slot_size:
            return None
        offset = self._slot * self.slot_size
        self._slot ^= 1
        self._shm.buf[offset:offset + len(raw)] = raw
        return offset, len(raw)

    def read(self, offset, length):
        if offset < 0 or length < 0 or offset + length > self._shm.size:
            raise ProtocolError("shm descriptor out of bounds")
        return bytes(self._shm.buf[offset:offset + length])

    def close(self):
        with ShmChannel._open_lock:
            ShmChannel._open_channels.discard(self)
        try:
            self._shm.close()
            if self._created:
                self._shm.unlink()
                ShmChannel._local_creations.discard(self.name)
        except Exception:
            pass


# -- legacy dict codec (kept for tooling/tests that round-trip payloads) --

def encode_payload(obj, codec="none"):
    import base64
    return {"codec": codec,
            "b64": base64.b64encode(pack_payload(obj, codec)).decode()}


def decode_payload(blob):
    import base64
    if blob is None:
        return None
    return unpack_payload(base64.b64decode(blob["b64"]), blob["codec"])
