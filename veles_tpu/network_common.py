"""Shared wire protocol for the job-farming control plane.

TPU-native counterpart of reference veles/network_common.py + the
txzmq streaming-pickle framing (txzmq/connection.py:140).  Design
difference, documented: the reference split a JSON-line TCP control
plane from a ZeroMQ pickled-tensor data plane (with posix-shm bypass)
because slave jobs carried whole minibatches and weight matrices between
GPU hosts.  On TPU pods tensor traffic rides ICI inside compiled steps
(veles_tpu.parallel), so this plane only carries job descriptors and
small deltas: one newline-delimited JSON stream with pickled payloads
(codec none | gzip, negotiated like the reference's
none/gzip/snappy/xz set) is sufficient and keeps the elastic semantics
testable in-process.
"""

import base64
import gzip
import pickle
import uuid

__all__ = ["encode_payload", "decode_payload", "parse_address", "new_id"]


def encode_payload(obj, codec="none"):
    raw = pickle.dumps(obj, protocol=4)
    if codec == "gzip":
        raw = gzip.compress(raw, 1)
    elif codec != "none":
        raise ValueError("unknown codec %r" % codec)
    return {"codec": codec,
            "b64": base64.b64encode(raw).decode("ascii")}


def decode_payload(blob):
    if blob is None:
        return None
    raw = base64.b64decode(blob["b64"])
    if blob["codec"] == "gzip":
        raw = gzip.decompress(raw)
    return pickle.loads(raw)


def parse_address(address, default_host="0.0.0.0"):
    host, sep, port = address.rpartition(":")
    if not sep:
        raise ValueError("address must be host:port, got %r" % address)
    return (host or default_host), int(port)


def new_id():
    return str(uuid.uuid4())
